#!/bin/bash
# Round-5 stage 2: after the main chain (tpu_capture_r5.sh) finishes,
# capture the north-star ACCURACY-vs-WALL-CLOCK curves on the chip
# (VERDICT r4 item #7 — BASELINE.json's metric is wall-clock to target
# accuracy, and no on-chip curve exists; at round-2 throughput the
# 100-round fedavg + scaffold curves are ~minutes each). Probes once
# with short patience: if the relay died again after the main capture,
# the CPU-branch curves stand.
#     nohup bash scripts/tpu_capture_r5b.sh > /tmp/tpu_capture_r5b.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
trap 'touch "$R5B_DONE"' EXIT

# done-sentinel, not pgrep: a pgrep poll reads "r5 not started yet"
# as "finished" and would probe concurrently with it (launch-order
# race — the relay is single-session)
wait_for_done "$R5_DONE"
echo "[tpu_capture_r5b] main chain done — probing"

BENCH_PROBE_TRIES=3 python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture_r5b] relay dead; on-chip curves not captured"
    exit 1
fi

echo "[tpu_capture_r5b] relay alive — capturing curves"
FAILED=0
run_curve() {
    local out="$1"; shift
    echo "=== $* -> $out ==="
    BENCH_PROBE_TRIES=2 "$@" > "$out.tmp" && mv "$out.tmp" "$out"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

run_curve NORTHSTAR_CURVE_FEDAVG.json \
    python scripts/northstar_synthetic.py --rounds 100
run_curve NORTHSTAR_CURVE_SCAFFOLD.json \
    python scripts/northstar_synthetic.py --rounds 100 --algorithm scaffold
echo "[tpu_capture_r5b] done (failed=$FAILED)"
exit $FAILED
