"""Diagnose the round-5 on-chip zoo failure: seqpar/ring err 0.078 vs
the dense oracle on a 1-device TPU mesh (TPU_ZOO.json seqpar_1chip).

Hypothesis: TPU f32 matmuls default to bf16-precision MXU passes
(jax default_matmul_precision), so the sharded ring program and the
dense oracle — different contraction orders — diverge at bf16 rounding
scale. On CPU the same check passes at 1e-3 because CPU matmuls are
true f32. This probe runs _run_sequence_parallel(1) under the default
precision and under 'highest' (f32-accurate MXU passes): if 'highest'
collapses the error by orders of magnitude, the divergence is MXU
rounding, not a program bug.

Writes SEQPAR_TPU_PROBE.json.
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import jax

    from __graft_entry__ import _run_sequence_parallel

    dev = jax.devices()[0]
    results = {"platform": str(dev), "cases": {}}

    for prec in ("default", "highest"):
        try:
            # tol=inf: we want the measured error, not the assert
            worst = _run_sequence_parallel(
                1, label=f"probe[{prec}]", tol=float("inf"),
                matmul_precision=prec)
            results["cases"][prec] = {"worst_err": worst}
            print(f"precision={prec}: worst err {worst:.3e}")
        except Exception as e:  # pragma: no cover - diagnostic
            results["cases"][prec] = {"error": str(e)[:300]}
            print(f"precision={prec}: FAIL {e}")

    d = results["cases"].get("default", {}).get("worst_err")
    h = results["cases"].get("highest", {}).get("worst_err")
    if d is not None and h is not None:
        results["ratio_default_over_highest"] = (
            d / h if h > 0 else float("inf"))
        results["finding"] = (
            "MXU bf16-pass rounding artifact (highest-precision error "
            "is orders of magnitude smaller)" if h < d / 30 else
            "NOT explained by matmul precision alone — investigate "
            "the ring program")
        print(results["finding"])

    with open(os.path.join(REPO, "SEQPAR_TPU_PROBE.json"), "w") as f:
        json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
