"""Async-commit-plane A/B: `sync_mode='sync'` vs `'async'` under the
straggler-heavy chaos schedule (scripts/chaos_suite.py's preset).

The claim under test (ISSUE 6): a synchronous round is gated on its
SLOWEST online client — under a long-tail delay distribution the round
clock is the tail — while the FedBuff-style buffer commits on the
FASTEST m arrivals, so commit cadence is tail-independent. Both planes
share one deterministic delay model (threefry draws off the experiment
key, async_plane/scheduler.py), so the A/B compares:

* **virtual commit cadence** — the event clock: per sync round, the
  MAX of its k dispatch delays (`simulate_sync_round_times`); per
  async commit, `AsyncSchedule.commit_times` deltas. The headline is
  aggregated client updates per virtual time unit, which normalizes
  for the buffer committing m <= k updates at a time;
* **wall-clock per commit** (fetch-synced, bench_timing.sync) — the
  device cost of the commit program vs the round program;
* **accuracy parity** at an equal client-update budget (R sync rounds
  of k updates == R*k/m async commits of m), against the chaos-suite
  <=5-point bar;
* **trace-once** — the commit program must not retrace mid-run
  (RecompilationSentinel), plus the scheduler's straggler/ring-clamp
  counters.

A third ``async_trace`` leg reruns the async side under the
deployment-realism availability model (robustness/availability.py:
device-class delays + diurnal dropouts) at the same commit budget —
the default sync/async legs keep the legacy delay chain bitwise.

Writes ASYNC_AB.json (ASYNC_AB_PATH overrides, for the test smoke).
ASYNC_BENCH_SMOKE=1 shrinks the workload for CPU CI.

Run:  python scripts/async_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from fedtorch_tpu.utils import enable_compile_cache, \
    honor_platform_env  # noqa: E402

honor_platform_env()  # the site hook may pin jax_platforms to the proxy
enable_compile_cache()

from bench_timing import sync  # noqa: E402
from chaos_suite import straggler_heavy_fault  # noqa: E402
from fedtorch_tpu.algorithms import make_algorithm  # noqa: E402
from fedtorch_tpu.async_plane import AsyncFederatedTrainer  # noqa: E402
from fedtorch_tpu.async_plane.scheduler import (  # noqa: E402
    simulate_sync_round_times,
)
from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
    ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data  # noqa: E402
from fedtorch_tpu.models import define_model  # noqa: E402
from fedtorch_tpu.parallel import FederatedTrainer, evaluate  # noqa: E402
from fedtorch_tpu.utils.tracing import RecompilationSentinel  # noqa: E402

SMOKE = os.environ.get("ASYNC_BENCH_SMOKE") == "1"
NUM_CLIENTS = 12 if SMOKE else 100
BATCH = 8 if SMOKE else 50
K = 2 if SMOKE else 10
SYNC_ROUNDS = 4 if SMOKE else 40
ONLINE = 0.5 if SMOKE else 0.1
ARCH = "logistic_regression" if SMOKE else "mlp"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(sync_mode: str, num_comms: int, fault_extra: dict = None):
    fault_kwargs = dict(straggler_heavy_fault(), **(fault_extra or {}))
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=30,
                        batch_size=BATCH, synthetic_alpha=0.5,
                        synthetic_beta=0.5),
        federated=FederatedConfig(
            federated=True, num_clients=NUM_CLIENTS,
            num_comms=num_comms, online_client_rate=ONLINE,
            algorithm="fedavg", sync_type="local_step",
            sync_mode=sync_mode),
        model=ModelConfig(arch=ARCH, mlp_num_layers=2,
                          mlp_hidden_size=64),
        optim=OptimConfig(lr=0.5, weight_decay=0.0),
        train=TrainConfig(local_step=K),
        fault=FaultConfig(**fault_kwargs),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=BATCH)
    cls = AsyncFederatedTrainer if sync_mode == "async" \
        else FederatedTrainer
    tr = cls(cfg, model, make_algorithm(cfg), data.train)
    return cfg, tr, data


def timed(tr, steps: int):
    """Warmup one step (the expected trace), then time the rest under
    the sentinel."""
    server, clients = tr.init_state(jax.random.key(0))
    server, clients, _ = tr.run_round(server, clients)
    sync(server.params)
    with RecompilationSentinel() as sentinel:
        t0 = time.perf_counter()
        stale_dev = []
        for _ in range(steps - 1):
            server, clients, m = tr.run_round(server, clients)
            # defer the fetch: a per-commit float() would serialize a
            # blocking transfer into the timed window (lint FTL001)
            stale_dev.append(m.staleness_mean)
        sync(server.params)
        dt = (time.perf_counter() - t0) / max(steps - 1, 1)
    retraces = sum(sentinel.counts.values())
    stale = [float(x) for x in jax.device_get(stale_dev)]
    return server, dt, retraces, sum(stale) / max(len(stale), 1)


def main():
    devs = jax.devices()
    log(f"devices: {len(devs)} x {devs[0].platform}")
    k = max(int(ONLINE * NUM_CLIENTS), 1)
    m = max(k // 2, 1)  # the auto buffer size
    commits = SYNC_ROUNDS * k // m  # equal client-update budget
    out = {
        "platform": f"{len(devs)} x {devs[0].device_kind}",
        "config": {"clients": NUM_CLIENTS, "k_online": k,
                   "buffer_m": m, "batch": BATCH, "K": K, "arch": ARCH,
                   "sync_rounds": SYNC_ROUNDS, "async_commits": commits,
                   "fault": straggler_heavy_fault(), "smoke": SMOKE},
        "modes": {},
    }

    # -- sync leg --------------------------------------------------------
    cfg, tr, data = build("sync", SYNC_ROUNDS)
    server, dt, retraces, _ = timed(tr, SYNC_ROUNDS)
    acc = float(evaluate(tr.model, server.params, data.test_x,
                         data.test_y).top1)
    # the SAME key the async leg's scheduler draws its delays from:
    # server.rng (init_state's split of key(0), never advanced by the
    # round program) — so the two legs share one delay model and the
    # comparison is PAIRED per dispatch id, not two unrelated streams
    key_data = np.asarray(
        jax.device_get(jax.random.key_data(server.rng)))
    key_impl = jax.random.key_impl(server.rng)
    flt = straggler_heavy_fault()
    round_times = simulate_sync_round_times(
        key_data, key_impl, rounds=SYNC_ROUNDS, k_online=k,
        straggler_rate=flt["straggler_rate"],
        straggler_step_frac=flt["straggler_step_frac"])
    vtotal = float(np.sum(round_times))
    out["modes"]["sync"] = {
        "top1": round(acc, 4),
        "ms_per_commit_wall": round(dt * 1e3, 2),
        "retraces_during_timed": retraces,
        "virtual_time_total": round(vtotal, 3),
        "virtual_mean_step_interval": round(vtotal / SYNC_ROUNDS, 3),
        "commits_per_virtual_unit": round(SYNC_ROUNDS / vtotal, 4),
        "client_updates_per_virtual_unit": round(
            SYNC_ROUNDS * k / vtotal, 4),
    }
    log(f"sync : top1 {acc:.4f}  {dt*1e3:.1f} ms/round  "
        f"virtual {vtotal/SYNC_ROUNDS:.2f}/round (max of {k} delays)")

    # -- async leg -------------------------------------------------------
    cfg, tr, data = build("async", commits)
    server, dt_a, retraces_a, stale = timed(tr, commits)
    acc_a = float(evaluate(tr.model, server.params, data.test_x,
                           data.test_y).top1)
    ct = np.asarray(tr._sched.commit_times)
    stats = tr.schedule_stats
    vtotal_a = float(ct[-1])
    out["modes"]["async"] = {
        "top1": round(acc_a, 4),
        "ms_per_commit_wall": round(dt_a * 1e3, 2),
        "retraces_during_timed": retraces_a,
        "virtual_time_total": round(vtotal_a, 3),
        "virtual_mean_step_interval": round(vtotal_a / commits, 3),
        "commits_per_virtual_unit": round(commits / vtotal_a, 4),
        "client_updates_per_virtual_unit": round(
            commits * m / vtotal_a, 4),
        "staleness_mean": round(stale, 3),
        "scheduler": {"dispatches": stats.dispatches,
                      "stragglers": stats.stragglers,
                      "ring_clamped": stats.staleness_clamped,
                      "dropouts": stats.dropouts},
    }
    tr.invalidate_stream()
    log(f"async: top1 {acc_a:.4f}  {dt_a*1e3:.1f} ms/commit  "
        f"virtual {vtotal_a/commits:.2f}/commit  "
        f"staleness {stale:.2f}")

    # -- async leg, trace availability model -----------------------------
    # same commit budget, but arrivals drawn from the deployment-realism
    # trace (robustness/availability.py): device-class delay multipliers
    # + diurnal mid-round dropouts. The default legs above are untouched
    # (their delay model is the legacy chain, bitwise), so this leg
    # measures what deployment realism costs the commit cadence.
    cfg, tr, data = build("async", commits,
                          fault_extra={"avail_model": "trace",
                                       "avail_dropout_rate": 0.1,
                                       "avail_diurnal_period": 8})
    server, dt_t, retraces_t, stale_t = timed(tr, commits)
    acc_t = float(evaluate(tr.model, server.params, data.test_x,
                           data.test_y).top1)
    ct_t = np.asarray(tr._sched.commit_times)
    stats_t = tr.schedule_stats
    vtotal_t = float(ct_t[-1])
    out["modes"]["async_trace"] = {
        "top1": round(acc_t, 4),
        "ms_per_commit_wall": round(dt_t * 1e3, 2),
        "retraces_during_timed": retraces_t,
        "virtual_time_total": round(vtotal_t, 3),
        "virtual_mean_step_interval": round(vtotal_t / commits, 3),
        "commits_per_virtual_unit": round(commits / vtotal_t, 4),
        "client_updates_per_virtual_unit": round(
            commits * m / vtotal_t, 4),
        "staleness_mean": round(stale_t, 3),
        "scheduler": {"dispatches": stats_t.dispatches,
                      "stragglers": stats_t.stragglers,
                      "ring_clamped": stats_t.staleness_clamped,
                      "dropouts": stats_t.dropouts},
    }
    tr.invalidate_stream()
    log(f"async_trace: top1 {acc_t:.4f}  {dt_t*1e3:.1f} ms/commit  "
        f"virtual {vtotal_t/commits:.2f}/commit  "
        f"dropouts {stats_t.dropouts}")

    # -- the verdict -----------------------------------------------------
    s, a = out["modes"]["sync"], out["modes"]["async"]
    out["commit_rate_speedup_virtual"] = round(
        a["commits_per_virtual_unit"] / s["commits_per_virtual_unit"], 3)
    out["update_rate_speedup_virtual"] = round(
        a["client_updates_per_virtual_unit"]
        / s["client_updates_per_virtual_unit"], 3)
    out["accuracy_gap_points"] = round((acc - acc_a) * 100.0, 2)
    # the bar: async commits are NOT gated on the slowest client — its
    # mean commit interval beats the sync round's straggler-set clock
    out["async_not_tail_gated"] = bool(
        a["virtual_mean_step_interval"] < s["virtual_mean_step_interval"])
    log(f"virtual commit-rate speedup {out['commit_rate_speedup_virtual']}x"
        f", update-rate {out['update_rate_speedup_virtual']}x, "
        f"acc gap {out['accuracy_gap_points']:+.2f}pts")

    path = os.environ.get("ASYNC_AB_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ASYNC_AB.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
