"""Validate + micro-bench the Pallas fused quantize kernel ON THE REAL TPU.

tests/test_pallas.py exercises the kernel bodies in interpret mode on the
CPU CI mesh; this script is the real-lowering counterpart (VMEM limits,
SMEM scalar handling, mosaic codegen), run whenever the TPU relay is
healthy.

Checks (reference semantics anchor: flow_utils.py:169-212 affine scheme):
  1. single-block kernel == XLA path on a spread of sizes/bit-widths
  2. client-grid batch kernel == vmapped XLA path (per-client statistics)
  3. timed fused-vs-XLA on resnet20-shaped payloads (downlink: one tensor
     per param; uplink: [k_online, n] stacked client payloads)

Writes a JSON summary to PALLAS_TPU.json and prints it.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from fedtorch_tpu.ops.pallas.quant_kernel import (
    fused_quantize_dequantize, fused_quantize_dequantize_batch)
from fedtorch_tpu.ops.quantize import quantize_dequantize


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ResNet-20 CIFAR parameter-tensor sizes (conv kernels, norms, fc) — the
# actual downlink payload shapes of the north-star config.
RESNET20_SIZES = (
    [432] +                                   # stem conv 3*3*3*16
    [2304] * 12 + [16, 16] * 13 +             # stage 1: 16ch convs + norms
    [4608] + [9216] * 11 + [32, 32] * 13 +    # stage 2
    [18432] + [36864] * 11 + [64, 64] * 13 +  # stage 3
    [640, 10]                                 # fc
)


# payloads past this element count are "large/bandwidth-bound" for the
# finding summary; below it, timings are launch-bound noise
_BIG_PAYLOAD = 1_000_000


def _timeit(fn, *args, iters=50):
    """Fetch-synced timing (scripts/bench_timing.py): block_until_ready
    can no-op on the relay backend — round 5 block-synced timers read
    24-44us for computations with a ~350us MXU FLOPs floor."""
    from bench_timing import timeit
    return timeit(fn, *args, iters=iters)


def main():
    devs = jax.devices()
    log(f"devices: {devs}")
    on_tpu = devs[0].platform != "cpu"
    if not on_tpu:
        log("WARNING: no TPU — this run does not validate the real lowering")

    results = {"platform": str(devs[0]), "correctness": [], "bench": {}}
    rng = np.random.RandomState(0)

    # --- 1. single-block + tiled correctness, compiled (not interpret) ---
    # n <= 512k takes the single-block kernel (identical reduction order,
    # err ~ulp); larger n takes the two-pass tiled kernel, whose
    # block-sequential stats can flip bin-boundary elements by one bin.
    max_err_bound_ok = True
    for n, bits in [(100, 8), (1000, 8), (1000, 16), (128, 8),
                    (36864, 8), (500_000, 8), (2_000_000, 8),
                    (2_000_000, 16)]:
        x = jnp.asarray(rng.randn(n).astype(np.float32) * 3)
        got = np.asarray(fused_quantize_dequantize(x, bits,
                                                   force_pallas=True))
        want = np.asarray(quantize_dequantize(x, bits))
        # one quantization bin on this payload
        bin_w = (float(x.max()) - float(x.min())) / (2 ** bits - 1)
        err = float(np.abs(got - want).max())
        tol = 0.51 if n <= 512 * 1024 else 1.05
        ok = err < tol * bin_w
        max_err_bound_ok &= ok
        results["correctness"].append(
            {"case": f"single n={n} bits={bits}", "max_err": err,
             "bin": bin_w, "ok": ok})
        log(f"single n={n:>8} bits={bits:>2}: max_err={err:.3e} "
            f"(bin {bin_w:.3e}) {'OK' if ok else 'FAIL'}")

    # --- 2. client-grid batch correctness ---
    # Real-TPU kernel reductions order differently from XLA's vmapped
    # tree-reduce, so bin-boundary elements may flip one bin (loudest at
    # int16's narrow bins); tolerance is one bin, not half.
    for C, n, bits in [(10, 36864, 8), (10, 1000, 16), (100, 2304, 8)]:
        x = jnp.asarray(rng.randn(C, n).astype(np.float32) * 2)
        got = np.asarray(fused_quantize_dequantize_batch(
            x, bits, force_pallas=True))
        want = np.asarray(jax.vmap(
            lambda v: quantize_dequantize(v, bits))(x))
        bin_w = float((x.max(axis=1) - x.min(axis=1)).max()) / (2 ** bits - 1)
        err = float(np.abs(got - want).max())
        ok = err < 1.05 * bin_w
        max_err_bound_ok &= ok
        results["correctness"].append(
            {"case": f"batch C={C} n={n} bits={bits}", "max_err": err,
             "bin": bin_w, "ok": ok})
        log(f"batch C={C:>3} n={n:>6} bits={bits:>2}: max_err={err:.3e} "
            f"{'OK' if ok else 'FAIL'}")

    # --- 3. timed comparison on resnet20-shaped payloads ---
    # Downlink: the full per-tensor parameter sweep inside one jit, as the
    # aggregation path executes it.
    tensors = [jnp.asarray(rng.randn(s).astype(np.float32))
               for s in RESNET20_SIZES]

    @jax.jit
    def downlink_xla(ts):
        return [quantize_dequantize(t, 8) for t in ts]

    @jax.jit
    def downlink_pallas(ts):
        return [fused_quantize_dequantize(t, 8, force_pallas=True)
                for t in ts]

    t_xla = _timeit(downlink_xla, tensors)
    t_pal = _timeit(downlink_pallas, tensors)
    results["bench"]["downlink_resnet20"] = {
        "xla_us": round(t_xla * 1e6, 1), "pallas_us": round(t_pal * 1e6, 1),
        "speedup": round(t_xla / t_pal, 2),
        "n_tensors": len(tensors),
        "payload_elems": int(sum(RESNET20_SIZES))}
    log(f"downlink (per-tensor sweep, {len(tensors)} tensors, "
        f"{sum(RESNET20_SIZES)} elems): xla={t_xla*1e6:.0f}us "
        f"pallas={t_pal*1e6:.0f}us speedup={t_xla/t_pal:.2f}x")

    # Uplink: k_online=10 stacked client payloads, flattened-model layout.
    total = int(sum(RESNET20_SIZES))
    xb = jnp.asarray(rng.randn(10, total).astype(np.float32))

    @jax.jit
    def uplink_xla(v):
        return jax.vmap(lambda t: quantize_dequantize(t, 8))(v)

    @jax.jit
    def uplink_pallas(v):
        return fused_quantize_dequantize_batch(v, 8, force_pallas=True)

    t_xla_u = _timeit(uplink_xla, xb)
    t_pal_u = _timeit(uplink_pallas, xb)
    results["bench"]["uplink_10x_resnet20_flat"] = {
        "xla_us": round(t_xla_u * 1e6, 1),
        "pallas_us": round(t_pal_u * 1e6, 1),
        "speedup": round(t_xla_u / t_pal_u, 2),
        "payload_elems": 10 * total}
    log(f"uplink ([10, {total}]): xla={t_xla_u*1e6:.0f}us "
        f"pallas={t_pal_u*1e6:.0f}us speedup={t_xla_u/t_pal_u:.2f}x")

    # Bucketed tree transform: the engine's actual quantized paths — one
    # grid launch per distinct leaf size instead of one per leaf.
    from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_tree
    down_tree = {f"t{i}": t for i, t in enumerate(tensors)}
    up_tree = {f"t{i}": jnp.asarray(rng.randn(10, s).astype(np.float32))
               for i, s in enumerate(RESNET20_SIZES)}

    @jax.jit
    def down_bucketed(tr):
        return fused_quantize_dequantize_tree(tr, 8)

    @jax.jit
    def up_bucketed(tr):
        return fused_quantize_dequantize_tree(tr, 8, leading_batch=True)

    @jax.jit
    def up_perleaf_xla(tr):
        return jax.tree.map(
            lambda x: jax.vmap(lambda v: quantize_dequantize(v, 8))(x), tr)

    t_db = _timeit(down_bucketed, down_tree)
    t_ub = _timeit(up_bucketed, up_tree)
    t_ux = _timeit(up_perleaf_xla, up_tree)
    results["bench"]["downlink_bucketed_tree"] = {
        "pallas_us": round(t_db * 1e6, 1),
        "speedup_vs_perleaf_xla": round(t_xla / t_db, 2),
        "payload_elems": int(sum(RESNET20_SIZES))}
    results["bench"]["uplink_bucketed_tree"] = {
        "pallas_us": round(t_ub * 1e6, 1),
        "perleaf_xla_us": round(t_ux * 1e6, 1),
        "speedup_vs_perleaf_xla": round(t_ux / t_ub, 2),
        "payload_elems": 10 * int(sum(RESNET20_SIZES))}
    log(f"downlink bucketed tree: {t_db*1e6:.0f}us "
        f"({t_xla/t_db:.2f}x vs per-leaf xla)")
    log(f"uplink bucketed tree: {t_ub*1e6:.0f}us vs per-leaf xla "
        f"{t_ux*1e6:.0f}us ({t_ux/t_ub:.2f}x)")

    # Large single payload (bandwidth-bound regime the kernel targets)
    for n in [1 << 20, 1 << 21]:
        xl = jnp.asarray(rng.randn(n).astype(np.float32))
        f_x = jax.jit(lambda v: quantize_dequantize(v, 8))
        f_p = jax.jit(lambda v: fused_quantize_dequantize(
            v, 8, force_pallas=True))
        t_x = _timeit(f_x, xl)
        t_p = _timeit(f_p, xl)
        results["bench"][f"single_{n}"] = {
            "xla_us": round(t_x * 1e6, 1), "pallas_us": round(t_p * 1e6, 1),
            "speedup": round(t_x / t_p, 2)}
        log(f"single n={n}: xla={t_x*1e6:.0f}us pallas={t_p*1e6:.0f}us "
            f"speedup={t_x/t_p:.2f}x")

    # --- 4. flash attention: real lowering + long-context timing ---
    from fedtorch_tpu.ops.pallas.flash_attention import flash_attention
    from fedtorch_tpu.parallel.sequence import reference_attention
    # Correctness compares PROGRAMS, so both the kernel's in-kernel
    # dots and the dense reference run under pinned f32-exact matmul
    # precision — at the TPU default, both sides use bf16-precision
    # MXU passes and legitimately diverge at rounding scale (round 5
    # measured 6.7e-3 on f32; same finding as SEQPAR_TPU_PROBE.json).
    # The timing section below stays at default precision: that is the
    # production configuration for both contenders.
    for (B, T, H, D, dt, causal) in [
            (2, 256, 4, 64, jnp.float32, True),
            (2, 256, 4, 64, jnp.float32, False),
            (1, 1024, 8, 64, jnp.bfloat16, True)]:
        ks = jax.random.split(jax.random.key(7), 3)
        q, k, v = (jax.random.normal(kk, (B, T, H, D), dt) for kk in ks)
        with jax.default_matmul_precision("highest"):
            want = np.asarray(reference_attention(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), causal=causal))
            got = np.asarray(flash_attention(q, k, v, causal=causal),
                             dtype=np.float32)
        err = float(np.abs(got - want).max())
        tol = 2e-5 if dt == jnp.float32 else 3e-2
        ok = err < tol
        max_err_bound_ok &= ok
        results["correctness"].append(
            {"case": f"flash B={B} T={T} H={H} D={D} {np.dtype(dt).name}"
                     f" causal={causal}", "max_err": err, "ok": ok})
        log(f"flash T={T:>5} {np.dtype(dt).name} causal={causal}: "
            f"max_err={err:.3e} {'OK' if ok else 'FAIL'}")
        # gradient path (chunked VJP) compiles + stays finite on chip
        g = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=causal).astype(jnp.float32) ** 2))(q)
        grad_ok = bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
        max_err_bound_ok &= grad_ok
        results["correctness"].append(
            {"case": f"flash-grad T={T} {np.dtype(dt).name} "
                     f"causal={causal}", "ok": grad_ok})

    # long-context timing: fused kernel vs materialized-score attention
    for T in (2048, 4096):
        ks = jax.random.split(jax.random.key(9), 3)
        q, k, v = (jax.random.normal(kk, (1, T, 8, 64), jnp.bfloat16)
                   for kk in ks)
        f_flash = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True))
        f_dense = jax.jit(lambda q, k, v: reference_attention(
            q, k, v, causal=True))
        t_f = _timeit(f_flash, q, k, v, iters=20)
        t_d = _timeit(f_dense, q, k, v, iters=20)
        results["bench"][f"flash_attn_T{T}"] = {
            "dense_us": round(t_d * 1e6, 1),
            "flash_us": round(t_f * 1e6, 1),
            "speedup": round(t_d / t_f, 2)}
        log(f"flash attention T={T}: dense={t_d*1e6:.0f}us "
            f"flash={t_f*1e6:.0f}us speedup={t_d/t_f:.2f}x")

    results["all_correct"] = bool(max_err_bound_ok)
    # Derive the summary from this run's measurements — never assert
    # validation or wins the adjacent keys don't show.
    def _payload(k, v):
        if k.startswith("single_"):
            return int(k.split("_")[1])
        return v.get("payload_elems", 0)

    big, small = [], []
    for k, v in results["bench"].items():
        if k.startswith("flash_attn_"):
            continue  # summarized separately below
        sp = v.get("speedup", v.get("speedup_vs_perleaf_xla"))
        (big if _payload(k, v) > _BIG_PAYLOAD else small).append(sp)
    flash_sp = [v["speedup"] for k, v in results["bench"].items()
                if k.startswith("flash_attn_")]
    corr = ("Correctness of the real-TPU lowering validated on every case "
            "(single-block, client-grid batch, two-pass tiled kernels)."
            if max_err_bound_ok else
            "CORRECTNESS FAILURES on the real-TPU lowering - see the "
            "'correctness' list; do not trust the kernels until fixed.")
    results["finding"] = (
        f"{corr} This run's timings: multi-MB payloads "
        f"{min(big):.2f}-{max(big):.2f}x vs XLA across the tiled and "
        f"client-grid batch kernels (the tiled kernel's ~2x win at 2M "
        f"elems has been consistent across sessions), "
        f"small launch-bound sweeps {min(small):.2f}-{max(small):.2f}x "
        f"(within the +/-30% run-to-run noise of the relay-attached "
        f"v5e). Kernels stay the default on unsharded TPU paths: "
        f"at-worst noise-equivalent on small payloads, faster on large "
        f"ones, single-pass stats at every size, payload trees bucketed "
        f"into one launch per distinct leaf size; XLA remains the "
        f"fallback elsewhere."
        + (f" Flash attention (causal, bf16, B=1 H=8 D=64): "
           f"{min(flash_sp):.2f}-{max(flash_sp):.2f}x vs "
           f"materialized-score attention at T=2048-4096."
           if flash_sp else ""))
    with open("PALLAS_TPU.json", "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps({"pallas_tpu_ok": results["all_correct"],
                      "platform": results["platform"],
                      "bench": results["bench"]}))
    return 0 if max_err_bound_ok else 1


if __name__ == "__main__":
    sys.exit(main())
