"""Conv-lowering A/B on the XLA **CPU** backend (VERDICT r4 item #5).

The on-chip conv A/B (``MFU_SWEEP.json`` / ``VMAP_PENALTY.json``) is
relay-gated and has never fired. This is the honest no-relay fallback:
the SAME compiled federated round program (``FederatedTrainer.run_rounds``
— the program ``bench.py`` times) is built twice per batch size, once
with ``conv_impl='conv'`` (grouped conv from per-client weights) and
once with ``conv_impl='matmul'`` (im2col batched matmul,
``models/common.py:MatmulConv``), and timed on XLA-compiled CPU. That
upgrades the round-4 claim from "2.8-5.1x on numpy CPU" to "X× between
XLA-compiled identical programs", with per-row algorithmic FLOPs from
XLA cost analysis of the conv lowering (``scripts/mfu_sweep.py``
accounting — matmul rows do NOT book im2col patch extraction as useful
work).

CAVEAT (recorded in the artifact): the CPU backend has no MXU; the
absolute times say nothing about the v5e, and the conv-vs-matmul
ratio can differ on the chip where the MXU executes large matmuls at
full rate (the reason the matmul lowering should win HARDER there —
the roofline argument in docs/performance.md "MFU roofline"). The
on-chip sweep (`scripts/tpu_capture.sh conv-ab`) remains the decision
authority; this table is the best evidence obtainable without the
relay.

Writes CONV_AB_CPU.json; prints one JSON line. Grid sizes via
MFU_CLIENTS/MFU_STEPS/MFU_ROUNDS (kept small: 1-core host).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must hold before the first jax backend touch
os.environ.setdefault("MFU_CLIENTS", "8")
os.environ.setdefault("MFU_STEPS", "5")
os.environ.setdefault("MFU_ROUNDS", "2")
os.environ["JAX_PLATFORMS"] = "cpu"

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "CONV_AB_CPU.json")


def log(msg):
    print(f"[conv_ab_cpu] {msg}", file=sys.stderr, flush=True)


def main() -> int:
    from fedtorch_tpu.utils import enable_compile_cache, \
        honor_platform_env
    honor_platform_env()
    enable_compile_cache()
    import jax
    if jax.devices()[0].platform != "cpu":
        log(f"expected cpu backend, got {jax.devices()[0]} — refusing "
            "(this script's numbers are only labeled correctly on CPU)")
        return 1

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from mfu_sweep import run_config

    rows = []
    for batch in (50, 128):
        for conv_impl in ("conv", "matmul"):
            name = f"b{batch}_{conv_impl}"
            log(f"running {name} ...")
            row = run_config(name, batch=batch, dtype="float32",
                             online_rate=0.25, conv_impl=conv_impl)
            # mfu_pct/achieved_tflops divide CPU wall-clock by the TPU
            # peak — a fabricated MFU; only the chip may report one
            for key in ("mfu_pct", "achieved_tflops", "peak_tflops"):
                row.pop(key, None)
            rows.append(row)

    # pair up the A/Bs; the ratio comes from the UNROUNDED timed
    # segments (same step count per batch config), not the 2-decimal
    # steps/s display values, which quantize to +-20-40% at these
    # magnitudes
    by = {(r["batch"], r["conv_impl"]): r for r in rows}
    speedups = {}
    for batch in (50, 128):
        conv_t = by[(batch, "conv")]["timed_s"]
        mm_t = by[(batch, "matmul")]["timed_s"]
        speedups[f"matmul_vs_conv_b{batch}"] = round(conv_t / mm_t, 2)

    record = {
        "metric": "conv_lowering_ab_xla_cpu",
        "backend": "cpu (XLA, 1 core)",
        "caveat": ("XLA-compiled identical round programs on the CPU "
                   "backend; no MXU — ratios are evidence, not the "
                   "on-chip decision (see the tpu_capture.sh conv-ab "
                   "step). FLOPs numerator is the conv lowering's "
                   "cost analysis for every row. Speedups are ratios "
                   "of the unrounded timed segments (identical step "
                   "counts per batch)."),
        "rows": rows,
        "speedups": speedups,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "grid": {k: os.environ[k] for k in
                 ("MFU_CLIENTS", "MFU_STEPS", "MFU_ROUNDS")},
    }
    with open(OUT, "w") as f:
        json.dump(record, f, indent=1)
    log(f"wrote {OUT}")
    print(json.dumps({"metric": record["metric"],
                      "speedups": speedups}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
