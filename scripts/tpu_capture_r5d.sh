#!/bin/bash
# Round-5 stage 4 (recovery): the container restarted mid-round and
# killed the r5/r5b/r5c chain after its first stages landed (bench,
# conv A/B both sides, MFU sweep, vmap penalty, MoE A/B). This stage
# runs ONLY what the crash left un-captured, in information-value
# order: flash-under-Mosaic (VERDICT r4 #4), the flash training A/B,
# the zoo refresh (TPU_ZOO.json is still the round-2 19-case run),
# the on-chip accuracy-vs-wall-clock curves (VERDICT r4 #7), the
# baseline suite, and a final bench re-persist at the current head.
#
# Single-session relay discipline (same as tpu_capture_r5.sh): strict
# serial execution, never wrap a relay-touching run in `timeout`.
#     nohup bash scripts/tpu_capture_r5d.sh > /tmp/tpu_capture_r5d.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5D_DONE=/tmp/tpu_capture_r5d.done
trap 'touch "$R5D_DONE"' EXIT

# If any earlier-stage script somehow survived the restart, defer.
while pgrep -f "bash scripts/tpu_capture_r5.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r5b.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r5c.sh" > /dev/null; do
    sleep 120
done

LAUNCH="$(date +%s)"
DEADLINE="${TPU_CAPTURE_DEADLINE_UNIX:-$(( LAUNCH + 32400 ))}"  # 9 h
echo "[tpu_capture_r5d] probing until $(date -u -d "@$DEADLINE" +%H:%M:%S) UTC"

GRANTED=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    BENCH_PROBE_TRIES=5 python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
    if [ $? -eq 0 ]; then
        GRANTED=1
        break
    fi
    echo "[tpu_capture_r5d] relay dead at $(date -u +%H:%M:%S) UTC"
    sleep 60
done

if [ "$GRANTED" -ne 1 ]; then
    echo "[tpu_capture_r5d] relay never recovered; nothing captured"
    exit 1
fi

echo "[tpu_capture_r5d] relay alive — capturing remaining stages"
FAILED=0
run() {
    echo "=== $* ==="
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}
run_to() {
    local out="$1"; shift
    echo "=== $* -> $out ==="
    BENCH_PROBE_TRIES=2 "$@" > "$out.tmp" && mv "$out.tmp" "$out"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

run python scripts/pallas_tpu_check.py           # -> PALLAS_TPU.json (flash under real Mosaic)
run python scripts/flash_train_bench.py          # -> FLASH_TRAIN.json
run python scripts/tpu_zoo_check.py              # -> TPU_ZOO.json (refresh: flash/MoE/remat/matmulconv cases)
run_to NORTHSTAR_CURVE_FEDAVG.json \
    python scripts/northstar_synthetic.py --rounds 100
run_to NORTHSTAR_CURVE_SCAFFOLD.json \
    python scripts/northstar_synthetic.py --rounds 100 --algorithm scaffold
run python scripts/baseline_suite.py             # -> BASELINE_SUITE.json
if ! conv_side_captured; then
    capture_conv_side || FAILED=1
fi
run python bench.py                              # re-persist at current head
echo "[tpu_capture_r5d] capture done (failed=$FAILED)"
exit $FAILED
