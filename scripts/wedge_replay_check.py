"""Prove the wedged-relay bench replay with a REAL capture
(VERDICT r3 #4, final leg).

Once a live on-chip bench run has persisted TPU_BENCH_CAPTURE.json,
this script simulates a wedge (probe stubbed False — touches no relay)
and runs ``bench.main()`` end-to-end, asserting the emitted record
replays the capture with machine-readable provenance. The passing
transcript is appended to docs/wedge_report_drive.md.

Exit codes: 0 = verified; 2 = no real capture present (nothing to
prove yet); 1 = replay failed (the record did NOT match the capture).
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import bench

    if not os.path.exists(bench.TPU_CAPTURE_PATH):
        print("no TPU_BENCH_CAPTURE.json yet — nothing to prove",
              file=sys.stderr)
        return 2
    with open(bench.TPU_CAPTURE_PATH) as f:
        cap = json.load(f)
    if "SYNTHETIC" in cap.get("notes", ""):
        print("capture is synthetic — refusing to certify with it",
              file=sys.stderr)
        return 2
    # a stale capture from BEFORE this pipeline launched (e.g. a prior
    # round's file) must not be certified as this round's
    min_unix = int(os.environ.get("WEDGE_MIN_CAPTURED_UNIX", "0"))
    if cap.get("captured_unix", 0) < min_unix:
        print("capture predates this pipeline launch — not certifying",
              file=sys.stderr)
        return 2

    bench.probe_device = lambda *a, **k: False  # simulated wedge
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        bench.main()
    line = out.getvalue().strip().splitlines()[-1]
    rec = json.loads(line)

    if rec.get("cached") is not True:
        # bench REFUSED the capture (stale >24h / ancestry) and emitted
        # the honest CPU record — a by-design refusal, not a broken
        # replay path; report it distinctly
        print("bench refused the capture (stale or unverifiable) and "
              "emitted the live CPU record — refusal path exercised, "
              f"replay not certified:\n{line}", file=sys.stderr)
        return 2

    ok = (rec.get("cached") is True
          and rec.get("value") == cap["value"]
          and rec.get("vs_baseline") == cap["vs_baseline"]
          and rec.get("captured_at") == cap["captured_at"]
          and rec.get("git_head") == cap["git_head"])
    if not ok:
        print(f"REPLAY MISMATCH:\ncapture={json.dumps(cap)}\n"
              f"record={json.dumps(rec)}", file=sys.stderr)
        return 1

    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "docs", "wedge_report_drive.md"),
              "a") as f:
        f.write(
            f"\n## REAL-capture replay verified ({stamp})\n\n"
            "With a live on-chip capture present, `bench.main()` under "
            "a simulated wedge (probe stubbed; no relay touched) "
            "emitted exactly the capture with machine-readable "
            "provenance:\n\n```json\n" + line + "\n```\n")
    print(json.dumps({"wedge_replay_verified": True,
                      "value": rec["value"],
                      "captured_at": rec["captured_at"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
