"""Sparse-MoE dispatch A/B off-chip (VERDICT r4 item #6, no-relay branch).

Runs the SAME cases as ``scripts/moe_ab_bench.py`` (dense exact
dispatch vs Switch sparse capacity dispatch at cf 1.0/1.25/2.0, full
train steps with aux loss) on the XLA CPU backend, recording what IS
hardware-independent:

* executed-FLOPs ratio per case (XLA cost analysis — dense dispatch
  books E× the expert-MLP FLOPs; sparse books ~cf×/E of that),
* per-layer token drop fractions at each capacity factor,
* same-seed loss trajectories (sparse must track dense closely),
* CPU step-time ratios (directional only — no MXU; recorded with that
  caveat).

Additionally times the expert-parallel layer (``parallel/expert.py:
ep_moe_apply``) dense-vs-sparse on the 8-device virtual CPU mesh, the
deployment shape for E=16 at scale.

The on-chip A/B (queued in scripts/tpu_capture_r5.sh) stays the
decision authority for absolute times; this artifact is the evidence
basis for the recommended-config note in docs/performance.md.

Writes MOE_AB_CPU.json; prints one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# 1-core host: shrink the workload before moe_ab_bench reads its env
# knobs at import time. Dense E=16 at these sizes is ~tens of GFLOPs
# per step — minutes total, not hours.
os.environ.setdefault("MOE_AB_BATCH", "2")
os.environ.setdefault("MOE_AB_SEQ", "128")
os.environ.setdefault("MOE_AB_ITERS", "3")
os.environ.setdefault("MOE_AB_LOSS_STEPS", "12")
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

OUT = os.path.join(REPO, "MOE_AB_CPU.json")


def log(msg):
    print(f"[moe_ab_cpu] {msg}", file=sys.stderr, flush=True)


def ep_mesh_ab():
    """Layer-level dense-vs-sparse timing with experts sharded over an
    8-device 'ep' axis — the virtual-mesh half of VERDICT r4 #6."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from fedtorch_tpu.parallel.expert import ep_moe_apply
    from fedtorch_tpu.models.transformer import MoEMLP

    E, D, B, T = 16, 256, 2, 128
    devs = jax.devices()
    if len(devs) < 8:
        raise RuntimeError(
            f"expected the 8-device virtual mesh, found {len(devs)} "
            "devices (a pre-existing XLA_FLAGS device count?) — "
            "refusing to record mislabeled ep timings")
    mesh = Mesh(np.array(devs[:8]), ("ep",))
    x = jax.random.normal(jax.random.key(2), (B, T, D), jnp.float32)
    params = MoEMLP(num_experts=E).init(  # d inferred from x
        jax.random.key(0), x)["params"]

    rows = {}
    for name, cf in (("dense", 0.0), ("cf1.25", 1.25)):
        out = ep_moe_apply(params, x, mesh, capacity_factor=cf)
        jax.block_until_ready(out)  # compile
        t0 = time.time()
        for _ in range(5):
            out = ep_moe_apply(params, x, mesh, capacity_factor=cf)
        jax.block_until_ready(out)
        rows[name] = round((time.time() - t0) / 5 * 1e3, 2)
        log(f"ep-mesh {name}: {rows[name]} ms/layer-fwd")
    rows["sparse_cf1.25_speedup"] = round(
        rows["dense"] / rows["cf1.25"], 2)
    return rows


def main() -> int:
    from fedtorch_tpu.utils import enable_compile_cache, \
        honor_platform_env
    honor_platform_env()
    enable_compile_cache()
    import jax
    if jax.devices()[0].platform != "cpu":
        log("expected the cpu backend — refusing to mislabel")
        return 1

    import moe_ab_bench as ab

    results = {"platform": "cpu (XLA, 1 core; 8-device virtual mesh "
                           "for the ep section)",
               "caveat": ("off-chip: step-time ratios are directional "
                          "(no MXU); flops_per_step ratios, drop "
                          "fractions and loss tracking are hardware-"
                          "independent. On-chip decision authority: "
                          "MOE_AB.json via scripts/tpu_capture_r5.sh"),
               "config": {"batch": ab.B, "seq": ab.T, "experts": ab.E,
                          "d_model": ab.D_MODEL, "layers": ab.LAYERS,
                          "loss_steps": ab.LOSS_STEPS},
               "cases": {}}
    for name, cf in (("dense", 0.0), ("cf1.0", 1.0),
                     ("cf1.25", 1.25), ("cf2.0", 2.0)):
        log(f"running {name} ...")
        results["cases"][name] = ab.run_case(name, cf)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    dense = results["cases"]["dense"]
    sp = results["cases"]["cf1.25"]
    summary = {}
    if dense.get("flops_per_step") and sp.get("flops_per_step"):
        summary["flops_ratio_dense_over_cf1.25"] = round(
            dense["flops_per_step"] / sp["flops_per_step"], 2)
    summary["steptime_ratio_dense_over_cf1.25"] = round(
        dense["step_ms"] / sp["step_ms"], 2)
    summary["ce_delta_cf1.25_minus_dense"] = round(
        sp["final_ce"] - dense["final_ce"], 4)
    results["summary"] = summary

    try:
        results["ep_mesh_8dev"] = ep_mesh_ab()
    except Exception as e:
        results["ep_mesh_8dev"] = {"error": str(e)[:300]}
        log(f"ep-mesh section failed: {str(e)[:160]}")

    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    log(f"wrote {OUT}")
    print(json.dumps({"metric": "moe_dispatch_ab_cpu", **summary}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
