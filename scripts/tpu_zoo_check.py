"""Execute the full algorithm zoo ON THE REAL TPU (single chip).

`__graft_entry__.dryrun_multichip` validates the sharded program on the
virtual CPU mesh; this is its real-hardware counterpart: every
aggregation family, wire format, and engine hook compiles through the
actual TPU toolchain (mosaic/XLA-TPU) and executes one round on the
chip. Catches real-lowering-only failures (e.g. the scoped-VMEM OOM the
pallas quantize kernel hit at 2M elements, PALLAS_TPU.json).

Also covers engine and model families the MLP-only dryrun matrix does
not: the char-GRU (shakespeare workload, explicit carry), the
transformer LM, bf16 ResNet-20 (the north-star arch), the non-federated
local-SGD engine (`LocalSGDTrainer.fit`), and both sequence-parallel
attention strategies on a 1-chip mesh.

Writes TPU_ZOO.json; prints one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _run_zoo_case, _zoo_configs  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _mean_online_loss(metrics) -> float:
    """Per-online-client mean train loss — the one loss definition every
    case in this artifact reports."""
    return float(metrics.train_loss.sum()
                 / max(float(metrics.online_mask.sum()), 1.0))


def _model_cases():
    """(name, cfg-builder) cases beyond the MLP zoo matrix."""
    import jax

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    import numpy as np

    def run(arch, feats, labels, *, dataset, dtype="float32", C=4, B=4,
            model_kw=None, mesh_kw=None, seq=None):
        parts = [np.arange(i * len(feats) // C, (i + 1) * len(feats) // C)
                 for i in range(C)]
        data = stack_partitions(feats, labels, parts)
        mkw = dict(model_kw or {})
        if seq:
            mkw["rnn_seq_len"] = seq
        cfg = ExperimentConfig(
            data=DataConfig(dataset=dataset, batch_size=B),
            federated=FederatedConfig(federated=True, num_clients=C,
                                      online_client_rate=1.0,
                                      algorithm="fedavg",
                                      sync_type="local_step"),
            model=ModelConfig(arch=arch, **mkw),
            optim=OptimConfig(lr=0.05, in_momentum=True),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(num_devices=1, compute_dtype=dtype,
                            **(mesh_kw or {})),
        ).finalize()
        model = define_model(cfg, batch_size=B)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
        server, clients = trainer.init_state(jax.random.key(0))
        server, clients, m = trainer.run_round(server, clients)
        jax.block_until_ready(server.params)
        return _mean_online_loss(m)

    rng = np.random.RandomState(3)

    def resnet_bf16():
        return run("resnet20",
                   rng.randn(64, 32, 32, 3).astype(np.float32),
                   rng.randint(0, 10, 64), dataset="cifar10",
                   dtype="bfloat16")

    def gru_shakespeare():
        # shakespeare-shaped: int char ids, next-char targets
        x = rng.randint(0, 86, (64, 50)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        return run("rnn", x, y, dataset="shakespeare", dtype="bfloat16",
                   seq=50)

    def transformer_lm():
        x = rng.randint(0, 86, (64, 64)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        return run("transformer", x, y, dataset="shakespeare",
                   dtype="bfloat16", seq=64,
                   model_kw={"mlp_num_layers": 2,
                             "rnn_hidden_size": 32})

    def local_sgd():
        # the non-federated data-parallel engine (distributed.py mode):
        # two steps-per-sync rounds through LocalSGDTrainer.fit
        from fedtorch_tpu.parallel import build_local_sgd

        cfg = ExperimentConfig(
            data=DataConfig(dataset="cifar10", batch_size=4),
            federated=FederatedConfig(federated=False, num_clients=4),
            model=ModelConfig(arch="cnn"),
            optim=OptimConfig(lr=0.05, in_momentum=True),
            train=TrainConfig(local_step=2, num_epochs=1),
            mesh=MeshConfig(num_devices=1, compute_dtype="bfloat16"),
        ).finalize()
        feats = rng.randn(64, 32, 32, 3).astype(np.float32)
        labels = rng.randint(0, 10, 64)
        model = define_model(cfg, batch_size=4)
        trainer = build_local_sgd(cfg, model, feats, labels)
        _, _, history = trainer.fit(jax.random.key(0))
        return _mean_online_loss(history[-1])

    def seqpar_single_chip():
        # both sequence-parallel strategies lower through the real TPU
        # toolchain (1-chip mesh: the collectives become no-ops but the
        # shard_map program still compiles on mosaic/XLA-TPU); same
        # check as the CPU-mesh dryrun, on real hardware
        from __graft_entry__ import _run_sequence_parallel

        return _run_sequence_parallel(1, label="tpu_zoo(1)")

    def transformer_flash_moe():
        # flash-attention kernel + sparse-MoE dispatch + Switch aux loss
        # through the engine on the real chip, bf16
        x = rng.randint(0, 86, (64, 64)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        return run("transformer", x, y, dataset="shakespeare",
                   dtype="bfloat16", seq=64,
                   model_kw={"mlp_num_layers": 2, "rnn_hidden_size": 32,
                             "attention": "flash", "moe_experts": 4,
                             "moe_capacity_factor": 1.25,
                             "moe_aux_weight": 0.01})

    def resnet_remat_bf16():
        # per-block rematerialization through the real backward pass
        return run("resnet20",
                   rng.randn(64, 32, 32, 3).astype(np.float32),
                   rng.randint(0, 10, 64), dataset="cifar10",
                   dtype="bfloat16", mesh_kw={"remat": True})

    def resnet_matmulconv_bf16():
        # the im2col batched-matmul conv lowering through the real MXU
        # (models/common.py MatmulConv — the MFU lever; mfu_sweep times
        # it, this proves lowering + a finite training round)
        return run("resnet20",
                   rng.randn(64, 32, 32, 3).astype(np.float32),
                   rng.randint(0, 10, 64), dataset="cifar10",
                   dtype="bfloat16",
                   model_kw={"conv_impl": "matmul"})

    def batched_rounds():
        # the single-dispatch scan driver (bench fast path) on the chip
        parts = [np.arange(i * 16, (i + 1) * 16) for i in range(4)]
        feats = rng.randn(64, 20).astype(np.float32)
        labels = rng.randint(0, 10, 64)
        data = stack_partitions(feats, labels, parts)
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=20,
                            batch_size=8),
            federated=FederatedConfig(federated=True, num_clients=4,
                                      online_client_rate=1.0,
                                      algorithm="fedavg",
                                      sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.05),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(num_devices=1),
        ).finalize()
        model = define_model(cfg, batch_size=8)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
        server, clients = trainer.init_state(jax.random.key(0))
        server, clients, ms = trainer.run_rounds(server, clients, 3)
        jax.block_until_ready(server.params)
        return float(ms.train_loss[-1].sum()
                     / max(float(ms.online_mask[-1].sum()), 1.0))

    return [("resnet20_bf16", resnet_bf16, "loss"),
            ("rnn_gru_bf16", gru_shakespeare, "loss"),
            ("transformer_bf16", transformer_lm, "loss"),
            ("transformer_flash_moe_bf16", transformer_flash_moe, "loss"),
            ("resnet20_remat_bf16", resnet_remat_bf16, "loss"),
            ("resnet20_matmulconv_bf16", resnet_matmulconv_bf16,
             "loss"),
            ("batched_rounds_scan", batched_rounds, "loss"),
            ("local_sgd_cnn_bf16", local_sgd, "loss"),
            ("seqpar_1chip", seqpar_single_chip, "err")]


def main():
    import jax

    devs = jax.devices()
    log(f"devices: {devs}")
    on_tpu = devs[0].platform != "cpu"
    results = {"platform": str(devs[0]), "cases": {}}
    ok = True

    # ZOO_ONLY=substr[,substr...]: run only matching cases and MERGE
    # them into the existing artifact (all_ok recomputed over the
    # merged set). Lets a targeted fix re-validate one case in minutes
    # of relay window instead of re-running the full zoo.
    only = [s for s in os.environ.get("ZOO_ONLY", "").split(",") if s]
    if only and not on_tpu:
        # a PARTIAL CPU run must not clobber a real on-chip artifact
        # with a one-case CPU record — refuse before running anything
        log("ZOO_ONLY partial run off-TPU: artifact left untouched")
        print(json.dumps({"tpu_zoo_ok": False, "skipped": True,
                          "platform": results["platform"]}))
        return 1

    def selected(name: str) -> bool:
        return not only or any(s in name for s in only)

    for name, fed_kw, trainer_kw in _zoo_configs(1):
        if not selected(name):
            continue
        t0 = time.time()
        try:
            m = _run_zoo_case(name, fed_kw, trainer_kw, 1)
            loss = _mean_online_loss(m)
            finite = loss == loss and abs(loss) != float("inf")
            results["cases"][name] = {
                "ok": bool(finite), "loss": round(loss, 4),
                "secs": round(time.time() - t0, 1)}
            ok &= finite
            log(f"{name}: loss {loss:.4f} ({time.time()-t0:.1f}s)")
        except Exception as e:
            results["cases"][name] = {"ok": False,
                                      "error": str(e)[:300]}
            ok = False
            log(f"{name}: FAIL {str(e)[:200]}")

    for name, fn, kind in _model_cases():
        if not selected(name):
            continue
        t0 = time.time()
        try:
            val = fn()
            finite = val == val and abs(val) != float("inf")
            # "err" cases measure a numerical error bound (seqpar vs the
            # dense oracle), not a training loss — keep full precision
            rec = {"ok": bool(finite),
                   kind: round(val, 4) if kind == "loss" else val,
                   "secs": round(time.time() - t0, 1)}
            results["cases"][name] = rec
            ok &= finite
            log(f"{name}: {kind} {val:.4g} ({time.time()-t0:.1f}s)")
        except Exception as e:
            results["cases"][name] = {"ok": False,
                                      "error": str(e)[:300]}
            ok = False
            log(f"{name}: FAIL {str(e)[:200]}")

    if not on_tpu:
        # the whole point is the real TPU toolchain: a CPU run proves
        # nothing and must not produce a passing artifact
        ok = False
        log("NOT ON TPU — recording failure; rerun when the relay is up")

    if only and not results["cases"]:
        # a pattern that selects nothing must not write a vacuously
        # green artifact
        log(f"ZOO_ONLY={','.join(only)} matched no cases — not writing")
        print(json.dumps({"tpu_zoo_ok": False, "skipped": True,
                          "platform": results["platform"]}))
        return 1

    if only:
        # partial run: merge into the prior ON-CHIP artifact; all_ok
        # reflects the MERGED case set so one green re-run can't mask
        # other failures (and vice versa). Refuse when there is no
        # prior artifact or the prior is a CPU run — merging would
        # stamp never-ran-on-chip cases into a green on-chip record.
        prior = None
        if os.path.exists("TPU_ZOO.json"):
            with open("TPU_ZOO.json") as f:
                prior = json.load(f)
        if prior is None or "CPU RUN" in prior.get("note", ""):
            log("ZOO_ONLY needs a prior on-chip TPU_ZOO.json to merge "
                "into — run the full zoo first; not writing")
            print(json.dumps({"tpu_zoo_ok": False, "skipped": True,
                              "platform": results["platform"]}))
            return 1
        merged = dict(prior.get("cases", {}))
        merged.update(results["cases"])
        updated = sorted(results["cases"])
        results["cases"] = merged
        results["partial_update"] = {
            "cases": updated,
            "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        ok = all(c.get("ok") for c in merged.values())

    results["all_ok"] = bool(ok)
    results["note"] = ("single-chip execution of every zoo case; the "
                       "sharded multi-device program is covered by "
                       "dryrun_multichip on the virtual CPU mesh"
                       if on_tpu else
                       "CPU RUN — does not validate the TPU toolchain; "
                       "all_ok forced false")
    with open("TPU_ZOO.json", "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps({"tpu_zoo_ok": ok,
                      "n_cases": len(results["cases"]),
                      "platform": results["platform"]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
