#!/bin/bash
# Round-5 stage 3: after the curve stage, run the grouped-conv side of
# the bench-level lowering A/B. The shipped default is now the im2col
# matmul lowering (conv_impl='auto' — models/__init__.py
# resolve_conv_impl), so the main chain's default bench.py run measures
# matmul and this records the conv side for the on-chip speedup table.
#     nohup bash scripts/tpu_capture_r5c.sh > /tmp/tpu_capture_r5c.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

while pgrep -f "bash scripts/tpu_capture_r5.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r5b.sh" > /dev/null; do
    sleep 120
done
if [ -s BENCH_CONVSIDE_AB.json ] \
        && ! grep -q "CPU fallback" BENCH_CONVSIDE_AB.json; then
    echo "[tpu_capture_r5c] conv side already captured by the main "\
"chain; nothing to do"
    exit 0
fi
echo "[tpu_capture_r5c] prior stages done — probing"

BENCH_PROBE_TRIES=3 python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture_r5c] relay dead; conv-side A/B not captured"
    exit 1
fi

echo "[tpu_capture_r5c] relay alive — conv-side bench A/B"
BENCH_PROBE_TRIES=2 env BENCH_CONV_IMPL=conv python bench.py \
    | tee BENCH_CONVSIDE_AB.json
rc=${PIPESTATUS[0]}  # bench's status, not tee's
if [ "$rc" -ne 0 ] \
        || grep -q "CPU fallback" BENCH_CONVSIDE_AB.json; then
    # bench exits 0 on relay fallback; a wedged-relay CPU record must
    # not sit under an on-chip A/B filename either
    rm -f BENCH_CONVSIDE_AB.json
    rc=1
fi
echo "[tpu_capture_r5c] done rc=$rc"
exit $rc
