#!/bin/bash
# Round-5 stage 3: after the curve stage, backfill the NON-DEFAULT
# side of the bench-level lowering A/B if the main chain didn't get
# to it. The shipped default 'auto' is backend-aware (native conv on
# TPU: models/__init__.py resolve_conv_impl, reversed on-chip in
# round 5), so the main chain's default bench.py run measures grouped
# conv and this records the im2col matmul side for the speedup table.
#     nohup bash scripts/tpu_capture_r5c.sh > /tmp/tpu_capture_r5c.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh

wait_for_done "$R5B_DONE"  # sentinel ordering: see capture_lib.sh
if conv_side_captured; then
    echo "[tpu_capture_r5c] conv side already captured by the main "\
"chain; nothing to do"
    exit 0
fi
echo "[tpu_capture_r5c] prior stages done — probing"

BENCH_PROBE_TRIES=3 python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture_r5c] relay dead; conv-side A/B not captured"
    exit 1
fi

echo "[tpu_capture_r5c] relay alive — backfilling the conv side"
capture_conv_side
rc=$?
echo "[tpu_capture_r5c] done rc=$rc"
exit $rc
