#!/bin/bash
# Round-5 stage 11 (tail watchdog): the relay wedged after the big
# round-5 window closed (~08:00Z). Probe until just before the
# driver's end-of-round bench; if the relay recovers, take one
# quiet-host north-star capture at the current head (+ wedge-replay
# re-certification) so the driver's record is as fresh as possible.
#     nohup bash scripts/tpu_capture_r5k.sh > /tmp/tpu_capture_r5k.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5K_DONE=/tmp/tpu_capture_r5k.done
rm -f "$R5K_DONE"
trap 'touch "$R5K_DONE"' EXIT

# All prior stages have touched their sentinels (verified before this
# stage was written); this guard only covers a stray survivor. It is
# BOUNDED so a hung predecessor cannot eat the whole watchdog window
# (review r5k) — after 30 min we proceed regardless and rely on the
# probe itself failing if the relay is genuinely busy.
WAITED=0
while pgrep -f "bash scripts/tpu_capture_r5[d-j]" > /dev/null \
      && [ "$WAITED" -lt 1800 ]; do
    sleep 120
    WAITED=$(( WAITED + 120 ))
done

DEADLINE="${TPU_CAPTURE_DEADLINE_UNIX:-$(( $(date +%s) + 14400 ))}"  # ~4 h
echo "[tpu_capture_r5k] probing until $(date -u -d "@$DEADLINE" +%H:%M:%S) UTC"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    if probe_relay 2; then
        echo "[tpu_capture_r5k] relay recovered at $(date -u +%H:%M:%S) UTC"
        # quiet-host gate (1-core box: load < 0.9, up to 5 min patience)
        for _ in $(seq 10); do
            LOAD="$(cut -d' ' -f1 /proc/loadavg)"
            OK="$(python -c "print(1 if float('$LOAD') < 0.9 else 0)")"
            [ "$OK" = "1" ] && break
            sleep 30
        done
        BENCH_T0="$(date +%s)"
        BENCH_PROBE_TRIES=2 python bench.py
        echo "[tpu_capture_r5k] bench rc=$?"
        FRESH="$(BENCH_T0="$BENCH_T0" python - <<'EOF'
import json, os
try:
    with open("TPU_BENCH_CAPTURE.json") as f:
        print(1 if json.load(f).get("captured_unix", 0)
              >= int(os.environ["BENCH_T0"]) else 0)
except Exception:
    print(0)
EOF
)"
        if [ "$FRESH" = "1" ]; then
            # certify exactly the capture just taken: min-unix is this
            # bench's launch time, not a round-start constant
            WEDGE_MIN_CAPTURED_UNIX="$BENCH_T0" \
                python scripts/wedge_replay_check.py
            rc=$?
            echo "[tpu_capture_r5k] fresh capture; cert rc=$rc (0=verified)"
            exit $rc
        fi
        echo "[tpu_capture_r5k] bench ran but capture not refreshed (relay re-wedged?); continuing to probe"
    fi
    sleep 180
done
echo "[tpu_capture_r5k] deadline reached without a fresh capture; the 07:37Z capture stands"
exit 1
