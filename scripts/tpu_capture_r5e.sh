#!/bin/bash
# Round-5 stage 5: close out the capture chain after the recovery
# stage (tpu_capture_r5d.sh). Two jobs the recovery stage left open
# (flagged in its review):
#   1. VALIDATE the final re-persist — bench.py exits 0 on a CPU
#      fallback without touching TPU_BENCH_CAPTURE.json, so r5d's
#      last stage can silently no-op; if the capture is still the
#      old-head one and the relay answers, redo the re-persist.
#   2. CERTIFY the wedge-replay path against the REAL capture
#      (VERDICT r4 item #3) with WEDGE_MIN_CAPTURED_UNIX pinned to
#      this round's start so only a round-5 capture can satisfy it.
#     nohup bash scripts/tpu_capture_r5e.sh > /tmp/tpu_capture_r5e.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
R5D_DONE=/tmp/tpu_capture_r5d.done
while [ ! -f "$R5D_DONE" ]; do sleep 120; done
echo "[tpu_capture_r5e] recovery stage done"

# Round-5 started 2026-07-31T01:53Z (commit 24a437a); any real capture
# after that is this round's. Rounds 3-4 had zero captures, so the
# stamp only has to exclude the round-2 session.
ROUND5_START_UNIX=1785462780

capture_head() {
    python - <<'EOF'
import json, sys
try:
    with open("TPU_BENCH_CAPTURE.json") as f:
        cap = json.load(f)
    print(cap.get("git_head", ""))
except Exception:
    print("")
EOF
}

HEAD_NOW="$(git rev-parse HEAD)"
CAP_HEAD="$(capture_head)"
if [ "$CAP_HEAD" != "$HEAD_NOW" ]; then
    echo "[tpu_capture_r5e] capture head $CAP_HEAD != HEAD $HEAD_NOW — re-persisting"
    BENCH_PROBE_TRIES=3 python bench.py
    CAP_HEAD="$(capture_head)"
    if [ "$CAP_HEAD" != "$HEAD_NOW" ]; then
        echo "[tpu_capture_r5e] re-persist did NOT refresh the capture (relay wedged?); the prior-head capture stands (ancestry-validated at replay time)"
    fi
fi

WEDGE_MIN_CAPTURED_UNIX="$ROUND5_START_UNIX" \
    python scripts/wedge_replay_check.py
rc=$?
echo "[tpu_capture_r5e] wedge_replay_check rc=$rc (0=verified, 2=no eligible capture)"
echo "[tpu_capture_r5e] done"
exit $rc
