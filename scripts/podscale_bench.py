"""Pod-scale shard-sweep A/B (ISSUE 20): rounds/sec and clients/sec
vs `mesh.client_shards` on the north-star-shaped workload.

For each S in {1, 2, 4} that divides both the device count and the
dispatch cohort width, builds the stream-plane round program with the
client axis sharded S ways (per-shard vmap slab, on-chip partial sums,
exactly ONE cross-shard all-reduce at the `_round_core` seam) and
records, under the recompilation sentinel:

* steady-state round wall-time (fetch-synced — bench_timing.sync),
  rounds/sec, and clients/sec (= k_dispatch * rounds/sec — the
  pod-scale headline: how fast the pod chews through online clients);
* retraces during the timed window (the sharded program must trace
  exactly once, in warmup — trace-once is a hard bar, not a metric);
* bitwise parity of the final server params against the S=1 arm (the
  hierarchical level-1/level-2 sum is shard-count-invariant by
  construction; this is the run-time proof);
* the pod-scale gauges (`client_shards`, `cohort_allreduce_bytes`,
  per-shard producer walls) off `telemetry_gauges()`.

Writes PODSCALE_AB.json (PODSCALE_AB_PATH overrides, for the test
smoke), seeded with the MULTICHIP_r05.json point when that capture
artifact is present, plus a compare-able run dir (PODSCALE_RUNS_DIR,
default artifacts/podscale_northstar) from the LARGEST shard arm that
the `podscale` capture step gates via `fedtorch-tpu compare --gate
tests/data/ops_runs/podscale_gates.json` against the previous window
(regressed clients/sec fails the capture).

PODSCALE_BENCH_SMOKE=1 shrinks the workload for CPU CI and forces an
8-device host-platform mesh so the shard sweep is real on one CPU.

Run:  python scripts/podscale_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

SMOKE = os.environ.get("PODSCALE_BENCH_SMOKE") == "1"
if SMOKE:
    # the sweep needs a multi-device mesh even on a CPU box — force it
    # BEFORE jax imports (flag is read at backend init)
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from fedtorch_tpu.utils import enable_compile_cache, \
    honor_platform_env  # noqa: E402

if not SMOKE:
    honor_platform_env()  # site hook may pin jax_platforms to proxy
enable_compile_cache()

from bench_timing import sync  # noqa: E402
from fedtorch_tpu.algorithms import make_algorithm  # noqa: E402
from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
    ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data  # noqa: E402
from fedtorch_tpu.models import define_model  # noqa: E402
from fedtorch_tpu.parallel import FederatedTrainer  # noqa: E402
from fedtorch_tpu.utils.tracing import (  # noqa: E402
    RecompilationSentinel,
)

SHARD_SWEEP = (1, 2, 4)
NUM_CLIENTS = 8 if SMOKE else 64
ONLINE = 0.5 if SMOKE else 0.25          # k = 4 smoke / 16 full
BATCH = 8 if SMOKE else 32
K_LOCAL = 2 if SMOKE else 10
DIM = 16 if SMOKE else 256
ROUNDS = 3 if SMOKE else 20
SETTLE = 0 if SMOKE else 2
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(shards: int) -> FederatedTrainer:
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=DIM,
                        batch_size=BATCH, data_plane="stream"),
        federated=FederatedConfig(
            federated=True, num_clients=NUM_CLIENTS,
            online_client_rate=ONLINE, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=K_LOCAL),
        mesh=MeshConfig(client_shards=shards),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=BATCH)
    return FederatedTrainer(cfg, model, make_algorithm(cfg),
                            data.train)


def run_arm(shards: int):
    """One shard arm: warmup trace + settle, then ROUNDS timed rounds
    under the sentinel. Returns (per-round rows, summary, params)."""
    tr = build(shards)
    server, clients = tr.init_state(jax.random.key(0))
    server, clients, m = tr.run_round(server, clients)
    sync(server.params)
    jax.device_get(tr.round_scalars_dev(clients, m))
    for _ in range(SETTLE):
        server, clients, m = tr.run_round(server, clients)
        jax.device_get(tr.round_scalars_dev(clients, m))
    rows = []
    with RecompilationSentinel() as sentinel:
        t0 = time.perf_counter()
        for r in range(ROUNDS):
            r0 = time.perf_counter()
            server, clients, m = tr.run_round(server, clients)
            sync(server.params)
            dt = time.perf_counter() - r0
            sc = jax.device_get(tr.round_scalars_dev(clients, m))
            n = max(float(sc["n_online"]), 1.0)
            rows.append({"round": r, "round_s": dt,
                         "loss": float(sc["loss_sum"]) / n,
                         "acc": float(sc["acc_sum"]) / n,
                         "comm_bytes": float(sc["comm_bytes"])})
        total = time.perf_counter() - t0
    retraces = sum(sentinel.counts.values())
    gauges = tr.telemetry_gauges()
    params = jax.device_get(server.params)
    tr.invalidate_stream()
    k = tr.k_dispatch
    rps = ROUNDS / total
    summary = {
        "client_shards": shards,
        "k_dispatch": int(k),
        "ms_per_round": total / ROUNDS * 1e3,
        "rounds_per_s": rps,
        "clients_per_s": k * rps,
        "retraces_during_timed_rounds": retraces,
        "cohort_allreduce_bytes": gauges.get("cohort_allreduce_bytes",
                                             0.0),
        "stream_shard_pack_s": gauges.get("stream_shard_pack_s", 0.0),
    }
    return rows, summary, gauges, params


def write_run_dir(path: str, rows, meta: dict, gauges: dict):
    """The compare-able artifact (fedtorch_tpu.metrics/v1, the same
    shape `fedtorch-tpu summarize/compare` reads for every bench)."""
    os.makedirs(path, exist_ok=True)
    keep = {k: float(v) for k, v in gauges.items()
            if k in ("client_shards", "cohort_allreduce_bytes",
                     "stream_shard_pack_s", "stream_shard_rows")}
    with open(os.path.join(path, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"schema": "fedtorch_tpu.metrics/v1",
                            "created_unix": time.time(),
                            "run": meta}) + "\n")
        for row in rows:
            f.write(json.dumps(dict(row, **keep)) + "\n")


def main():
    devs = jax.devices()
    log(f"devices: {len(devs)} x {devs[0].platform}")
    out = {
        "platform": f"{len(devs)} x {devs[0].device_kind}",
        "config": {"num_clients": NUM_CLIENTS, "online": ONLINE,
                   "batch": BATCH, "K": K_LOCAL, "dim": DIM,
                   "rounds_timed": ROUNDS, "smoke": SMOKE,
                   "data_plane": "stream", "shard_sweep": []},
        "shards": {},
    }
    seed_path = os.path.join(REPO, "MULTICHIP_r05.json")
    if os.path.exists(seed_path):
        with open(seed_path) as f:
            out["seed_point"] = json.load(f)
    finals = {}
    best = None
    n_dev = len(devs)
    # probe k once (S=1 always admissible) for the divisibility filter
    k_probe = build(1).k_dispatch
    sweep = [s for s in SHARD_SWEEP
             if n_dev % s == 0 and k_probe % s == 0]
    out["config"]["shard_sweep"] = sweep
    for shards in sweep:
        log(f"--- client_shards={shards}")
        rows, summary, gauges, params = run_arm(shards)
        finals[shards] = params
        # finals hold HOST numpy (device_get in run_arm) — no device
        # sync; the parity bar is bitwise against the S=1 twin
        diffs = [float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                 for a, b in zip(jax.tree.leaves(finals[sweep[0]]),
                                 jax.tree.leaves(finals[shards]))]
        summary["parity_bitwise_vs_one_shard"] = max(diffs) == 0.0
        out["shards"][str(shards)] = summary
        log(f"    {summary['ms_per_round']:.2f} ms/round  "
            f"{summary['clients_per_s']:.1f} clients/s  "
            f"retraces={summary['retraces_during_timed_rounds']}  "
            f"bitwise={summary['parity_bitwise_vs_one_shard']}")
        best = (rows, summary, gauges)  # largest S wins the run dir
    if best is not None:
        runs_dir = os.environ.get("PODSCALE_RUNS_DIR") or os.path.join(
            REPO, "artifacts", "podscale_northstar")
        write_run_dir(runs_dir, best[0],
                      dict(out["config"],
                           client_shards=best[1]["client_shards"],
                           platform=out["platform"]),
                      best[2])
        log(f"run dir: {runs_dir}")
    out["ok"] = all(
        s["parity_bitwise_vs_one_shard"]
        and s["retraces_during_timed_rounds"] == 0
        for s in out["shards"].values())
    path = os.environ.get("PODSCALE_AB_PATH") or os.path.join(
        REPO, "PODSCALE_AB.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"wrote {path}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
