#!/bin/bash
# Round-4 stage 3: after stages 1+2 finish, certify the wedged-relay
# replay path with the REAL capture (scripts/wedge_replay_check.py).
# Touches no relay (the check stubs the probe), so it is safe to run
# regardless of relay state; it no-ops (rc 2) if no real capture landed.
#     nohup bash scripts/tpu_capture_r4b.sh > /tmp/tpu_capture_r4b.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

# only certify a capture taken AFTER this launch (a prior round's
# leftover file must not produce a spurious "verified" transcript)
export WEDGE_MIN_CAPTURED_UNIX="$(date +%s)"

while pgrep -f "bash scripts/tpu_capture_full.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r4.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r4c.sh" > /dev/null; do
    sleep 120
done
echo "[tpu_capture_r4b] stages 1+2 done — running the replay check"
python scripts/wedge_replay_check.py
rc=$?
echo "[tpu_capture_r4b] wedge_replay_check rc=$rc (0=verified, 2=no capture)"
exit $rc
