"""Transformer TRAINING throughput on the real TPU: dense vs flash
attention (vs flash+remat), at growing context length.

scripts/pallas_tpu_check.py times the attention FORWARD in isolation;
this script times full training steps (loss + backward + SGD update,
jitted, bf16) of a small causal LM, where the flash kernel's fused
forward and the chunked recompute-from-logsumexp VJP both participate —
the number a user choosing ``--attention flash`` actually experiences.
remat adds the activation-memory trade on top (expected: slightly
slower, much smaller activation footprint — enabling longer T).

Writes FLASH_TRAIN.json; prints one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    from bench import probe_device
    if not probe_device():
        log("TPU unavailable — this bench only means something on the "
            "real chip; nothing recorded")
        return 1
    import jax
    import jax.numpy as jnp
    import optax

    from fedtorch_tpu.models.transformer import TransformerLM
    from fedtorch_tpu.utils import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    log(f"device: {dev}")

    results = {"platform": str(dev), "cases": {}}
    B, D_MODEL, HEADS, LAYERS, VOCAB = 1, 256, 8, 4, 256

    def step_time(model, params, toks, tgts, iters=10):
        opt = optax.sgd(0.01)

        @jax.jit
        def train_step(params, state):
            def loss_fn(p):
                logits = model.apply({"params": p}, toks)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.take_along_axis(
                    logp, tgts[..., None], axis=-1))

            loss, g = jax.value_and_grad(loss_fn)(params)
            upd, state = opt.update(g, state)
            return optax.apply_updates(params, upd), state, loss

        state = opt.init(params)
        t0 = time.time()
        params, state, loss = train_step(params, state)
        float(loss)  # fetch-sync: block_until_ready can no-op on the
        compile_s = time.time() - t0  # relay (BASELINE_REPRO round 5)
        t0 = time.time()
        for _ in range(iters):
            params, state, loss = train_step(params, state)
        final_loss = float(loss)  # materialize BEFORE reading the clock
        return (time.time() - t0) / iters, compile_s, final_loss

    for T in (1024, 2048, 4096, 8192):
        toks = jax.random.randint(jax.random.key(1), (B, T), 0, VOCAB)
        tgts = jnp.roll(toks, -1, axis=1)
        row = {}
        base_params = None
        for name, kw in (("dense", {}),
                         ("flash", {"attention": "flash"}),
                         ("flash_remat", {"attention": "flash",
                                          "remat": True})):
            model = TransformerLM(vocab_size=VOCAB, d_model=D_MODEL,
                                  num_heads=HEADS, num_layers=LAYERS,
                                  max_len=T, dtype="bfloat16", **kw)
            try:
                if base_params is None:
                    base_params = model.init(jax.random.key(0), toks)[
                        "params"]
                sec, compile_s, loss = step_time(model, base_params,
                                                 toks, tgts)
                row[name] = {"step_ms": round(sec * 1e3, 2),
                             "compile_s": round(compile_s, 1),
                             "loss": round(loss, 4)}
                log(f"T={T} {name}: {sec*1e3:.1f} ms/step "
                    f"(compile {compile_s:.1f}s, loss {loss:.3f})")
            except Exception as e:  # OOM at long T is itself a datum
                row[name] = {"error": str(e)[:200]}
                log(f"T={T} {name}: FAIL {str(e)[:120]}")
        if "step_ms" in row.get("dense", {}) \
                and "step_ms" in row.get("flash", {}):
            row["flash_speedup"] = round(
                row["dense"]["step_ms"] / row["flash"]["step_ms"], 2)
        results["cases"][f"T{T}"] = row

    with open("FLASH_TRAIN.json", "w") as f:
        json.dump(results, f, indent=1)
    speedups = [c.get("flash_speedup") for c in
                results["cases"].values() if c.get("flash_speedup")]
    print(json.dumps({
        "flash_train_ok": bool(speedups),
        "flash_speedup_range": [min(speedups), max(speedups)]
        if speedups else None,
        "platform": str(dev)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
