#!/bin/bash
# Round-5 stage 12 (opportunistic): if the tail watchdog (r5k)
# converted a relay recovery, spend any remaining window on the one
# ambiguous flash data point — the T=2048 training-step A/B read
# 1.04x (old 128x128 blocks), 0.68x (new (256,512) blocks), and the
# forward-only sweep 1.08x across three same-day samples (+/-30% relay
# variance), so a fourth sample decides whether the (256,512) default
# holds there. Runs the full flash_train_bench (fetch-synced).
#     nohup bash scripts/tpu_capture_r5l.sh > /tmp/tpu_capture_r5l.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5K_DONE=/tmp/tpu_capture_r5k.done
rm -f /tmp/tpu_capture_r5l.done
trap 'touch /tmp/tpu_capture_r5l.done' EXIT

wait_for_done "$R5K_DONE"
echo "[tpu_capture_r5l] watchdog done — probing"
if ! probe_relay 2; then
    echo "[tpu_capture_r5l] relay dead; no extra sample"
    exit 1
fi
FAILED=0
run python scripts/flash_train_bench.py    # -> FLASH_TRAIN.json (4th T=2048 sample)
# on-chip head-to-head at the closing head (round 2 measured 4-16x
# with per-round dispatch; the CPU re-run at the batched-scan engine
# reads 47-266x — this records the on-chip side of that update)
run python scripts/compare_reference.py --rounds 10 --tpu
echo "[tpu_capture_r5l] done (failed=$FAILED)"
exit $FAILED
