"""Quantify the cost of per-client weights in the federated hot loop.

Federated local training gives every online client its OWN parameters, so
the round program vmaps the train step over a [k] client axis of weights:
XLA lowers the convolutions with ``batch_group_count=k`` (grouped conv)
instead of one large dense conv. This script measures that penalty on the
current backend by timing a single fwd+bwd train step three ways on
identical total work (k*B images):

  shared   — one conv batch of k*B images, one weight set (the ceiling:
             what a non-federated data-parallel step would cost)
  vmapped  — vmap over k clients with k weight sets (the federated round's
             actual shape)
  scanned  — lax.scan over the k clients (serialized small batches)

The gap between `shared` and `vmapped` is the price of federated
semantics, not implementation slack; `scanned` shows the alternative the
engine rejected.  Writes VMAP_PENALTY.json.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig,
)
from fedtorch_tpu.models import define_model  # noqa: E402
from fedtorch_tpu.utils import enable_compile_cache  # noqa: E402

K_CLIENTS, BATCH = 10, 50
STEPS = 20


def build_model(dtype="bfloat16"):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="cifar10", batch_size=BATCH),
        federated=FederatedConfig(federated=True, num_clients=K_CLIENTS),
        model=ModelConfig(arch="resnet20"),
        optim=OptimConfig(lr=0.1),
        mesh=MeshConfig(compute_dtype=dtype),
    ).finalize()
    return define_model(cfg, batch_size=BATCH)


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(STEPS):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / STEPS


def main():
    model = build_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(K_CLIENTS, BATCH, 32, 32, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, (K_CLIENTS, BATCH)))
    params = model.init(jax.random.key(0))
    kparams = jax.vmap(lambda _: params)(jnp.arange(K_CLIENTS))

    def loss_fn(p, bx, by):
        logits = model.apply(p, bx)
        onehot = jax.nn.one_hot(by, logits.shape[-1])
        return -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * onehot, axis=-1))

    grad_step = jax.grad(loss_fn)

    @jax.jit
    def shared(p, bx, by):
        return grad_step(p, bx.reshape(-1, 32, 32, 3), by.reshape(-1))

    @jax.jit
    def vmapped(kp, bx, by):
        return jax.vmap(grad_step)(kp, bx, by)

    @jax.jit
    def scanned(kp, bx, by):
        def body(_, args):
            return None, grad_step(*args)
        return jax.lax.scan(body, None, (kp, bx, by))[1]

    devs = jax.devices()
    print(f"devices: {devs}", file=sys.stderr)
    out = {"platform": devs[0].device_kind,
           "config": {"clients": K_CLIENTS, "batch": BATCH,
                      "model": "resnet20", "dtype": "bfloat16"},
           "ms_per_step": {}}
    for name, fn, p in (("shared", shared, params),
                        ("vmapped", vmapped, kparams),
                        ("scanned", scanned, kparams)):
        dt = timeit(fn, p, x, y)
        out["ms_per_step"][name] = round(dt * 1e3, 2)
        print(f"{name:8s}: {dt*1e3:8.2f} ms for {K_CLIENTS}x{BATCH} "
              "images fwd+bwd", file=sys.stderr)
    out["vmap_penalty_x"] = round(
        out["ms_per_step"]["vmapped"] / out["ms_per_step"]["shared"], 2)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "VMAP_PENALTY.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), file=sys.stderr)


if __name__ == "__main__":
    from bench import probe_device  # patient, wedge-aware relay probe

    if not probe_device():
        print("TPU relay unavailable; aborting without a number "
              "(this micro-bench is only meaningful on the chip)",
              file=sys.stderr)
        sys.exit(1)
    enable_compile_cache()
    main()
