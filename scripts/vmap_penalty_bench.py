"""Quantify the cost of per-client weights in the federated hot loop.

Federated local training gives every online client its OWN parameters, so
the round program vmaps the train step over a [k] client axis of weights:
XLA lowers the convolutions with ``batch_group_count=k`` (grouped conv)
instead of one large dense conv. This script measures that penalty on the
current backend by timing a single fwd+bwd train step three ways on
identical total work (k*B images):

  shared   — one conv batch of k*B images, one weight set (the ceiling:
             what a non-federated data-parallel step would cost)
  vmapped  — vmap over k clients with k weight sets (the federated round's
             actual shape)
  scanned  — lax.scan over the k clients (serialized small batches)

The gap between `shared` and `vmapped` is the price of federated
semantics, not implementation slack; `scanned` shows the alternative the
engine rejected.

Second section (``conv_lowering``): per-stage micro A/B of HOW the
per-client conv lowers. vmap-of-conv with a [k] weight axis becomes a
``batch_group_count=k`` grouped convolution; the alternative
formulation extracts im2col patches and runs one batched matmul
``[k, B·P, 9C] x [k, 9C, F]`` — rows/cols the MXU tiles directly. If
the matmul form wins decisively on fwd+bwd, a model-level opt-in conv
path is the next MFU lever; if not, the grouped-conv lowering is
already fine and the MFU ceiling is the channel underfill documented
in docs/performance.md.  Writes VMAP_PENALTY.json.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig,
)
from fedtorch_tpu.models import define_model  # noqa: E402
from fedtorch_tpu.utils import enable_compile_cache  # noqa: E402

K_CLIENTS, BATCH = 10, 50
STEPS = 20


def build_model(dtype="bfloat16"):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="cifar10", batch_size=BATCH),
        federated=FederatedConfig(federated=True, num_clients=K_CLIENTS),
        model=ModelConfig(arch="resnet20"),
        optim=OptimConfig(lr=0.1),
        mesh=MeshConfig(compute_dtype=dtype),
    ).finalize()
    return define_model(cfg, batch_size=BATCH)


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(STEPS):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / STEPS


def conv_lowering_ab():
    """Per-resnet20-stage fwd+bwd timing: vmapped conv (grouped-conv
    lowering) vs im2col + batched matmul (same math, MXU-native
    shape). Patch extraction is charged to the matmul variant — it is
    part of that formulation's real cost."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(1)
    dt = jnp.bfloat16
    section = {}
    for cin, cout, hw in ((16, 16, 32), (32, 32, 16), (64, 64, 8)):
        x = jnp.asarray(rng.randn(K_CLIENTS, BATCH, hw, hw, cin), dt)
        w = jnp.asarray(rng.randn(K_CLIENTS, 3, 3, cin, cout) * 0.05,
                        dt)

        def conv_one(xi, wi):
            return lax.conv_general_dilated(
                xi, wi, window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        def loss_conv(w_):
            return jnp.sum(jax.vmap(conv_one)(x, w_) ** 2)

        def loss_matmul(w_):
            # [k, B, hw, hw, 9*cin] patches; charged to this variant
            patches = jax.vmap(lambda xi: lax.conv_general_dilated_patches(
                xi, (3, 3), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")))(x)
            p = patches.reshape(K_CLIENTS, BATCH * hw * hw, 9 * cin)
            # conv_general_dilated_patches orders features as
            # [cin, 3, 3]; permute the weights to match
            wm = w_.transpose(0, 3, 1, 2, 4).reshape(
                K_CLIENTS, cin * 9, cout)
            return jnp.sum(jnp.einsum("kpc,kcf->kpf", p, wm) ** 2)

        # numerics agreement guard (bf16 tolerance) before timing
        a = jax.jit(loss_conv)(w)
        b = jax.jit(loss_matmul)(w)
        rel = abs(float(a) - float(b)) / max(abs(float(a)), 1e-9)
        row = {"agree_rel_err": round(rel, 4)}
        if rel > 0.05:  # bf16 tolerance — ENFORCED, not just recorded
            row["invalid"] = ("formulations disagree; timing skipped "
                              "(patch ordering regression?)")
            print(f"conv_lowering {cin}->{cout}: DISAGREE rel={rel:.3f}"
                  " — skipping timings", file=sys.stderr)
            section[f"stage_{cin}x{cout}_{hw}px"] = row
            continue
        for name, fn in (("conv_vmap", loss_conv),
                         ("im2col_matmul", loss_matmul)):
            g = jax.jit(jax.grad(fn))
            dtms = timeit(g, w) * 1e3
            row[f"{name}_fwdbwd_ms"] = round(dtms, 3)
        row["matmul_speedup_x"] = round(
            row["conv_vmap_fwdbwd_ms"] / row["im2col_matmul_fwdbwd_ms"],
            2)
        section[f"stage_{cin}x{cout}_{hw}px"] = row
        print(f"conv_lowering {cin}->{cout} @{hw}px: conv "
              f"{row['conv_vmap_fwdbwd_ms']:.2f} ms vs matmul "
              f"{row['im2col_matmul_fwdbwd_ms']:.2f} ms "
              f"(x{row['matmul_speedup_x']}, rel err "
              f"{row['agree_rel_err']})", file=sys.stderr)
    return section


def main():
    model = build_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(K_CLIENTS, BATCH, 32, 32, 3),
                    jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, (K_CLIENTS, BATCH)))
    params = model.init(jax.random.key(0))
    kparams = jax.vmap(lambda _: params)(jnp.arange(K_CLIENTS))

    def loss_fn(p, bx, by):
        logits = model.apply(p, bx)
        onehot = jax.nn.one_hot(by, logits.shape[-1])
        return -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * onehot, axis=-1))

    grad_step = jax.grad(loss_fn)

    @jax.jit
    def shared(p, bx, by):
        return grad_step(p, bx.reshape(-1, 32, 32, 3), by.reshape(-1))

    @jax.jit
    def vmapped(kp, bx, by):
        return jax.vmap(grad_step)(kp, bx, by)

    @jax.jit
    def scanned(kp, bx, by):
        def body(_, args):
            return None, grad_step(*args)
        return jax.lax.scan(body, None, (kp, bx, by))[1]

    devs = jax.devices()
    print(f"devices: {devs}", file=sys.stderr)
    out = {"platform": devs[0].device_kind,
           "config": {"clients": K_CLIENTS, "batch": BATCH,
                      "model": "resnet20", "dtype": "bfloat16"},
           "ms_per_step": {}}
    for name, fn, p in (("shared", shared, params),
                        ("vmapped", vmapped, kparams),
                        ("scanned", scanned, kparams)):
        dt = timeit(fn, p, x, y)
        out["ms_per_step"][name] = round(dt * 1e3, 2)
        print(f"{name:8s}: {dt*1e3:8.2f} ms for {K_CLIENTS}x{BATCH} "
              "images fwd+bwd", file=sys.stderr)
    out["vmap_penalty_x"] = round(
        out["ms_per_step"]["vmapped"] / out["ms_per_step"]["shared"], 2)
    out["conv_lowering"] = conv_lowering_ab()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "VMAP_PENALTY.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), file=sys.stderr)


if __name__ == "__main__":
    from bench import probe_device  # patient, wedge-aware relay probe

    if not probe_device():
        print("TPU relay unavailable; aborting without a number "
              "(this micro-bench is only meaningful on the chip)",
              file=sys.stderr)
        sys.exit(1)
    enable_compile_cache()
    main()
