"""Measure gather_mode='batch' vs 'shard' on an actually-sharded mesh.

The 'batch' mode exists to bound cross-device data movement when a round
touches only K*B rows of a much larger client shard
(parallel/federated.py:104-121). On one device XLA fuses both modes into
local HBM gathers, so the win must be measured on a mesh where client
shards live on DIFFERENT devices and ``jnp.take(data.x, idx)`` crosses
them. This script times both modes on the virtual 8-device CPU mesh
(and on whatever real mesh is present if run under a TPU pod) with
K*B << shard size, and writes GATHER_MODE.json.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python scripts/gather_mode_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from fedtorch_tpu.utils import enable_compile_cache, \
    honor_platform_env  # noqa: E402

honor_platform_env()  # the site hook may pin jax_platforms to the proxy
enable_compile_cache()

from fedtorch_tpu.algorithms import make_algorithm  # noqa: E402
from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data.batching import stack_partitions  # noqa: E402
from fedtorch_tpu.models import define_model  # noqa: E402
# timed drains fetch-sync (block_until_ready can no-op on the
# relay — scripts/bench_timing.py / BASELINE_REPRO.md)
from fedtorch_tpu.utils.tracing import fetch_sync  # noqa: E402
from fedtorch_tpu.parallel import FederatedTrainer  # noqa: E402

# K*B = 160 rows touched per round vs 4000-row shards: 'batch' should
# move 4% of what 'shard' moves across devices.
NUM_CLIENTS, BATCH, K, SPC = 32, 16, 10, 4000
FEATURES = 784
ROUNDS = 20


def build(gather_mode: str):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist", batch_size=BATCH),
        federated=FederatedConfig(
            federated=True, num_clients=NUM_CLIENTS,
            online_client_rate=0.25, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch="mlp", mlp_num_layers=2,
                          mlp_hidden_size=256),
        optim=OptimConfig(lr=0.1),
        train=TrainConfig(local_step=K),
        mesh=MeshConfig(),
    ).finalize()
    rng = np.random.RandomState(0)
    feats = rng.randn(NUM_CLIENTS * SPC, FEATURES).astype(np.float32)
    labels = rng.randint(0, 10, NUM_CLIENTS * SPC)
    parts = [np.arange(i * SPC, (i + 1) * SPC)
             for i in range(NUM_CLIENTS)]
    data = stack_partitions(feats, labels, parts)
    model = define_model(cfg, batch_size=BATCH)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data,
                            gather_mode=gather_mode)


def timed(tr) -> tuple[float, float]:
    server, clients = tr.init_state(jax.random.key(0))
    server, clients, _ = tr.run_round(server, clients)
    fetch_sync(server.params)
    t0 = time.time()
    for _ in range(ROUNDS):
        server, clients, _ = tr.run_round(server, clients)
    fetch_sync(server.params)
    dt = (time.time() - t0) / ROUNDS
    loss = float(jax.device_get(
        tr.run_round(server, clients)[2].train_loss).sum())
    return dt, loss


def main():
    devs = jax.devices()
    print(f"devices: {len(devs)} x {devs[0].platform}", file=sys.stderr)
    out = {"platform": f"{len(devs)} x {devs[0].device_kind}",
           "config": {"clients": NUM_CLIENTS, "batch": BATCH, "K": K,
                      "shard_rows": SPC, "touched_rows": K * BATCH},
           "modes": {}}
    for mode in ("shard", "batch"):
        tr = build(mode)
        dt, loss = timed(tr)
        # bytes the data gather moves per round (host arithmetic, for the
        # artifact): k_online clients x rows x feature bytes
        rows = K * BATCH if mode == "batch" else SPC
        moved = tr.k_online * rows * FEATURES * 4
        out["modes"][mode] = {
            "ms_per_round": round(dt * 1e3, 2),
            "data_rows_gathered_per_client": rows,
            "data_mb_gathered_per_round": round(moved / 2**20, 2),
            "final_loss_sum": round(loss, 4),
        }
        print(f"{mode:6s}: {dt*1e3:8.2f} ms/round "
              f"({moved/2**20:.1f} MB data gathered)", file=sys.stderr)
    s, b = (out["modes"]["shard"]["ms_per_round"],
            out["modes"]["batch"]["ms_per_round"])
    out["speedup_batch_vs_shard"] = round(s / b, 2)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "GATHER_MODE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), file=sys.stderr)


if __name__ == "__main__":
    main()
