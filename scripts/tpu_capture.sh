#!/bin/bash
# CANONICAL parameterized TPU capture entry point.
#
#     bash scripts/tpu_capture.sh [step ...]
#
# Waits for the relay, then runs the named steps sequentially (one
# relay session, strictly serial — the single-session relay wedges
# under concurrent probes). With no arguments, runs the full default
# list. Steps:
#
#   bench            bench.py                     -> TPU_BENCH_CAPTURE.json
#   bench-unroll     BENCH_SCAN_UNROLL=4 bench.py (unroll A/B)
#   bench-dispatch   BENCH_SINGLE_DISPATCH=0      (dispatch A/B)
#   bench-streaming  BENCH_STREAMING=1 bench.py   (streaming-plane A/B
#                        side: host store + round-ahead prefetch)
#   stream           scripts/stream_bench.py      -> STREAM_AB.json
#                        (device vs stream wall-time + bytes moved +
#                         residency + retrace count on the real chip)
#   population       STREAM_BENCH_POPULATION=1 scripts/stream_bench.py
#                        -> MILLION_CLIENT_AB.json (million-client
#                         drill: C in {10^3,10^5,10^6} on the mmap
#                         store + sparse sampling — round wall flat in
#                         C, residency mapped-not-resident, bitwise
#                         mmap-vs-RAM parity, 0 retraces) + the
#                         artifacts/population_ab/{a,b} run dirs,
#                         gated by compare --gate
#                         tests/data/ops_runs/population_gates.json
#                         -> MILLION_CLIENT_COMPARE.json
#                         (docs/performance.md "The million-client
#                         store")
#   podscale         scripts/podscale_bench.py  -> PODSCALE_AB.json
#                        (shard sweep: rounds/sec + clients/sec vs
#                         mesh.client_shards, bitwise parity vs the
#                         1-shard twin, 0 retraces) + the
#                         artifacts/podscale_northstar run dir, gated
#                         against the previous window's rotated copy
#                         by tests/data/ops_runs/podscale_gates.json
#                         -> PODSCALE_COMPARE.json; regressed
#                         clients/sec exits nonzero
#                         (docs/performance.md "Pod-scale round
#                         programs")
#   async            scripts/async_bench.py       -> ASYNC_AB.json
#                        (sync round clock vs FedBuff-style commit
#                         clock under the straggler-heavy schedule +
#                         on-chip ms/commit + accuracy parity)
#   attack           scripts/chaos_suite.py --attack-matrix
#                        -> ATTACK_AB.json (byzantine attack x robust
#                         aggregator grid on the IID pool: 25%
#                         sign_flip must break plain mean by >5 pts
#                         while >=1 robust rule holds within 5 —
#                         docs/robustness.md threat-model table)
#   avail            scripts/chaos_suite.py --availability-matrix
#                        -> AVAIL_AB.json (deployment-realism drill:
#                         default-model arrivals bitwise vs the raw
#                         legacy straggler chain, armed trace-model
#                         lifecycle seeded-replayable + trace-once,
#                         sub-quorum degrade completes where abort
#                         escalates into the supervisor, async
#                         trace-model dropouts deterministic —
#                         docs/robustness.md "Deployment realism")
#   privacy          scripts/chaos_suite.py --privacy-matrix
#                        -> DP_AB.json (DP-FedAvg drill: DP-off leg
#                         HLO-byte-identical + bitwise replay, RDP
#                         accountant within 1% of the closed form,
#                         epsilon-vs-accuracy frontier at 3 budgets
#                         trace-once, DP x trimmed_mean x byzantine
#                         layering, both budget-exhaustion actions —
#                         docs/robustness.md "Privacy plane")
#   builder-matrix   scripts/chaos_suite.py --builder-matrix
#                        -> BUILDER_MATRIX.json (round-program-builder
#                         smoke: scanned device, scanned streamed and
#                         feed-commit cells under chaos + guards, each
#                         trace-once and bitwise vs its reference —
#                         docs/performance.md "Round-program builder")
#   host-chaos       scripts/chaos_suite.py --host-fault-matrix
#                        -> HOST_CHAOS_AB.json (host-plane fault
#                         drill: every HOST_FAULT_SEAMS seam injected
#                         at the default rate must complete with a
#                         bitwise-identical trajectory, fire its
#                         retry/degraded counters+events, and a dead
#                         stream producer must rebuild instead of
#                         aborting — docs/robustness.md "Host plane")
#   cohort           scripts/chaos_suite.py --ledger-attack
#                        -> COHORT_AB.json (ledger-separation drill:
#                         a real CLI run per robust rule with the
#                         byzantine cohort + --cohort_stats armed; the
#                         persisted client_ledger.json suspicion
#                         ranking must separate the adversarial cohort
#                         — precision/recall per rule;
#                         docs/observability.md "Federation plane")
#   telemetry        scripts/telemetry_bench.py   -> TELEMETRY_AB.json
#                        (off/default/debug overhead A/B on the
#                         north-star config, <=1% acceptance) +
#                         artifacts/telemetry_northstar/ metrics.jsonl
#                         + Perfetto trace.json capture
#   compare          fedtorch-tpu compare of the fresh
#                        artifacts/telemetry_northstar capture against
#                        the previous armed capture's rotated copy
#                        (artifacts/telemetry_northstar_prev), gated
#                        by tests/data/ops_runs/gates.json
#                        -> TELEMETRY_COMPARE.json; nonzero exit on a
#                         gated regression (docs/observability.md
#                         "Operating and comparing runs"). Always
#                         rotates the fresh capture into _prev for the
#                         next window; first window is baseline-only.
#   conv-ab          BENCH_CONV_IMPL=matmul|conv  (lowering A/B, both)
#   zoo              scripts/tpu_zoo_check.py     -> TPU_ZOO.json
#   pallas           scripts/pallas_tpu_check.py  -> PALLAS_TPU.json
#   flash-train      scripts/flash_train_bench.py -> FLASH_TRAIN.json
#   flash-sweep      scripts/flash_block_sweep.py -> FLASH_BLOCK_SWEEP.json
#   vmap             scripts/vmap_penalty_bench.py -> VMAP_PENALTY.json
#   mfu              MFU_PROFILE=1 scripts/mfu_sweep.py
#                        -> MFU_SWEEP.json (now incl. the client-fused
#                           configs) + artifacts/trace_northstar{,_fused}
#                           on-chip profiler traces, piped through
#                           tools/trace_attrib into
#                           artifacts/attrib_northstar{,_fused}.json/.txt
#                           (the device-time category table —
#                           docs/observability.md "Device-side")
#   moe              scripts/moe_ab_bench.py      -> MOE_AB.json
#   seqpar           scripts/seqpar_tpu_probe.py  -> SEQPAR_TPU_PROBE.json
#   baseline         scripts/baseline_suite.py    -> BASELINE_SUITE.json
#   curves           scripts/northstar_synthetic.py -> NORTHSTAR_CURVE_*.json
#   audit            python -m fedtorch_tpu.lint --audit
#                        -> PROGRAM_AUDIT.json (program-level FTP +
#                         registry FTC audit ON THE TPU BACKEND: every
#                         legal builder cell abstractly lowered and
#                         checked for f64/f32-in-bf16 promotion, host
#                         transfers, donation aliasing, collective
#                         budget, baked constants, peak-HBM watermark
#                         — the tier-1 CPU audit re-run against the
#                         real Mosaic/TPU lowering;
#                         docs/static_analysis.md "The program audit")
#   concurrency      python -m fedtorch_tpu.lint --concurrency
#                        -> CONCURRENCY_AUDIT.json (host-plane FTH
#                         lock/thread audit: lock-order cycles,
#                         emit-under-lock, unlocked thread-shared
#                         state, unbounded blocking, thread hygiene,
#                         non-atomic run-dir writes — stdlib-only,
#                         runs even when the relay's jax is wedged;
#                         docs/static_analysis.md "The concurrency
#                         audit")
#
# This supersedes the per-round stage chains (tpu_capture_full.sh,
# tpu_capture_r4*.sh, tpu_capture_r5*.sh) — kept for session history;
# see ARTIFACTS.md "Capture scripts". A/B variants are ordered before
# their defaults in the default list so the persisted default-config
# record is written LAST (the wedged-relay report fallback reads it).
#
# Run from the repo root, ideally in the background:
#     nohup bash scripts/tpu_capture.sh > /tmp/tpu_capture.log 2>&1 &
# The probe uses bench.probe_device (subprocess + SIGTERM-safe timeout);
# TPU_CAPTURE_WAIT_TRIES probes x 120 s (+120 s pauses) bound the wait.
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh

TRIES="${TPU_CAPTURE_WAIT_TRIES:-90}"   # ~6 h of patience by default

# mfu leads: round 6 is the utilization round — the fused-vs-base A/B
# and the first-ever on-chip traces are the highest-value capture if
# the relay wedges mid-list
# audit rides early: it is seconds of abstract lowering and proves the
# program invariants on the real backend before the long benches run
DEFAULT_STEPS="audit concurrency mfu stream population podscale \
builder-matrix avail \
privacy async attack host-chaos cohort telemetry compare bench-streaming \
bench-dispatch bench-unroll bench zoo pallas flash-train vmap baseline"
STEPS="${*:-$DEFAULT_STEPS}"

echo "[tpu_capture] waiting for the relay (up to ${TRIES}x120s probes)"
if ! probe_relay "$TRIES"; then
    echo "[tpu_capture] relay never recovered; nothing captured"
    exit 1
fi

echo "[tpu_capture] relay alive — capturing: $STEPS"
FAILED=0
for step in $STEPS; do
    case "$step" in
        bench)          run python bench.py ;;
        bench-unroll)   run env BENCH_SCAN_UNROLL=4 python bench.py ;;
        bench-dispatch) run env BENCH_SINGLE_DISPATCH=0 python bench.py ;;
        bench-streaming) run env BENCH_STREAMING=1 python bench.py ;;
        stream)         run python scripts/stream_bench.py ;;
        population)     run env STREAM_BENCH_POPULATION=1 \
                            python scripts/stream_bench.py
                        run python -m fedtorch_tpu.tools.compare \
                            artifacts/population_ab/a \
                            artifacts/population_ab/b \
                            --gate tests/data/ops_runs/population_gates.json \
                            --out MILLION_CLIENT_COMPARE.json ;;
        podscale)       # pod-scale shard sweep (ISSUE 20): rounds/sec
                        # + clients/sec vs client_shards, then gate the
                        # fresh largest-shard window against the
                        # previous one (same freshness-guard + rotate
                        # idiom as the telemetry compare step: a run
                        # dir not newer than _prev means the bench
                        # failed this window — skip the scaling gate
                        # rather than diff stale data against itself)
                        run python scripts/podscale_bench.py
                        if [ -d artifacts/podscale_northstar_prev ] \
                            && [ ! artifacts/podscale_northstar/metrics.jsonl \
                                 -nt artifacts/podscale_northstar_prev/metrics.jsonl ]; then
                            echo "[tpu_capture] podscale: capture is not" \
                                "newer than _prev (bench skipped/failed" \
                                "this window?) — skipping scaling gate"
                            FAILED=1
                        else
                            if [ -d artifacts/podscale_northstar_prev ]; then
                                run python -m fedtorch_tpu.tools.compare \
                                    artifacts/podscale_northstar_prev \
                                    artifacts/podscale_northstar \
                                    --gate tests/data/ops_runs/podscale_gates.json \
                                    --out PODSCALE_COMPARE.json
                            else
                                echo "[tpu_capture] podscale: no previous" \
                                    "capture — recording baseline only"
                            fi
                            if [ -d artifacts/podscale_northstar ]; then
                                rm -rf artifacts/podscale_northstar_prev
                                cp -r artifacts/podscale_northstar \
                                    artifacts/podscale_northstar_prev
                            fi
                        fi ;;
        async)          run python scripts/async_bench.py ;;
        attack)         run python scripts/chaos_suite.py \
                            --attack-matrix --rounds 25 \
                            --attack-out ATTACK_AB.json ;;
        builder-matrix) run python scripts/chaos_suite.py \
                            --builder-matrix --rounds 8 \
                            --builder-out BUILDER_MATRIX.json ;;
        avail)          run python scripts/chaos_suite.py \
                            --availability-matrix --rounds 12 \
                            --avail-out AVAIL_AB.json ;;
        privacy)        run python scripts/chaos_suite.py \
                            --privacy-matrix --rounds 12 \
                            --privacy-out DP_AB.json ;;
        host-chaos)     run python scripts/chaos_suite.py \
                            --host-fault-matrix --rounds 12 \
                            --host-out HOST_CHAOS_AB.json ;;
        cohort)         run python scripts/chaos_suite.py \
                            --ledger-attack --rounds 25 --seed 6 \
                            --ledger-out COHORT_AB.json ;;
        telemetry)      run python scripts/telemetry_bench.py \
                            --capture-run artifacts/telemetry_northstar ;;
        compare)        # regression-gate the fresh telemetry capture
                        # against the previous window's (rotated) one;
                        # stdlib-only, no relay round trip. Freshness
                        # guard: _prev is rotated (cp -r, mtimes reset
                        # to rotation time) AFTER each capture, so a
                        # capture that is not newer than _prev means
                        # the telemetry step did NOT run this window —
                        # comparing would diff stale data against its
                        # own copy and report a bogus green. Skip the
                        # compare AND the rotation in that case.
                        if [ -d artifacts/telemetry_northstar_prev ] \
                            && [ ! artifacts/telemetry_northstar/metrics.jsonl \
                                 -nt artifacts/telemetry_northstar_prev/metrics.jsonl ]; then
                            echo "[tpu_capture] compare: capture is not" \
                                "newer than _prev (telemetry step" \
                                "skipped/failed this window?) — skipping"
                            FAILED=1
                        else
                            if [ -d artifacts/telemetry_northstar_prev ]; then
                                run python -m fedtorch_tpu.tools.compare \
                                    artifacts/telemetry_northstar_prev \
                                    artifacts/telemetry_northstar \
                                    --gate tests/data/ops_runs/gates.json \
                                    --out TELEMETRY_COMPARE.json
                            else
                                echo "[tpu_capture] compare: no previous" \
                                    "capture — recording baseline only"
                            fi
                            if [ -d artifacts/telemetry_northstar ]; then
                                rm -rf artifacts/telemetry_northstar_prev
                                cp -r artifacts/telemetry_northstar \
                                    artifacts/telemetry_northstar_prev
                            fi
                        fi ;;
        conv-ab)        run env BENCH_CONV_IMPL=matmul python bench.py
                        run env BENCH_CONV_IMPL=conv python bench.py ;;
        zoo)            run python scripts/tpu_zoo_check.py ;;
        pallas)         run python scripts/pallas_tpu_check.py ;;
        flash-train)    run python scripts/flash_train_bench.py ;;
        flash-sweep)    run python scripts/flash_block_sweep.py ;;
        vmap)           run python scripts/vmap_penalty_bench.py ;;
        mfu)            run env MFU_PROFILE=1 python scripts/mfu_sweep.py
                        # pipe the armed on-chip traces straight through
                        # the attributor: the capture yields the
                        # category table without a second relay trip
                        run python -m fedtorch_tpu.tools.trace_attrib \
                            artifacts/trace_northstar \
                            --out artifacts/attrib_northstar.json \
                            --render artifacts/attrib_northstar.txt
                        run python -m fedtorch_tpu.tools.trace_attrib \
                            artifacts/trace_northstar_fused \
                            --out artifacts/attrib_northstar_fused.json \
                            --render artifacts/attrib_northstar_fused.txt ;;
        moe)            run python scripts/moe_ab_bench.py ;;
        seqpar)         run python scripts/seqpar_tpu_probe.py ;;
        baseline)       run python scripts/baseline_suite.py ;;
        curves)         run python scripts/northstar_synthetic.py ;;
        audit)          run python -m fedtorch_tpu.lint --audit \
                            --out PROGRAM_AUDIT.json ;;
        concurrency)    run python -m fedtorch_tpu.lint --concurrency \
                            --out CONCURRENCY_AUDIT.json ;;
        *) echo "[tpu_capture] unknown step: $step"; FAILED=1 ;;
    esac
done
echo "[tpu_capture] done (failed=$FAILED)"
exit $FAILED
