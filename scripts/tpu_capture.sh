#!/bin/bash
# Wait for the TPU relay to recover, then capture the full measurement
# list sequentially (each script writes its own artifact). Run from the
# repo root, ideally in the background:
#     nohup bash scripts/tpu_capture.sh > /tmp/tpu_capture.log 2>&1 &
# The probe uses bench.probe_device (subprocess + SIGTERM-safe timeout);
# TPU_CAPTURE_WAIT_TRIES probes x 120 s (+120 s pauses) bound the wait.
set -u
cd "$(dirname "$0")/.." || exit 1

TRIES="${TPU_CAPTURE_WAIT_TRIES:-90}"   # ~6 h of patience by default

echo "[tpu_capture] waiting for the relay (up to ${TRIES}x120s probes)"
BENCH_PROBE_TRIES="$TRIES" python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture] relay never recovered; nothing captured"
    exit 1
fi

echo "[tpu_capture] relay alive — capturing (each step sequential)"
FAILED=0
run() {
    echo "=== $* ==="
    # probes are already done; don't let per-script probes re-wait long
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

run python bench.py
run env BENCH_SCAN_UNROLL=4 python bench.py      # unroll A/B
run python scripts/tpu_zoo_check.py              # -> TPU_ZOO.json
run python scripts/vmap_penalty_bench.py         # -> VMAP_PENALTY.json
run python scripts/baseline_suite.py             # -> BASELINE_SUITE.json
echo "[tpu_capture] done (failed=$FAILED)"
exit $FAILED
