"""Head-to-head baseline reproduction vs the reference (BASELINE.md
procedure: reproduce the reference run configs numerically, then compare
wall-clock).

Runs the reference's OWN centered-mode implementation (torch, from
/root/reference, with minimal torch-2.x compatibility shims) and
fedtorch_tpu with the matched configuration on the IDENTICAL dataset (the
reference's generated synthetic shards are loaded directly), then prints
an accuracy/wall-clock table.

Usage:  python scripts/compare_reference.py [--rounds 10] [--algos ...]
Needs /root/reference mounted; runs offline (synthetic data only).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"
WORKDIR = "/tmp/fedtorch_compare"
OUT_JSON = os.path.join(REPO, "COMPARE_REFERENCE.json")

COMPARE_SCHEMA = "fedtorch_tpu.compare_reference/v1"
# the head-to-head acceptance band: ours must land within this many
# accuracy points of the reference on the SAME data + config (the
# BASELINE.md reproduction bar)
ACC_TOLERANCE_PTS = 5.0


def build_payload(rows: dict, rounds: int) -> dict:
    """The machine-checkable head-to-head record (VERDICT item 8):
    per-algorithm ``{ref_acc, ours_acc, ref_wall, ours_wall,
    speedup}`` — accuracies are final TEST top-1 in percent on the
    identical reference-generated shards, walls are seconds for the
    same number of rounds."""
    return {
        "schema": COMPARE_SCHEMA,
        "rounds": rounds,
        "acc_tolerance_pts": ACC_TOLERANCE_PTS,
        "algorithms": rows,
    }


def validate_payload(payload: dict) -> None:
    """Raise ``ValueError`` on schema violations or an accuracy delta
    outside the tolerance band — the test's entry point, so the claim
    "head-to-head parity" stays machine-checkable instead of a table
    in a log."""
    if payload.get("schema") != COMPARE_SCHEMA:
        raise ValueError(
            f"schema {payload.get('schema')!r} != {COMPARE_SCHEMA!r}")
    algos = payload.get("algorithms")
    if not isinstance(algos, dict) or not algos:
        raise ValueError("payload carries no per-algorithm rows")
    tol = float(payload.get("acc_tolerance_pts", ACC_TOLERANCE_PTS))
    for name, row in algos.items():
        for key in ("ref_acc", "ours_acc", "ref_wall", "ours_wall",
                    "speedup"):
            v = row.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(
                    f"{name}: field {key!r} must be numeric, got {v!r}")
        if row["ref_wall"] <= 0 or row["ours_wall"] <= 0:
            raise ValueError(f"{name}: non-positive wall time")
        expect = row["ref_wall"] / row["ours_wall"]
        if abs(row["speedup"] - expect) > 1e-6 * max(expect, 1.0):
            raise ValueError(
                f"{name}: speedup {row['speedup']} != ref_wall/"
                f"ours_wall ({expect})")
        delta = abs(row["ref_acc"] - row["ours_acc"])
        if delta > tol:
            raise ValueError(
                f"{name}: |ref_acc - ours_acc| = {delta:.2f}pts "
                f"exceeds the {tol}pt tolerance")


def install_reference_shims():
    """Make the torch-1.6-era reference run under torch 2.x on one core."""
    for name in ("torchvision", "torchvision.datasets",
                 "torchvision.transforms"):
        sys.modules.setdefault(name, types.ModuleType(name))
    sys.modules["torchvision"].datasets = sys.modules[
        "torchvision.datasets"]
    sys.modules["torchvision"].transforms = sys.modules[
        "torchvision.transforms"]
    sys.path.insert(0, REF)

    import torch
    import torch.utils.data as tud

    class _DL(tud.DataLoader):  # single-process loaders on a 1-core host
        def __init__(self, *a, **kw):
            kw["num_workers"] = 0
            kw["pin_memory"] = False
            super().__init__(*a, **kw)

    tud.DataLoader = _DL
    torch.utils.data.DataLoader = _DL
    # torch>=2 zero_grad defaults to set_to_none=True; the reference
    # mutates .grad.data in place and needs zeroed tensors
    _zero = torch.optim.Optimizer.zero_grad
    torch.optim.Optimizer.zero_grad = \
        lambda self, set_to_none=False: _zero(self, set_to_none=False)

    # .view on non-contiguous slices + formatting 1-elem tensors
    import fedtorch.components.metrics as M

    def _accuracy(output, target, topk=(1,), rnn=False):
        if rnn:
            output = output.permute(0, 2, 1).reshape(-1, output.size(1))
            target = target.reshape(-1)
        maxk = max(topk)
        batch_size = target.size(0)
        _, pred = output.topk(maxk, 1, True, True)
        pred = pred.t()
        correct = pred.eq(target.view(1, -1).expand_as(pred))
        return [correct[:k].contiguous().reshape(-1).float().sum(0)
                .mul_(100.0 / batch_size) for k in topk]

    M.accuracy = _accuracy

    # The reference's centered main CALLS qffl_aggregation_centered
    # (centered/main.py:206) but never imports it (main.py:18-22 pulls
    # only fedavg/fedgate/scaffold/qsparse) — its own qFFL entry path
    # crashes with NameError. Inject the function it meant to import
    # (defined at comms/algorithms/federated/centered/qffl.py:4) so
    # the comparison can still run the reference as intended.
    import fedtorch.comms.trainings.federated.centered.main as ref_main_mod
    if not hasattr(ref_main_mod, "qffl_aggregation_centered"):
        from fedtorch.comms.algorithms.federated.centered.qffl import \
            qffl_aggregation_centered
        ref_main_mod.qffl_aggregation_centered = qffl_aggregation_centered


def reference_argv(algo: str, rounds: int, extra=()):
    argv = [
        "main_centered.py", "--federated", "True",
        "--federated_type", algo if algo != "drfa" else "fedavg",
        "--data", "synthetic", "--data_dir", f"{WORKDIR}/data",
        "--num_comms", str(rounds), "--online_client_rate", "1.0",
        "--federated_sync_type", "local_step", "--local_step", "5",
        "--arch", "logistic_regression", "--lr", "0.1",
        "--batch_size", "20", "--weight_decay", "0.0001",
        "--iid_data", "False", "--num_workers", "4",
        "--on_cuda", "False", "--debug", "True",
        "--lr_schedule_scheme", "custom_multistep",
        "--checkpoint", f"{WORKDIR}/ckpt",
        "--is_distributed", "False", "--blocks", "4",
        "--manual_seed", "6",
    ]
    if algo == "drfa":
        argv += ["--federated_drfa", "True", "--drfa_gamma", "0.1"]
    if algo == "apfl":
        argv += ["--fed_personal", "True", "--fed_personal_alpha", "0.5"]
    if algo in ("perfedavg", "perfedme"):
        argv += ["--fed_personal", "True"]
    return argv + list(extra)


def run_reference(algo: str, rounds: int):
    import contextlib
    install_reference_shims()
    # the reference's synthetic generator ignores its own seed param and
    # draws from the GLOBAL numpy RNG (federated_datasets.py:204-212);
    # seed it so the generated shards are reproducible & non-degenerate
    import numpy as np
    np.random.seed(20260728)
    sys.argv = reference_argv(algo, rounds)
    from fedtorch.parameters import get_args
    args = get_args()
    from main_centered import main as ref_main
    t0 = time.time()
    with open(f"{WORKDIR}/ref_{algo}.log", "w") as f, \
            contextlib.redirect_stdout(f):
        ref_main(args)
    wall = time.time() - t0
    return wall


def load_reference_data():
    import numpy as np
    import torch
    base = f"{WORKDIR}/data/synthetic/synthetic0.0-0.0"
    cx, cy = [], []
    i = 0
    while os.path.exists(f"{base}/Client_{i}.pt"):
        x, y = torch.load(f"{base}/Client_{i}.pt")
        cx.append(np.asarray(x))
        cy.append(np.asarray(y))
        i += 1
    tx, ty = torch.load(f"{base}/Test.pt")
    return cx, cy, np.asarray(tx), np.asarray(ty)


def run_ours(algo: str, rounds: int, cx, cy, tx, ty,
             use_tpu: bool = False):
    import jax
    if not use_tpu:
        # force cpu WITHOUT calling jax.default_backend() — merely probing
        # the default backend would initialize the (possibly wedged) TPU
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import numpy as np
    import jax.numpy as jnp
    sys.path.insert(0, REPO)
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
        OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate

    sizes = [len(y) for y in cy]
    feats, labels = np.concatenate(cx), np.concatenate(cy)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    parts = [np.arange(offs[i], offs[i + 1]) for i in range(len(sizes))]
    val_data = None
    if algo in ("perfedavg", "perfedme"):
        # MAML-style algorithms evaluate on per-client validation
        # batches (needs_val_batch); same 10% split convention as
        # build_federated_data / the reference's random_split
        from fedtorch_tpu.data.batching import train_val_split
        parts, val_parts = train_val_split(parts, 0.1, seed=6)
        val_data = stack_partitions(feats, labels, val_parts)
    data = stack_partitions(feats, labels, parts)

    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=feats.shape[1],
                        batch_size=20),
        federated=FederatedConfig(
            federated=True, num_clients=len(sizes), num_comms=rounds,
            online_client_rate=1.0,
            algorithm=algo if algo != "drfa" else "fedavg",
            drfa=(algo == "drfa"), sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=1e-4),
        train=TrainConfig(local_step=5),
    ).finalize()
    model = define_model(cfg, batch_size=20)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data,
                               val_data=val_data)
    server, clients = trainer.init_state(jax.random.key(6))
    # compile warmup — TWO rounds, because algorithms with round-0
    # forcing (afl: uniform round 0, lambda-weighted afterwards) jit
    # two distinct round programs; a 1-round warmup left the second
    # compile inside the timed loop (measured: afl rounds 0 AND 1
    # each ~2.3s, rounds 2+ ~1ms)
    s, c, _ = trainer.run_round(server, clients)
    s, c, _ = trainer.run_round(s, c)
    # drain warmup / close the timed segment with a fetch-sync:
    # jax.block_until_ready can no-op on the relay backend, which
    # inflates the speedup by timing dispatch instead of execution
    # (scripts/bench_timing.py, round-5 methodology finding)
    from bench_timing import sync as bench_sync
    bench_sync(s.params)
    server, clients = trainer.init_state(jax.random.key(6))
    t0 = time.time()
    for _ in range(rounds):
        server, clients, _ = trainer.run_round(server, clients)
    bench_sync(server.params)
    wall = time.time() - t0
    tr = evaluate(model, server.params, feats, labels, batch_size=200)
    te = evaluate(model, server.params, tx, ty, batch_size=200)
    return wall, float(tr.top1) * 100, float(te.top1) * 100


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--algos", nargs="+",
                    default=["fedavg", "scaffold", "fedgate"])
    ap.add_argument("--tpu", action="store_true",
                    help="run ours on the default (TPU) platform")
    args = ap.parse_args()
    os.makedirs(WORKDIR, exist_ok=True)

    def ref_final_metrics(algo):
        import re
        last = {}
        with open(f"{WORKDIR}/ref_{algo}.log") as f:
            for line in f:
                m = re.search(
                    r"(Global performance for train"
                    r"|Global performance for validation|Test)"
                    r" at batch.*Prec@1: ([\d.]+).*Loss: ([\d.]+)",
                    line)
                if m:
                    # personal-eval paths (apfl) log the held-out
                    # metric as "Global performance for validation"
                    # instead of a "Test" line
                    key = "train" if "train" in m.group(1) else "test"
                    last[key] = float(m.group(2))
        return last

    print(f"{'algo':<10} {'ref wall':>9} {'ours wall':>10} {'speedup':>8} "
          f"{'ref tr/te%':>12} {'ours tr/te%':>12}")
    rows = {}
    for algo in args.algos:
        ref_wall = run_reference(algo, args.rounds)
        refm = ref_final_metrics(algo)
        if not refm:
            raise RuntimeError(
                f"reference run for {algo!r} produced no parseable "
                f"metrics — inspect {WORKDIR}/ref_{algo}.log")
        cx, cy, tx, ty = load_reference_data()
        ours_wall, tr, te = run_ours(algo, args.rounds, cx, cy, tx, ty,
                                     use_tpu=args.tpu)
        speedup = ref_wall / max(ours_wall, 1e-9)
        print(f"{algo:<10} {ref_wall:>8.2f}s {ours_wall:>9.2f}s "
              f"{speedup:>7.1f}x "
              f"{refm.get('train', 0):>5.1f}/{refm.get('test', 0):<5.1f} "
              f"{tr:>5.1f}/{te:<5.1f}")
        # some reference eval paths only log a train/validation metric
        # (apfl) — fall back so the row stays comparable like-for-like
        ref_acc = refm.get("test", refm.get("train", 0.0))
        ours_acc = te if "test" in refm else tr
        rows[algo] = {
            "ref_acc": ref_acc, "ours_acc": ours_acc,
            "ref_wall": ref_wall, "ours_wall": ours_wall,
            "speedup": speedup,
            "ref_train_acc": refm.get("train"),
            "ours_train_acc": tr, "ours_test_acc": te,
        }

    payload = build_payload(rows, args.rounds)
    validate_payload(payload)  # fail HERE, not in a later test run
    with open(OUT_JSON, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    print(f"wrote {OUT_JSON}")


if __name__ == "__main__":
    main()
