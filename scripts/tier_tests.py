"""Regenerate tests/slow_tests.txt from a pytest --durations=0 log.

The suite is tiered (VERDICT r3 #5): tests whose measured call time is
>= THRESHOLD seconds on the 1-core reference box are auto-marked
``slow`` by the conftest hook, giving CI a fast default lane
(``pytest -m "not slow"``) while ``pytest tests/`` still runs
everything. Regenerate after a significant suite change:

    python -m pytest tests/ -q --durations=0 > /tmp/durations.log
    python scripts/tier_tests.py /tmp/durations.log
"""
from __future__ import annotations

import os
import re
import sys

THRESHOLD_S = 3.1

_LINE = re.compile(r"^(\d+\.\d+)s call\s+(\S+)")


def main(log_path: str) -> int:
    rows = []
    with open(log_path) as f:
        for line in f:
            m = _LINE.match(line.strip())
            if m and float(m.group(1)) >= THRESHOLD_S:
                rows.append((float(m.group(1)), m.group(2)))
    if not rows:
        print("no slow tests found — is this a --durations=0 log?",
              file=sys.stderr)
        return 1
    rows.sort(reverse=True)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "slow_tests.txt")
    with open(out, "w") as f:
        f.write(
            "# Auto-marked `slow` by tests/conftest.py (nodeids whose\n"
            f"# measured call time was >= {THRESHOLD_S}s on the 1-core\n"
            "# reference box). Regenerate: see scripts/tier_tests.py.\n")
        for dur, nodeid in rows:
            f.write(f"{nodeid}  # {dur:.1f}s\n")
    print(f"wrote {out}: {len(rows)} slow tests "
          f"(sum {sum(d for d, _ in rows):.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
