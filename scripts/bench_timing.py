"""Fetch-synced device timing for the measurement scripts.

`jax.block_until_ready` can no-op on the axon relay backend: round-5
block-synced timers read 24-44us for computations whose MXU FLOPs
floor is ~350us (FLASH_BLOCK_SWEEP.json first two captures;
BASELINE_REPRO.md "timing-methodology finding"). Materializing result
BYTES on the host provably waits for the in-order device stream, so
every micro-benchmark syncs by fetching one element of its final
output. Import from here — a copy-pasted variant that drifts back to
block_until_ready silently resumes reading artifact timings.

The implementation lives in ``fedtorch_tpu.utils.tracing.fetch_sync``
(one copy — the profiler trace hook drains through the same rule);
this module stays the scripts-facing import surface.
"""
from __future__ import annotations

import time

from fedtorch_tpu.utils.tracing import fetch_sync as sync  # noqa: F401


def timeit(fn, *args, iters: int = 20) -> float:
    """Mean seconds per call over `iters` dispatches, fetch-synced."""
    sync(fn(*args))  # warmup/compile, fully drained
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters
