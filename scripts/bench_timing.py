"""Fetch-synced device timing for the measurement scripts.

`jax.block_until_ready` can no-op on the axon relay backend: round-5
block-synced timers read 24-44us for computations whose MXU FLOPs
floor is ~350us (FLASH_BLOCK_SWEEP.json first two captures;
BASELINE_REPRO.md "timing-methodology finding"). Materializing result
BYTES on the host provably waits for the in-order device stream, so
every micro-benchmark syncs by fetching one element of its final
output. Import from here — a copy-pasted variant that drifts back to
block_until_ready silently resumes reading artifact timings.

The implementation lives in ``fedtorch_tpu.utils.tracing.fetch_sync``
(one copy — the profiler trace hook drains through the same rule);
this module stays the scripts-facing import surface.
"""
from __future__ import annotations

import time

from fedtorch_tpu.utils.tracing import fetch_sync as sync  # noqa: F401


def timeit(fn, *args, iters: int = 20, sync_each: bool = False) -> float:
    """Mean seconds per call over `iters` dispatches, fetch-synced.

    Default mode queues all `iters` dispatches and drains ONCE at the
    end — the steady-state number (per-call dispatch overhead hides
    behind device compute), resting on the assumption that the device
    executes the queued calls in order and the final fetch therefore
    waits for all of them.

    ``sync_each=True`` is the opt-in cross-check mode (ADVICE round-5):
    every iteration drains through a fetch before the next dispatch.
    It reads strictly slower (per-call transfer latency lands on the
    clock), but it cannot be fooled by a backend that reorders,
    coalesces, or drops queued work — see :func:`timeit_crosscheck`.
    """
    sync(fn(*args))  # warmup/compile, fully drained
    if sync_each:
        t0 = time.perf_counter()
        for _ in range(iters):
            sync(fn(*args))
        return (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters


def timeit_crosscheck(fn, *args, iters: int = 20,
                      suspect_ratio: float = 3.0) -> dict:
    """Validate a queued-mode reading against the per-iteration-sync
    mode (the queued-in-order assumption check, ADVICE round-5).

    Physics bounds the honest relationship: ``synced`` >= ``queued``
    (it adds a round-trip per call) but by roughly the fetch latency,
    not by orders of magnitude. ``synced / queued > suspect_ratio``
    flags a SUSPICIOUS queued reading — the signature of a backend
    that acknowledged dispatches without executing them (the
    block_until_ready no-op failure mode), where queued mode times
    dispatch and only the cross-check pays for real execution. Callers
    seeing ``suspicious=True`` should report ``synced_s`` (an upper
    bound) and distrust the artifact's queued numbers."""
    queued = timeit(fn, *args, iters=iters)
    synced = timeit(fn, *args, iters=iters, sync_each=True)
    ratio = synced / queued if queued > 0 else float("inf")
    return {
        "queued_s": queued,
        "synced_s": synced,
        "sync_overhead_ratio": ratio,
        "suspect_ratio": suspect_ratio,
        "suspicious": ratio > suspect_ratio,
    }
