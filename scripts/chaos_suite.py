"""Chaos suite: short FedAvg + SCAFFOLD synthetic jobs under a fault
schedule, asserting the faulted run stays within an accuracy tolerance
of the fault-free run (ISSUE 1 acceptance: drop_rate=0.25 must complete
every round host-exception-free with final top-1 within 5 points).

Each algorithm trains twice from the same seed — once fault-free, once
under the chaos schedule (client crashes + stragglers + NaN-poisoned
uploads with the update guards on, all deterministic under the threaded
PRNG) — and the gap in final test accuracy is checked against the
tolerance. The supervisor wraps the faulted run, so a diverged round
would roll back instead of killing the job.

Registered as a `slow`-marked pytest (tests/test_chaos_suite.py) so the
tier-1 fast lane stays fast. Standalone usage:

    python scripts/chaos_suite.py [--rounds N] [--smoke] [--tol PTS]
    python scripts/chaos_suite.py --attack-matrix   # -> ATTACK_AB.json

`--attack-matrix` (ISSUE 9) runs the byzantine attack x robust
aggregator grid: each cell trains under an adversary schedule
(`fault.byzantine_*`) with one `--robust_agg` rule and is scored
against the fault-free baseline. Plain `mean` is the NEGATIVE CONTROL:
the acceptance bar requires the attack to break it (> tol points lost
under 25% sign_flip) while at least one robust rule holds within tol —
proving both that the attack bites and that the defense works.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def straggler_heavy_fault() -> dict:
    """The straggler-heavy chaos schedule (FaultConfig kwargs): a
    long-tail delay distribution — 40% of dispatches land in a 10x
    tail. Under the SYNC planes these knobs cut straggler step budgets
    (the deadline model); under the async commit plane the SAME knobs
    draw the event scheduler's completion delays, so one preset drives
    both sides of the async A/B (scripts/async_bench.py reuses it as
    its chaos schedule). Returned as kwargs so importers can compose
    it into a FaultConfig with guards/crashes of their own."""
    return {"straggler_rate": 0.4, "straggler_step_frac": 0.1}


def run_suite(rounds: int = 20, smoke: bool = False, tol_points: float = 5.0,
              algorithms=("fedavg", "scaffold"), seed: int = 0,
              straggler_heavy: bool = False) -> dict:
    """Returns the suite report; raises AssertionError on a tolerance
    breach (the pytest wrapper surfaces it directly).

    ``straggler_heavy=True`` switches the drill: instead of fault-free
    vs chaos on the SYNC plane, each algorithm runs sync vs ASYNC
    (``sync_mode='async'``) under the :func:`straggler_heavy_fault`
    schedule — the ISSUE 6 convergence bar (async within ``tol_points``
    of sync while its commit program traces exactly once)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate
    from fedtorch_tpu.robustness import RoundSupervisor
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    C = 8 if smoke else 16
    B = 16 if smoke else 32
    K = 3 if smoke else 5
    rounds = max(rounds, 4)
    # async needs num_clients >= concurrency + buffer so every arrival
    # has a distinct replacement; half-rate participation keeps the
    # smoke shapes legal while leaving the sync leg a real cohort
    online_rate = 0.5 if straggler_heavy else 1.0

    fault_schedule = FaultConfig(
        client_drop_rate=0.25, straggler_rate=0.25,
        straggler_step_frac=0.5, nan_inject_rate=0.1,
        guard_updates=True, max_retries=2, backoff_base_s=0.0)
    if straggler_heavy:
        fault_schedule = FaultConfig(**straggler_heavy_fault())

    def one_run(algorithm: str, fault: FaultConfig,
                sync_mode: str = "sync", num_comms: int = None):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B, synthetic_alpha=0.5,
                            synthetic_beta=0.5),
            federated=FederatedConfig(
                federated=True, num_clients=C,
                num_comms=num_comms or rounds,
                online_client_rate=online_rate, algorithm=algorithm,
                sync_type="local_step", sync_mode=sync_mode),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=K),
            fault=fault,
        ).finalize()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=B)
        if sync_mode == "async":
            from fedtorch_tpu.async_plane import AsyncFederatedTrainer
            trainer = AsyncFederatedTrainer(cfg, model,
                                            make_algorithm(cfg),
                                            data.train)
        else:
            trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                       data.train)
        server, clients = trainer.init_state(jax.random.key(seed))
        sup = RoundSupervisor(trainer, sleep_fn=lambda s: None)
        counters = {"dropped": 0.0, "stragglers": 0.0, "rejected": 0.0,
                    "retraces": 0}
        # first round/commit pays the (expected) trace; the sentinel
        # then proves the program re-traces ZERO times — the async
        # commit program is trace-once like every other plane

        def count(m):
            counters["dropped"] += float(m.dropped_clients)
            counters["stragglers"] += float(m.straggler_clients)
            counters["rejected"] += float(m.rejected_updates)

        server, clients, m = sup.run_round(server, clients)
        count(m)
        with RecompilationSentinel() as sentinel:
            for _ in range(cfg.federated.num_comms - 1):
                server, clients, m = sup.run_round(server, clients)
                count(m)
        counters["retraces"] = sum(sentinel.counts.values())
        trainer.invalidate_stream()
        assert all(bool(jnp.all(jnp.isfinite(x)))
                   for x in jax.tree.leaves(server.params)), \
            f"{algorithm}: non-finite server params survived the guards"
        res = evaluate(model, server.params, data.test_x, data.test_y)
        return float(res.top1), counters, sup.stats

    report = {"rounds": rounds, "clients": C, "tol_points": tol_points,
              "fault": straggler_heavy_fault() if straggler_heavy else
              {"client_drop_rate": 0.25, "straggler_rate": 0.25,
               "nan_inject_rate": 0.1, "guard": "reject"},
              "mode": "straggler_heavy_sync_vs_async"
              if straggler_heavy else "clean_vs_chaos",
              "algorithms": {}}
    t0 = time.time()
    for algorithm in algorithms:
        if straggler_heavy:
            # the async convergence bar: sync vs async under the same
            # long-tail schedule, equal CLIENT-UPDATE budget (R sync
            # rounds aggregate k updates each; the async buffer holds
            # m = k // 2, so it commits twice as often)
            sync_acc, _, _ = one_run(algorithm, fault_schedule, "sync")
            k = max(int(online_rate * C), 1)
            commits = rounds * k // max(k // 2, 1)
            async_acc, counters, stats = one_run(
                algorithm, fault_schedule, "async", num_comms=commits)
            gap = (sync_acc - async_acc) * 100.0
            entry = {
                "sync_top1": round(sync_acc, 4),
                "async_top1": round(async_acc, 4),
                "gap_points": round(gap, 2),
                "async_commits": commits,
                "async_stragglers": int(counters["stragglers"]),
                "commit_retraces": counters["retraces"],
            }
            report["algorithms"][algorithm] = entry
            log(f"{algorithm}: sync {sync_acc:.4f} async {async_acc:.4f}"
                f" gap {gap:+.2f}pts over {commits} commits "
                f"({entry['async_stragglers']} stragglers)")
            assert counters["stragglers"] > 0, \
                f"{algorithm}: straggler-heavy schedule delayed nothing"
            assert counters["retraces"] == 0, (
                f"{algorithm}: async commit program retraced "
                f"{counters['retraces']}x mid-run (trace-once bar)")
            assert gap <= tol_points, (
                f"{algorithm}: async lost {gap:.2f} accuracy points vs "
                f"sync (tolerance {tol_points}); ISSUE 6 regression")
            continue
        clean_acc, _, _ = one_run(algorithm, FaultConfig())
        chaos_acc, counters, stats = one_run(algorithm, fault_schedule)
        gap = (clean_acc - chaos_acc) * 100.0
        entry = {
            "clean_top1": round(clean_acc, 4),
            "chaos_top1": round(chaos_acc, 4),
            "gap_points": round(gap, 2),
            "faults_injected": {k: int(v) for k, v in counters.items()
                                if k != "retraces"},
            "supervisor": {"rollbacks": stats.rollbacks,
                           "skipped_rounds": stats.skipped_rounds},
        }
        report["algorithms"][algorithm] = entry
        log(f"{algorithm}: clean {clean_acc:.4f} chaos {chaos_acc:.4f} "
            f"gap {gap:+.2f}pts faults {entry['faults_injected']}")
        assert counters["dropped"] > 0, \
            f"{algorithm}: chaos schedule injected no crashes"
        assert counters["rejected"] > 0, \
            f"{algorithm}: guards rejected nothing despite NaN injection"
        assert gap <= tol_points, (
            f"{algorithm}: chaos run lost {gap:.2f} accuracy points "
            f"(tolerance {tol_points}); robustness regression")
    report["wall_seconds"] = round(time.time() - t0, 1)
    return report


# the full rule surface IS the matrix's aggregator axis — importing
# the stdlib-only config tuple keeps the two from drifting when a new
# rule lands ('mean' first = the negative control)
from fedtorch_tpu.config import ROBUST_AGGREGATORS as ATTACK_AGGREGATORS  # noqa: E402,E501

ATTACK_MODES = ("sign_flip", "collude", "gauss")


def run_attack_matrix(rounds: int = 20, smoke: bool = False,
                      tol_points: float = 5.0, seed: int = 0,
                      algorithm: str = "fedavg",
                      modes=None, aggregators=None,
                      byzantine_rate: float = 0.25,
                      byzantine_scale: float = 3.0,
                      out_path: str = None) -> dict:
    """The byzantine attack x robust-aggregator matrix (ISSUE 9).

    Every armed cell keeps the update GUARDS ON — the point of the
    byzantine threat model is that these attacks pass the benign-fault
    screen (a sign-flipped delta at scale 3 sits at 3x the median norm,
    under the 10x guard threshold), so the robust rule is the only
    defense actually being exercised. ``robust_trim_frac`` is set to
    the armed byzantine rate + margin: trimming/krum must budget for at
    least the adversarial fraction they face.

    Acceptance (the sign_flip row): plain ``mean`` must lose MORE than
    ``tol_points`` accuracy vs fault-free (the attack bites) while at
    least one robust aggregator stays within ``tol_points``.

    DATA: an IID partition of one pooled task mixture — NOT the
    per-client LEAF generator the fault suite uses. The LEAF-style
    generator draws each client's own feature means and label model at
    unit scale even at alpha=beta=0, so its clients are intrinsically
    heterogeneous (measured: honest full-batch client updates have
    cos ~0.35 to their mean), and coordinate-median/krum are BIASED
    estimators under heterogeneity with zero adversaries present
    (median plateaued 11 pts below mean on it, attack-free). The
    robust-aggregation literature states its guarantees under bounded
    heterogeneity; pooling ``C`` generator tasks and partitioning the
    shuffled pool IID isolates the axis this matrix actually measures
    — byzantine corruption — while the mixture keeps the task
    non-trivial.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.data.synthetic import generate_synthetic
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    modes = tuple(modes) if modes else (
        ("sign_flip",) if smoke else ATTACK_MODES)
    aggregators = tuple(aggregators) if aggregators else (
        ("mean", "median", "krum") if smoke else ATTACK_AGGREGATORS)
    C = 8 if smoke else 16
    B = 32 if smoke else 64
    K = 2
    rounds = max(rounds, 8)

    # IID pool: C generator tasks concatenated, shuffled, split evenly
    syn = generate_synthetic(num_tasks=C, alpha=0.0, beta=0.0,
                             num_dim=30, num_classes=2)
    x = np.concatenate(syn.client_x)
    y = np.concatenate(syn.client_y)
    perm = np.random.RandomState(seed).permutation(len(x))
    x, y = x[perm], y[perm]
    n = (len(x) // C) * C
    parts = [np.arange(i * (n // C), (i + 1) * (n // C))
             for i in range(C)]
    data = stack_partitions(x[:n], y[:n], parts)

    def one_run(fault: FaultConfig):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B),
            federated=FederatedConfig(
                federated=True, num_clients=C, num_comms=rounds,
                online_client_rate=1.0, algorithm=algorithm,
                sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=K),
            fault=fault,
        ).finalize()
        model = define_model(cfg, batch_size=B)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data)
        server, clients = trainer.init_state(jax.random.key(seed))
        counters = {"byzantine": 0.0, "rejected": 0.0, "selected": 0.0,
                    "trimmed": 0.0, "retraces": 0}

        def count(m):
            # one batched fetch per round (lint FTL001)
            byz, rej, sel, trm = jax.device_get(
                (m.byzantine_clients, m.rejected_updates,
                 m.robust_selected, m.robust_trimmed))
            counters["byzantine"] += float(byz)
            counters["rejected"] += float(rej)
            counters["selected"] += float(sel)
            counters["trimmed"] += float(trm)

        # round 0 pays the (expected) trace but its faults still count
        server, clients, m = trainer.run_round(server, clients)
        count(m)
        with RecompilationSentinel() as sentinel:
            for _ in range(rounds - 1):
                server, clients, m = trainer.run_round(server, clients)
                count(m)
        counters["retraces"] = sum(sentinel.counts.values())
        # one transfer for the whole EvalResult pytree (lint FTL001)
        res = jax.device_get(evaluate(model, server.params, syn.test_x,
                                      syn.test_y))
        return float(res.top1), counters

    trim = min(byzantine_rate + 0.1, 0.45)
    clean_acc, _ = one_run(FaultConfig(guard_updates=True))
    report = {
        "algorithm": algorithm, "rounds": rounds, "clients": C,
        "tol_points": tol_points, "clean_top1": round(clean_acc, 4),
        "byzantine_rate": byzantine_rate,
        "byzantine_scale": byzantine_scale,
        "robust_trim_frac": trim, "guards": "on (10x median, reject)",
        "matrix": {},
    }
    t0 = time.time()
    for mode in modes:
        row = {}
        for agg in aggregators:
            fault = FaultConfig(
                byzantine_rate=byzantine_rate, byzantine_mode=mode,
                byzantine_scale=byzantine_scale, guard_updates=True,
                robust_agg=agg, robust_trim_frac=trim)
            acc, counters = one_run(fault)
            gap = (clean_acc - acc) * 100.0
            row[agg] = {
                "top1": round(acc, 4), "gap_points": round(gap, 2),
                "byzantine_injected": int(counters["byzantine"]),
                "guard_rejected": int(counters["rejected"]),
                "robust_trimmed": int(counters["trimmed"]),
                "retraces": counters["retraces"],
            }
            log(f"attack {mode} x {agg}: top1 {acc:.4f} "
                f"(gap {gap:+.2f}pts, "
                f"{int(counters['byzantine'])} byz injected, "
                f"{int(counters['rejected'])} guard-rejected, "
                f"{counters['retraces']} retraces)")
            assert counters["byzantine"] > 0, \
                f"{mode} x {agg}: attack schedule injected nothing"
            assert counters["retraces"] == 0, (
                f"{mode} x {agg}: robust aggregator retraced "
                f"{counters['retraces']}x mid-run (trace-once bar)")
        report["matrix"][mode] = row

    report["wall_seconds"] = round(time.time() - t0, 1)

    # the acceptance bar rides the sign_flip row when armed
    if "sign_flip" in report["matrix"] and "mean" in aggregators:
        row = report["matrix"]["sign_flip"]
        mean_gap = row["mean"]["gap_points"]
        robust_gaps = {a: c["gap_points"] for a, c in row.items()
                       if a != "mean"}
        best = min(robust_gaps, key=robust_gaps.get)
        report["acceptance"] = {
            "mean_gap_points": mean_gap,
            "best_robust": best,
            "best_robust_gap_points": robust_gaps[best],
            "attack_bites": mean_gap > tol_points,
            "defense_holds": robust_gaps[best] <= tol_points,
        }
        log(f"attack matrix: mean gap {mean_gap:+.2f}pts (must exceed "
            f"{tol_points}); best robust {best} "
            f"{robust_gaps[best]:+.2f}pts (must be within)")
        assert mean_gap > tol_points, (
            f"negative control failed: 25% sign_flip cost plain mean "
            f"only {mean_gap:.2f}pts (<= {tol_points}) — the attack "
            "does not bite, so the matrix proves nothing")
        assert robust_gaps[best] <= tol_points, (
            f"no robust aggregator held: best ({best}) lost "
            f"{robust_gaps[best]:.2f}pts (> {tol_points})")

    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"attack matrix written to {out_path}")
    return report


def run_kill_drill(rounds: int = 150, ckpt_root: str = None) -> dict:
    """Process-lifecycle chaos (ISSUE 4): SIGTERM the REAL CLI mid-run,
    assert it drains and exits 75, then let the ElasticRunner harness
    relaunch it with --resume and finish the job. The bitwise
    trajectory-identity half of this drill lives in
    tests/test_kill_drill.py; this entry checks the operator-facing
    lifecycle end to end (drain -> restartable exit -> relaunch ->
    completion) against the production entry point."""
    import signal
    import subprocess
    import tempfile
    import threading

    from fedtorch_tpu.robustness.harness import (
        ElasticRunner, read_checkpoint_round,
    )

    run_dir = os.path.join(ckpt_root or tempfile.mkdtemp(), "run")
    cmd = [sys.executable, "-m", "fedtorch_tpu.cli",
           "--federated", "true", "-d", "synthetic", "-a",
           "logistic_regression", "--num_comms", str(rounds),
           "--num_workers", "8", "--online_client_rate", "0.5",
           "--federated_sync_type", "local_step", "--local_step", "2",
           "--batch_size", "8", "--lr", "0.1", "--eval_freq", "1",
           "--debug", "false", "--run_dir", run_dir]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    state = {"killed": False}

    def popen(c, **kw):
        proc = subprocess.Popen(c, env=env, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        if not state["killed"]:
            # watch checkpoint.json; SIGTERM once the run is mid-flight
            def killer():
                while proc.poll() is None:
                    r = read_checkpoint_round(run_dir)
                    if r is not None and r >= 3:
                        state["killed"] = True
                        try:
                            proc.send_signal(signal.SIGTERM)
                        except OSError:  # raced to exit
                            pass
                        return
                    time.sleep(0.02)

            threading.Thread(target=killer, daemon=True).start()
        return proc

    runner = ElasticRunner(cmd, ckpt_dir=run_dir, max_restarts=3,
                           backoff_base_s=0.1, popen=popen, log_fn=log)
    t0 = time.time()
    rc = runner.run()
    final_round = read_checkpoint_round(run_dir)
    assert state["killed"], \
        "kill drill never landed its SIGTERM (job finished too fast — " \
        "raise rounds)"
    assert rc == 0, f"relaunched job did not complete cleanly (rc={rc})"
    assert runner.launches >= 2, \
        "child was killed but the harness never relaunched it"
    assert final_round == rounds, \
        f"resumed job stopped at round {final_round}, wanted {rounds}"
    report = {"rounds": rounds, "launches": runner.launches,
              "final_round": final_round,
              "wall_seconds": round(time.time() - t0, 1)}
    log(f"kill drill: {report}")
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI")
    ap.add_argument("--tol", type=float, default=5.0,
                    help="max accuracy-point gap vs the fault-free run")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill-drill", action="store_true",
                    help="also run the process-lifecycle kill drill "
                         "(SIGTERM -> exit 75 -> relaunch -> complete)")
    ap.add_argument("--straggler-heavy", action="store_true",
                    help="long-tail delay preset: compare SYNC vs "
                         "ASYNC (sync_mode='async') under the "
                         "straggler-heavy schedule instead of clean "
                         "vs chaos (the ISSUE 6 convergence bar)")
    ap.add_argument("--attack-matrix", action="store_true",
                    help="run the byzantine attack x robust-aggregator "
                         "grid instead of the fault suite (plain mean "
                         "as the negative control) and write "
                         "--attack-out")
    ap.add_argument("--attack-out", default="ATTACK_AB.json",
                    help="output path for the attack-matrix report")
    args = ap.parse_args()
    if args.attack_matrix:
        report = run_attack_matrix(rounds=args.rounds, smoke=args.smoke,
                                   tol_points=args.tol, seed=args.seed,
                                   out_path=args.attack_out)
        print(json.dumps(report), flush=True)
        return
    report = run_suite(rounds=args.rounds, smoke=args.smoke,
                       tol_points=args.tol, seed=args.seed,
                       straggler_heavy=args.straggler_heavy)
    if args.kill_drill:
        report["kill_drill"] = run_kill_drill(
            rounds=60 if args.smoke else 150)
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
