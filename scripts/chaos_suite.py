"""Chaos suite: short FedAvg + SCAFFOLD synthetic jobs under a fault
schedule, asserting the faulted run stays within an accuracy tolerance
of the fault-free run (ISSUE 1 acceptance: drop_rate=0.25 must complete
every round host-exception-free with final top-1 within 5 points).

Each algorithm trains twice from the same seed — once fault-free, once
under the chaos schedule (client crashes + stragglers + NaN-poisoned
uploads with the update guards on, all deterministic under the threaded
PRNG) — and the gap in final test accuracy is checked against the
tolerance. The supervisor wraps the faulted run, so a diverged round
would roll back instead of killing the job.

Registered as a `slow`-marked pytest (tests/test_chaos_suite.py) so the
tier-1 fast lane stays fast. Standalone usage:

    python scripts/chaos_suite.py [--rounds N] [--smoke] [--tol PTS]
    python scripts/chaos_suite.py --attack-matrix   # -> ATTACK_AB.json

`--attack-matrix` (ISSUE 9) runs the byzantine attack x robust
aggregator grid: each cell trains under an adversary schedule
(`fault.byzantine_*`) with one `--robust_agg` rule and is scored
against the fault-free baseline. Plain `mean` is the NEGATIVE CONTROL:
the acceptance bar requires the attack to break it (> tol points lost
under 25% sign_flip) while at least one robust rule holds within tol —
proving both that the attack bites and that the defense works.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def straggler_heavy_fault() -> dict:
    """The straggler-heavy chaos schedule (FaultConfig kwargs): a
    long-tail delay distribution — 40% of dispatches land in a 10x
    tail. Under the SYNC planes these knobs cut straggler step budgets
    (the deadline model); under the async commit plane the SAME knobs
    draw the event scheduler's completion delays, so one preset drives
    both sides of the async A/B (scripts/async_bench.py reuses it as
    its chaos schedule). Returned as kwargs so importers can compose
    it into a FaultConfig with guards/crashes of their own."""
    return {"straggler_rate": 0.4, "straggler_step_frac": 0.1}


def run_suite(rounds: int = 20, smoke: bool = False, tol_points: float = 5.0,
              algorithms=("fedavg", "scaffold"), seed: int = 0,
              straggler_heavy: bool = False) -> dict:
    """Returns the suite report; raises AssertionError on a tolerance
    breach (the pytest wrapper surfaces it directly).

    ``straggler_heavy=True`` switches the drill: instead of fault-free
    vs chaos on the SYNC plane, each algorithm runs sync vs ASYNC
    (``sync_mode='async'``) under the :func:`straggler_heavy_fault`
    schedule — the ISSUE 6 convergence bar (async within ``tol_points``
    of sync while its commit program traces exactly once)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate
    from fedtorch_tpu.robustness import RoundSupervisor
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    C = 8 if smoke else 16
    B = 16 if smoke else 32
    K = 3 if smoke else 5
    rounds = max(rounds, 4)
    # async needs num_clients >= concurrency + buffer so every arrival
    # has a distinct replacement; half-rate participation keeps the
    # smoke shapes legal while leaving the sync leg a real cohort
    online_rate = 0.5 if straggler_heavy else 1.0

    fault_schedule = FaultConfig(
        client_drop_rate=0.25, straggler_rate=0.25,
        straggler_step_frac=0.5, nan_inject_rate=0.1,
        guard_updates=True, max_retries=2, backoff_base_s=0.0)
    if straggler_heavy:
        fault_schedule = FaultConfig(**straggler_heavy_fault())

    def one_run(algorithm: str, fault: FaultConfig,
                sync_mode: str = "sync", num_comms: int = None):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B, synthetic_alpha=0.5,
                            synthetic_beta=0.5),
            federated=FederatedConfig(
                federated=True, num_clients=C,
                num_comms=num_comms or rounds,
                online_client_rate=online_rate, algorithm=algorithm,
                sync_type="local_step", sync_mode=sync_mode),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=K),
            fault=fault,
        ).finalize()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=B)
        if sync_mode == "async":
            from fedtorch_tpu.async_plane import AsyncFederatedTrainer
            trainer = AsyncFederatedTrainer(cfg, model,
                                            make_algorithm(cfg),
                                            data.train)
        else:
            trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                       data.train)
        server, clients = trainer.init_state(jax.random.key(seed))
        sup = RoundSupervisor(trainer, sleep_fn=lambda s: None)
        counters = {"dropped": 0.0, "stragglers": 0.0, "rejected": 0.0,
                    "retraces": 0}
        # first round/commit pays the (expected) trace; the sentinel
        # then proves the program re-traces ZERO times — the async
        # commit program is trace-once like every other plane

        def count(m):
            counters["dropped"] += float(m.dropped_clients)
            counters["stragglers"] += float(m.straggler_clients)
            counters["rejected"] += float(m.rejected_updates)

        server, clients, m = sup.run_round(server, clients)
        count(m)
        with RecompilationSentinel() as sentinel:
            for _ in range(cfg.federated.num_comms - 1):
                server, clients, m = sup.run_round(server, clients)
                count(m)
        counters["retraces"] = sum(sentinel.counts.values())
        trainer.invalidate_stream()
        assert all(bool(jnp.all(jnp.isfinite(x)))
                   for x in jax.tree.leaves(server.params)), \
            f"{algorithm}: non-finite server params survived the guards"
        res = evaluate(model, server.params, data.test_x, data.test_y)
        return float(res.top1), counters, sup.stats

    report = {"rounds": rounds, "clients": C, "tol_points": tol_points,
              "fault": straggler_heavy_fault() if straggler_heavy else
              {"client_drop_rate": 0.25, "straggler_rate": 0.25,
               "nan_inject_rate": 0.1, "guard": "reject"},
              "mode": "straggler_heavy_sync_vs_async"
              if straggler_heavy else "clean_vs_chaos",
              "algorithms": {}}
    t0 = time.time()
    for algorithm in algorithms:
        if straggler_heavy:
            # the async convergence bar: sync vs async under the same
            # long-tail schedule, equal CLIENT-UPDATE budget (R sync
            # rounds aggregate k updates each; the async buffer holds
            # m = k // 2, so it commits twice as often)
            sync_acc, _, _ = one_run(algorithm, fault_schedule, "sync")
            k = max(int(online_rate * C), 1)
            commits = rounds * k // max(k // 2, 1)
            async_acc, counters, stats = one_run(
                algorithm, fault_schedule, "async", num_comms=commits)
            gap = (sync_acc - async_acc) * 100.0
            entry = {
                "sync_top1": round(sync_acc, 4),
                "async_top1": round(async_acc, 4),
                "gap_points": round(gap, 2),
                "async_commits": commits,
                "async_stragglers": int(counters["stragglers"]),
                "commit_retraces": counters["retraces"],
            }
            report["algorithms"][algorithm] = entry
            log(f"{algorithm}: sync {sync_acc:.4f} async {async_acc:.4f}"
                f" gap {gap:+.2f}pts over {commits} commits "
                f"({entry['async_stragglers']} stragglers)")
            assert counters["stragglers"] > 0, \
                f"{algorithm}: straggler-heavy schedule delayed nothing"
            assert counters["retraces"] == 0, (
                f"{algorithm}: async commit program retraced "
                f"{counters['retraces']}x mid-run (trace-once bar)")
            assert gap <= tol_points, (
                f"{algorithm}: async lost {gap:.2f} accuracy points vs "
                f"sync (tolerance {tol_points}); ISSUE 6 regression")
            continue
        clean_acc, _, _ = one_run(algorithm, FaultConfig())
        chaos_acc, counters, stats = one_run(algorithm, fault_schedule)
        gap = (clean_acc - chaos_acc) * 100.0
        entry = {
            "clean_top1": round(clean_acc, 4),
            "chaos_top1": round(chaos_acc, 4),
            "gap_points": round(gap, 2),
            "faults_injected": {k: int(v) for k, v in counters.items()
                                if k != "retraces"},
            "supervisor": {"rollbacks": stats.rollbacks,
                           "skipped_rounds": stats.skipped_rounds},
        }
        report["algorithms"][algorithm] = entry
        log(f"{algorithm}: clean {clean_acc:.4f} chaos {chaos_acc:.4f} "
            f"gap {gap:+.2f}pts faults {entry['faults_injected']}")
        assert counters["dropped"] > 0, \
            f"{algorithm}: chaos schedule injected no crashes"
        assert counters["rejected"] > 0, \
            f"{algorithm}: guards rejected nothing despite NaN injection"
        assert gap <= tol_points, (
            f"{algorithm}: chaos run lost {gap:.2f} accuracy points "
            f"(tolerance {tol_points}); robustness regression")
    report["wall_seconds"] = round(time.time() - t0, 1)
    return report


def run_availability_matrix(rounds: int = 12, smoke: bool = False,
                            seed: int = 0, out_path: str = None) -> dict:
    """The deployment-realism drill (docs/robustness.md §7) →
    AVAIL_AB.json. Four legs:

    * ``default_bitwise`` — the async scheduler under the ``default``
      availability model must reproduce the RAW legacy straggler-knob
      fold chain bitwise (recomputed inline here, independently of
      `robustness/availability.py`), so the model refactor cannot have
      moved a single draw.
    * ``trace_replay`` — the armed sync lifecycle (trace model,
      over-selection, deadline, quorum) run twice from one seed:
      per-round server-param sha256 fingerprints identical, lifecycle
      counters active, round program traced exactly once.
    * ``degrade_vs_abort`` — at 95% dropout under a 0.9 quorum the
      ``degrade`` action completes EVERY round (degraded, never
      wedged — a naive deadline abort would stall the run), while the
      ``abort`` action escalates into the supervisor's reseeded
      retry → skip-with-cause path.
    * ``async_dropout`` — the async commit loop under trace-model
      dropouts: arrivals discarded + re-dispatched, commit sequence
      deterministic under replay.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import hashlib

    import jax
    import numpy as np

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    from fedtorch_tpu.robustness import RoundSupervisor
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    C = 8 if smoke else 16
    B = 16 if smoke else 32
    rounds = max(rounds, 6)
    t0 = time.time()
    report = {"rounds": rounds, "clients": C, "seed": seed, "legs": {}}

    def fingerprint(tree) -> str:
        h = hashlib.sha256()
        for leaf in jax.tree.leaves(tree):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()[:16]

    def make_cfg(fault: FaultConfig, sync_mode: str = "sync",
                 num_comms: int = None):
        return ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B, synthetic_alpha=0.5,
                            synthetic_beta=0.5),
            federated=FederatedConfig(
                federated=True, num_clients=C,
                num_comms=num_comms or rounds,
                online_client_rate=0.5, algorithm="fedavg",
                sync_type="local_step", sync_mode=sync_mode),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=3),
            fault=fault,
        ).finalize()

    # -- leg 1: default model bitwise vs the raw legacy fold chain ------
    from fedtorch_tpu.async_plane.scheduler import AsyncSchedule
    from fedtorch_tpu.robustness.availability import LEGACY_DELAY_SALT

    rate, frac = 0.4, 0.1
    # lint: disable=FTL001 — offline harness setup, raw key bytes
    kd = np.asarray(jax.random.key_data(jax.random.key(seed)))
    impl = jax.random.key_impl(jax.random.key(seed))

    def make_sched():
        return AsyncSchedule(kd, impl, num_clients=C, concurrency=4,
                             buffer_size=2, ring_size=4,
                             straggler_rate=rate,
                             straggler_step_frac=frac)

    sched = make_sched()
    # dispatch 0's delay sits in the event heap as its finish time
    # (dispatched at now=0), before any commit pops it
    d0 = next(t for t, did, *_ in sched._heap if did == 0)
    # recompute it by hand off the RAW legacy chain — u = uniform(
    # fold(fold(key, SALT), dispatch_id), (2,)) on the cpu backend,
    # then the historical host-f64 tail math
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        k = jax.random.fold_in(jax.random.key(seed), LEGACY_DELAY_SALT)
        # lint: disable=FTL001 — the sync IS the measurement here
        u = np.asarray(jax.random.uniform(jax.random.fold_in(k, 0),
                                          (2,)), np.float64)
    base = 1.0 + 0.25 * u[1]
    want = base * (1.0 / frac) if u[0] < rate else base

    def commit_seq(s):
        return [(cm.commit, cm.idx.tolist(), cm.version.tolist(),
                 cm.arrival_times.tolist()) for cm in
                (s.next_commit() for _ in range(6))]

    seq = commit_seq(sched)
    seq2 = commit_seq(make_sched())
    # lint: disable=FTL001 — report scalars for the JSON artifact
    want_f, d0_f = float(want), float(d0)
    report["legs"]["default_bitwise"] = {
        "legacy_d0_recomputed": want_f,
        "scheduler_d0": d0_f,
        "d0_bitwise_match": want_f == d0_f,
        "replay_identical": seq == seq2,
        "commit_sequence_len": len(seq),
    }
    assert d0 == want, (
        f"default model moved the legacy delay chain: scheduler drew "
        f"{d0!r}, raw fold chain gives {want!r}")
    assert seq == seq2, "default-model commit sequence not replayable"

    # -- leg 2: armed sync lifecycle, bitwise replay + trace-once -------
    armed = FaultConfig(avail_model="trace", avail_dropout_rate=0.3,
                        avail_diurnal_period=8, over_select_frac=1.5,
                        avail_quorum_frac=0.5)

    def sync_run(fault, supervise=False, causes=None):
        cfg = make_cfg(fault)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=B)
        t = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
        server, clients = t.init_state(jax.random.key(seed))
        run = t.run_round
        sup = None
        if supervise:
            sup = RoundSupervisor(
                t, sleep_fn=lambda s: None,
                on_round_skipped=(lambda r, c: causes.append(c))
                if causes is not None else None)
            run = sup.run_round
        fps, counters = [], {"avail_dropped": 0.0, "deadline_missed": 0.0,
                             "quorum_degraded": 0.0}
        server, clients, m = run(server, clients)
        with RecompilationSentinel() as sentinel:
            for _ in range(cfg.federated.num_comms - 1):
                server, clients, m = run(server, clients)
                for key_ in counters:
                    counters[key_] += float(getattr(m, key_))
                fps.append(fingerprint(server.params))
        retraces = sum(sentinel.counts.values())
        return fps, counters, retraces, sup, server

    fps_a, counters, retraces, _, _ = sync_run(armed)
    fps_b, _, _, _, _ = sync_run(armed)
    report["legs"]["trace_replay"] = {
        "fingerprints_identical": fps_a == fps_b,
        "avail_dropped": int(counters["avail_dropped"]),
        "deadline_missed": int(counters["deadline_missed"]),
        "retraces": retraces,
    }
    assert fps_a == fps_b, \
        "armed trace-model trajectories not seeded-replayable"
    assert counters["avail_dropped"] + counters["deadline_missed"] > 0, \
        "armed lifecycle injected nothing"
    assert retraces == 0, (
        f"armed round program retraced {retraces}x — over-selection/"
        "deadline masking broke trace-once")

    # -- leg 3: sub-quorum degrade completes; abort escalates -----------
    heavy = dict(avail_model="trace", avail_dropout_rate=0.95,
                 avail_diurnal_period=4, over_select_frac=1.5,
                 avail_quorum_frac=0.9)
    _, deg_counters, _, _, deg_server = sync_run(FaultConfig(**heavy))
    deg_rounds = int(jax.device_get(deg_server.round))
    causes = []
    _, _, _, sup, ab_server = sync_run(
        FaultConfig(supervisor=True, max_retries=1, backoff_base_s=0.0,
                    avail_quorum_action="abort", **heavy),
        supervise=True, causes=causes)
    ab_rounds = int(jax.device_get(ab_server.round))
    report["legs"]["degrade_vs_abort"] = {
        "degrade_rounds_completed": deg_rounds,
        "degraded_rounds": int(deg_counters["quorum_degraded"]),
        "abort_rounds_completed": ab_rounds,
        "abort_skipped_quorum": sup.stats.skipped_quorum,
        "abort_skip_causes": sorted(set(causes)),
    }
    assert deg_rounds == rounds, (
        f"degrade leg wedged at round {deg_rounds}/{rounds} — "
        "sub-quorum rounds must complete degraded")
    assert deg_counters["quorum_degraded"] > 0, \
        "degrade leg never went sub-quorum at 95% dropout"
    assert ab_rounds == rounds, "abort leg wedged the round counter"
    assert sup.stats.skipped_quorum > 0 and causes, \
        "abort leg never escalated a sub-quorum round"
    assert set(causes) == {"quorum"}, f"unexpected skip causes {causes}"

    # -- leg 4: async trace-model dropouts, deterministic ---------------
    def async_run():
        cfg = make_cfg(FaultConfig(avail_model="trace",
                                   avail_dropout_rate=0.3,
                                   **straggler_heavy_fault()),
                       sync_mode="async", num_comms=rounds)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=B)
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer
        t = AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                  data.train)
        server, clients = t.init_state(jax.random.key(seed))
        for _ in range(rounds):
            server, clients, m = t.run_round(server, clients)
        st = t.schedule_stats  # grab before invalidate clears the sim
        t.invalidate_stream()
        return fingerprint(server.params), st

    fp1, st1 = async_run()
    fp2, st2 = async_run()
    report["legs"]["async_dropout"] = {
        "fingerprint_identical": fp1 == fp2,
        "dropouts": st1.dropouts,
    }
    assert fp1 == fp2, "async trace-model run not seeded-replayable"
    assert st1.dropouts > 0, "async availability model dropped nothing"
    assert st1.dropouts == st2.dropouts, \
        "async dropout count not deterministic"

    report["wall_seconds"] = round(time.time() - t0, 1)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        log(f"wrote {out_path}")
    return report


def run_privacy_matrix(rounds: int = 12, smoke: bool = False,
                       seed: int = 0, out_path: str = None) -> dict:
    """The privacy-plane drill (docs/robustness.md §8) → DP_AB.json.
    Five legs:

    * ``off_identical`` — the DP-off build is the pre-PR program:
      lowered round HLO byte-identical across disarmed DP knob
      settings, server.aux unwrapped, no dp_* metrics fields, and the
      off trajectory bitwise-replayable.
    * ``closed_form_control`` — the stdlib RDP accountant within 1%
      of the continuous closed-form ε on the pure-Gaussian
      no-subsampling control, and subsampling strictly amplifies.
    * ``frontier`` — the measured ε-vs-accuracy frontier at
      ε ∈ {2, 8, ∞} (δ fixed): noise calibrated by bisection against
      the accountant itself, every armed cell bitwise-replayable and
      traced exactly once, spend within budget.
    * ``layered`` — DP × trimmed_mean × byzantine cohort: the layered
      defense completes every round with finite params while both the
      robust rule and the clip+noise stage fire.
    * ``exhaustion`` — both budget actions drilled through the real
      CLI loop: ``stop`` ends at the last affordable round with a
      `complete` intent + `privacy.budget_exhausted` event; `degrade`
      finishes every round noise-free with a `degraded` intent.
      Neither wedges.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import hashlib
    import shutil
    import tempfile

    import jax
    import numpy as np

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
        FederatedConfig, ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    from fedtorch_tpu.robustness.privacy import (
        PrivacyAccountant, calibrate_noise_multiplier,
        closed_form_epsilon,
    )
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    C = 8 if smoke else 16
    B = 16 if smoke else 32
    rounds = max(rounds, 6)
    delta = 1e-5
    t0 = time.time()
    report = {"rounds": rounds, "clients": C, "seed": seed,
              "delta": delta, "legs": {}}

    def fingerprint(tree) -> str:
        h = hashlib.sha256()
        for leaf in jax.tree.leaves(tree):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()[:16]

    def make_cfg(fault: FaultConfig, num_comms: int = None,
                 run_dir: str = None):
        return ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B, synthetic_alpha=0.5,
                            synthetic_beta=0.5),
            federated=FederatedConfig(
                federated=True, num_clients=C,
                num_comms=num_comms or rounds,
                online_client_rate=0.5, algorithm="fedavg",
                sync_type="local_step", sync_mode="sync"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=3),
            checkpoint=CheckpointConfig(run_dir=run_dir, debug=False)
            if run_dir else CheckpointConfig(),
            fault=fault,
        ).finalize()

    def make_trainer(fault: FaultConfig):
        cfg = make_cfg(fault)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=B)
        return FederatedTrainer(cfg, model, make_algorithm(cfg),
                                data.train)

    def dp_run(fault: FaultConfig):
        """rounds sync rounds; per-round fingerprints + tail accuracy
        + dp gauges, trace count."""
        t = make_trainer(fault)
        server, clients = t.init_state(jax.random.key(seed))
        fps, accs, gauges = [], [], {}
        totals = {"byzantine": 0.0, "robust_trimmed": 0.0}
        with RecompilationSentinel() as sentinel:
            for _ in range(rounds):
                server, clients, m = t.run_round(server, clients)
                sc = t.round_host_scalars(clients, m)
                accs.append(sc["acc_sum"] / max(sc["n_online"], 1.0))
                fps.append(fingerprint(server.params))
                for key_ in totals:
                    totals[key_] += sc[key_]
                gauges = {k: sc[k] for k in
                          ("dp_clipped_frac", "dp_noise_sigma")
                          if k in sc}
        return (fps, sum(accs[-3:]) / 3, gauges,
                sum(sentinel.counts.values()), server, m, totals)

    # -- leg 1: DP off IS the pre-PR program ----------------------------
    def lowered(fault: FaultConfig) -> str:
        t = make_trainer(fault)
        server, clients = t.init_state(jax.random.key(seed))
        return t._round_jit.lower(server, clients, t.data,
                                  t.val_data).as_text()

    hlo_plain = lowered(FaultConfig())
    # disarmed DP knobs at non-default values must not reach the
    # lowered program (static-config contract)
    hlo_disarmed = lowered(FaultConfig(dp_noise_multiplier=0.0,
                                       dp_clip_norm=9.0, dp_delta=0.5,
                                       dp_budget_action="degrade"))
    t_off = make_trainer(FaultConfig())
    s_off, _ = t_off.init_state(jax.random.key(seed))
    fps_off, acc_off, g_off, tr_off, _, m_off, _ = dp_run(FaultConfig())
    fps_off2 = dp_run(FaultConfig())[0]
    report["legs"]["off_identical"] = {
        "hlo_bytes": len(hlo_plain),
        "hlo_byte_identical": hlo_plain == hlo_disarmed,
        "aux_unwrapped": not (isinstance(s_off.aux, dict)
                              and "dp_noise_scale" in s_off.aux),
        "no_dp_metrics": m_off.dp_clipped_frac is None
        and "dp_clipped_frac" not in g_off,
        "replay_identical": fps_off == fps_off2,
        "retraces": tr_off - 1,
    }
    assert hlo_plain == hlo_disarmed, \
        "disarmed DP knobs leaked into the lowered round program"
    assert m_off.dp_clipped_frac is None, \
        "DP-off round emitted dp metrics fields"
    assert fps_off == fps_off2, "off leg not bitwise-replayable"

    # -- leg 2: accountant vs closed form -------------------------------
    z_ctl, T_ctl = 1.1, 100
    acc_ctl = PrivacyAccountant(z_ctl, delta)
    acc_ctl.charge(1.0, rounds=T_ctl)
    eps_grid = acc_ctl.epsilon()
    eps_cf = closed_form_epsilon(z_ctl, T_ctl, delta)
    rel = abs(eps_grid - eps_cf) / eps_cf
    sub = PrivacyAccountant(z_ctl, delta)
    sub.charge(0.25, rounds=T_ctl)
    report["legs"]["closed_form_control"] = {
        "noise_multiplier": z_ctl, "rounds": T_ctl,
        "epsilon_accounted": eps_grid, "epsilon_closed_form": eps_cf,
        "rel_error": rel,
        "epsilon_subsampled_q0.25": sub.epsilon(),
    }
    assert rel < 0.01, (
        f"accountant {eps_grid} vs closed form {eps_cf}: rel {rel}")
    assert sub.epsilon() < eps_grid, "subsampling did not amplify"

    # -- leg 3: the eps-vs-accuracy frontier ----------------------------
    q = min(1.0, (C // 2) / C)  # online_client_rate=0.5 cohort
    clip = 0.5
    frontier = []
    for eps_target in (2.0, 8.0, float("inf")):
        if eps_target == float("inf"):
            fault = FaultConfig()
            z = 0.0
        else:
            z = calibrate_noise_multiplier(eps_target, rounds, q,
                                           delta)
            fault = FaultConfig(dp_noise_multiplier=z,
                                dp_clip_norm=clip, dp_delta=delta)
        fps1, acc1, gauges, traces = dp_run(fault)[:4]
        fps2 = dp_run(fault)[0]
        spent = None
        if z > 0.0:
            a = PrivacyAccountant(z, delta)
            a.charge(q, rounds=rounds)
            spent = a.epsilon()
        cell = {"epsilon_target": eps_target if eps_target != float(
            "inf") else "inf",
            "noise_multiplier": z, "epsilon_spent": spent,
            "final_acc": acc1, "gauges": gauges,
            "replay_identical": fps1 == fps2,
            "retraces": traces - 1}
        frontier.append(cell)
        assert fps1 == fps2, \
            f"eps={eps_target} cell not bitwise-replayable"
        assert traces == 1, \
            f"eps={eps_target} cell traced {traces}x"
        if spent is not None:
            assert spent <= eps_target * 1.001, (
                f"calibrated z={z} overspent: {spent} > {eps_target}")
    report["legs"]["frontier"] = frontier

    # -- leg 4: DP x trimmed_mean x byzantine cohort --------------------
    z8 = calibrate_noise_multiplier(8.0, rounds, q, delta)
    layered = FaultConfig(dp_noise_multiplier=z8, dp_clip_norm=clip,
                          dp_delta=delta, robust_agg="trimmed_mean",
                          robust_trim_frac=0.25, byzantine_rate=0.25,
                          byzantine_mode="sign_flip",
                          byzantine_scale=3.0)
    fps1, acc_l, g_l, traces, server_l, _, tot_l = dp_run(layered)
    fps2 = dp_run(layered)[0]
    finite = all(np.isfinite(np.asarray(x)).all()
                 for x in jax.tree.leaves(server_l.params))
    report["legs"]["layered"] = {
        "noise_multiplier": z8, "final_acc": acc_l,
        "robust_trimmed_total": tot_l["robust_trimmed"],
        "byzantine_total": tot_l["byzantine"],
        "dp_gauges": g_l, "params_finite": finite,
        "replay_identical": fps1 == fps2, "retraces": traces - 1,
    }
    assert fps1 == fps2 and traces == 1, "layered cell broke contracts"
    assert finite, "layered defense diverged to non-finite params"
    assert tot_l["byzantine"] > 0, "adversary never fired"
    assert tot_l["robust_trimmed"] > 0, "trimmed_mean never trimmed"
    assert g_l.get("dp_noise_sigma", 0.0) > 0, "DP noise not applied"

    # -- leg 5: budget exhaustion drills (real CLI loop) ----------------
    from fedtorch_tpu.cli import run_experiment
    from fedtorch_tpu.telemetry import read_health
    from fedtorch_tpu.telemetry.schema import iter_jsonl

    z_ex = 1.0
    half = rounds // 2
    affordable = PrivacyAccountant(z_ex, delta)
    affordable.charge(q, rounds=half)
    budget = affordable.epsilon() * 1.0001  # affords exactly `half`
    exdrills = {}
    for action in ("stop", "degrade"):
        run_root = tempfile.mkdtemp(prefix=f"dp_{action}_")
        run_dir = os.path.join(run_root, "run")
        cfg = make_cfg(FaultConfig(dp_noise_multiplier=z_ex,
                                   dp_clip_norm=clip, dp_delta=delta,
                                   dp_epsilon_budget=budget,
                                   dp_budget_action=action),
                       run_dir=run_dir)
        res = run_experiment(cfg)
        events = [e for e in iter_jsonl(
            os.path.join(run_dir, "events.jsonl"))
            if e.get("event") == "privacy.budget_exhausted"]
        rows = [r for r in iter_jsonl(
            os.path.join(run_dir, "metrics.jsonl")) if "round" in r]
        intent = read_health(run_dir)["intent"]
        with open(os.path.join(run_dir,
                               "privacy_accountant.json")) as f:
            acc_doc = json.load(f)
        exdrills[action] = {
            "rounds_completed": len(rows),
            "exhausted_at_round": res.get("dp_exhausted_at_round"),
            "intent": intent, "events": len(events),
            "epsilon_spent": acc_doc["epsilon_spent"],
            "epsilon_budget": budget,
            "sigma_tail": rows[-1]["dp_noise_sigma"] if rows else None,
        }
        assert len(events) == 1 and events[0]["action"] == action, \
            f"{action}: budget event missing/mislabelled"
        assert acc_doc["epsilon_spent"] <= budget * 1.0001, \
            f"{action}: overspent the budget"
        if action == "stop":
            assert intent == "complete", \
                f"stop drill exited intent={intent}, want complete"
            assert len(rows) == half == res["dp_exhausted_at_round"], (
                f"stop drill ran {len(rows)} rounds, want {half}")
        else:
            assert intent == "degraded", \
                f"degrade drill exited intent={intent}, want degraded"
            assert len(rows) == rounds, \
                f"degrade drill wedged at {len(rows)}/{rounds}"
            assert rows[-1]["dp_noise_sigma"] == 0.0, \
                "degrade tail still noising"
            assert rows[half - 1]["dp_noise_sigma"] > 0.0, \
                "pre-exhaustion rounds were not noised"
        shutil.rmtree(run_root, ignore_errors=True)
    report["legs"]["exhaustion"] = exdrills

    report["wall_seconds"] = round(time.time() - t0, 1)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        log(f"wrote {out_path}")
    return report


def run_builder_matrix(rounds: int = 8, smoke: bool = False,
                       seed: int = 0, out_path: str = None) -> dict:
    """Round-program-builder smoke (ISSUE 11): three representative
    cells of the (source x dispatch x execution) matrix under the
    chaos schedule with guards ON — the composition the builder must
    keep working, on the real platform the capture step runs on:

    * ``resident x scan x vmap`` — the single-dispatch fast path;
    * ``feed x scan x vmap`` — the NEW scanned streamed program;
    * ``feed x commit x vmap`` — the async commit over the
      commit-keyed feed producer.

    Each cell must complete every dispatch host-exception-free with
    finite params, trace exactly once (zero retraces past warmup),
    and — the engine-wide bar — match its reference program BITWISE:
    the faulted per-round device program for the sync cells, the
    faulted resident commit program for the commit cell. Writes
    BUILDER_MATRIX.json (tpu_capture.sh ``builder-matrix`` step)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    from fedtorch_tpu.parallel.round_program import cell_name
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    C = 12 if smoke else 16
    B = 16 if smoke else 32
    K = 3 if smoke else 5
    rounds = max(rounds, 4)
    rounds -= rounds % 2  # scan chunks of 2
    fault = FaultConfig(
        client_drop_rate=0.25, straggler_rate=0.25,
        straggler_step_frac=0.5, nan_inject_rate=0.1,
        guard_updates=True, max_retries=2, backoff_base_s=0.0)

    def make_trainer(source, dispatch):
        sync_mode = "async" if dispatch == "commit" else "sync"
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B, synthetic_alpha=0.5,
                            synthetic_beta=0.5,
                            data_plane="stream" if source == "feed"
                            else "device"),
            federated=FederatedConfig(
                federated=True, num_clients=C, num_comms=rounds,
                online_client_rate=0.5, algorithm="fedavg",
                sync_type="local_step", sync_mode=sync_mode),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=K),
            fault=fault,
        ).finalize()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=B)
        if sync_mode == "async":
            from fedtorch_tpu.async_plane import AsyncFederatedTrainer
            return AsyncFederatedTrainer(cfg, model,
                                         make_algorithm(cfg),
                                         data.train)
        return FederatedTrainer(cfg, model, make_algorithm(cfg),
                                data.train)

    def run_cell(source, dispatch):
        trainer = make_trainer(source, dispatch)
        server, clients = trainer.init_state(jax.random.key(seed))
        t0 = time.time()
        metrics = []
        with RecompilationSentinel() as sentinel:
            if dispatch == "scan":
                for _ in range(rounds // 2):
                    server, clients, ms = trainer.run_rounds(
                        server, clients, 2)
                    metrics.append(jax.tree.map(np.asarray, ms))
                stacked = jax.tree.map(
                    lambda *xs: np.concatenate(xs, axis=0), *metrics)
            else:
                for _ in range(rounds):
                    server, clients, m = trainer.run_round(server,
                                                           clients)
                    metrics.append(jax.tree.map(np.asarray, m))
                stacked = jax.tree.map(
                    lambda *xs: np.stack(xs), *metrics)
            jax.block_until_ready(jax.tree.leaves(server.params))
        wall = time.time() - t0
        # one warmup trace per program is expected; anything more is a
        # retrace (the trace-once bar)
        retraces = max(sum(sentinel.counts.values()) - 1, 0)
        params = jax.device_get(server.params)
        trainer.invalidate_stream()
        finite = all(bool(np.all(np.isfinite(np.asarray(x))))
                     for x in jax.tree.leaves(params))
        return params, stacked, retraces, finite, wall

    cells = [("resident", "scan", "vmap"), ("feed", "scan", "vmap"),
             ("feed", "commit", "vmap")]
    # the references: faulted per-round device program (sync cells)
    # and the faulted resident commit program (the commit cell)
    ref_params, ref_metrics, *_ = run_cell("resident", "round")
    ref_commit_params, ref_commit_metrics, *_ = run_cell("resident",
                                                         "commit")
    report = {"rounds": rounds, "clients": C,
              "fault": {"client_drop_rate": 0.25,
                        "straggler_rate": 0.25,
                        "nan_inject_rate": 0.1, "guard": "reject"},
              "cells": {}}
    t0 = time.time()
    for source, dispatch, execution in cells:
        params, metrics, retraces, finite, wall = run_cell(source,
                                                           dispatch)
        rp, rm = (ref_commit_params, ref_commit_metrics) \
            if dispatch == "commit" else (ref_params, ref_metrics)
        # operands were already fetched to host above
        max_diff = max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(jax.tree.leaves(params),
                            jax.tree.leaves(rp)))
        metric_diff = max(
            float(np.max(np.abs(np.asarray(a, np.float64)
                                - np.asarray(b, np.float64))))
            for a, b in zip(jax.tree.leaves(metrics),
                            jax.tree.leaves(rm)))
        name = cell_name(source, dispatch, execution)
        entry = {"retraces": retraces, "finite": finite,
                 "bitwise_vs_reference": max_diff == 0.0
                 and metric_diff == 0.0,
                 "max_abs_diff": max_diff, "wall_s": round(wall, 2)}
        report["cells"][name] = entry
        log(f"builder cell {name}: retraces={retraces} "
            f"bitwise={entry['bitwise_vs_reference']} "
            f"wall={wall:.2f}s")
        assert finite, f"{name}: non-finite params under chaos"
        assert retraces == 0, f"{name}: retraced {retraces}x mid-run"
        # the bitwise bar is an XLA-CPU guarantee (run_rounds
        # docstring: a scan body is a separate XLA compilation, which
        # other backends may reassociate at ulp level) — on-chip the
        # assertion hedges to ulp tolerance and the JSON records the
        # measured bitwise flag either way
        if jax.default_backend() == "cpu":
            assert entry["bitwise_vs_reference"], (
                f"{name}: trajectory diverged from its reference "
                f"program (max|d| params {max_diff}, metrics "
                f"{metric_diff})")
        else:
            assert max_diff <= 1e-5 and metric_diff <= 1e-4, (
                f"{name}: trajectory diverged beyond ulp tolerance "
                f"from its reference program (max|d| params "
                f"{max_diff}, metrics {metric_diff})")
    report["wall_seconds"] = round(time.time() - t0, 1)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"builder matrix written to {out_path}")
    return report


# the full rule surface IS the matrix's aggregator axis — importing
# the stdlib-only config tuple keeps the two from drifting when a new
# rule lands ('mean' first = the negative control)
from fedtorch_tpu.config import ROBUST_AGGREGATORS as ATTACK_AGGREGATORS  # noqa: E402,E501

ATTACK_MODES = ("sign_flip", "collude", "gauss")


def run_attack_matrix(rounds: int = 20, smoke: bool = False,
                      tol_points: float = 5.0, seed: int = 0,
                      algorithm: str = "fedavg",
                      modes=None, aggregators=None,
                      byzantine_rate: float = 0.25,
                      byzantine_scale: float = 3.0,
                      out_path: str = None) -> dict:
    """The byzantine attack x robust-aggregator matrix (ISSUE 9).

    Every armed cell keeps the update GUARDS ON — the point of the
    byzantine threat model is that these attacks pass the benign-fault
    screen (a sign-flipped delta at scale 3 sits at 3x the median norm,
    under the 10x guard threshold), so the robust rule is the only
    defense actually being exercised. ``robust_trim_frac`` is set to
    the armed byzantine rate + margin: trimming/krum must budget for at
    least the adversarial fraction they face.

    Acceptance (the sign_flip row): plain ``mean`` must lose MORE than
    ``tol_points`` accuracy vs fault-free (the attack bites) while at
    least one robust aggregator stays within ``tol_points``.

    DATA: an IID partition of one pooled task mixture — NOT the
    per-client LEAF generator the fault suite uses. The LEAF-style
    generator draws each client's own feature means and label model at
    unit scale even at alpha=beta=0, so its clients are intrinsically
    heterogeneous (measured: honest full-batch client updates have
    cos ~0.35 to their mean), and coordinate-median/krum are BIASED
    estimators under heterogeneity with zero adversaries present
    (median plateaued 11 pts below mean on it, attack-free). The
    robust-aggregation literature states its guarantees under bounded
    heterogeneity; pooling ``C`` generator tasks and partitioning the
    shuffled pool IID isolates the axis this matrix actually measures
    — byzantine corruption — while the mixture keeps the task
    non-trivial.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.data.synthetic import generate_synthetic
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    modes = tuple(modes) if modes else (
        ("sign_flip",) if smoke else ATTACK_MODES)
    aggregators = tuple(aggregators) if aggregators else (
        ("mean", "median", "krum") if smoke else ATTACK_AGGREGATORS)
    C = 8 if smoke else 16
    B = 32 if smoke else 64
    K = 2
    rounds = max(rounds, 8)

    # IID pool: C generator tasks concatenated, shuffled, split evenly
    syn = generate_synthetic(num_tasks=C, alpha=0.0, beta=0.0,
                             num_dim=30, num_classes=2)
    x = np.concatenate(syn.client_x)
    y = np.concatenate(syn.client_y)
    perm = np.random.RandomState(seed).permutation(len(x))
    x, y = x[perm], y[perm]
    n = (len(x) // C) * C
    parts = [np.arange(i * (n // C), (i + 1) * (n // C))
             for i in range(C)]
    data = stack_partitions(x[:n], y[:n], parts)

    def one_run(fault: FaultConfig):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=30,
                            batch_size=B),
            federated=FederatedConfig(
                federated=True, num_clients=C, num_comms=rounds,
                online_client_rate=1.0, algorithm=algorithm,
                sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            train=TrainConfig(local_step=K),
            fault=fault,
        ).finalize()
        model = define_model(cfg, batch_size=B)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data)
        server, clients = trainer.init_state(jax.random.key(seed))
        counters = {"byzantine": 0.0, "rejected": 0.0, "selected": 0.0,
                    "trimmed": 0.0, "retraces": 0}

        def count(m):
            # one batched fetch per round (lint FTL001)
            byz, rej, sel, trm = jax.device_get(
                (m.byzantine_clients, m.rejected_updates,
                 m.robust_selected, m.robust_trimmed))
            counters["byzantine"] += float(byz)
            counters["rejected"] += float(rej)
            counters["selected"] += float(sel)
            counters["trimmed"] += float(trm)

        # round 0 pays the (expected) trace but its faults still count
        server, clients, m = trainer.run_round(server, clients)
        count(m)
        with RecompilationSentinel() as sentinel:
            for _ in range(rounds - 1):
                server, clients, m = trainer.run_round(server, clients)
                count(m)
        counters["retraces"] = sum(sentinel.counts.values())
        # one transfer for the whole EvalResult pytree (lint FTL001)
        res = jax.device_get(evaluate(model, server.params, syn.test_x,
                                      syn.test_y))
        return float(res.top1), counters

    trim = min(byzantine_rate + 0.1, 0.45)
    clean_acc, _ = one_run(FaultConfig(guard_updates=True))
    report = {
        "algorithm": algorithm, "rounds": rounds, "clients": C,
        "tol_points": tol_points, "clean_top1": round(clean_acc, 4),
        "byzantine_rate": byzantine_rate,
        "byzantine_scale": byzantine_scale,
        "robust_trim_frac": trim, "guards": "on (10x median, reject)",
        "matrix": {},
    }
    t0 = time.time()
    for mode in modes:
        row = {}
        for agg in aggregators:
            fault = FaultConfig(
                byzantine_rate=byzantine_rate, byzantine_mode=mode,
                byzantine_scale=byzantine_scale, guard_updates=True,
                robust_agg=agg, robust_trim_frac=trim)
            acc, counters = one_run(fault)
            gap = (clean_acc - acc) * 100.0
            row[agg] = {
                "top1": round(acc, 4), "gap_points": round(gap, 2),
                "byzantine_injected": int(counters["byzantine"]),
                "guard_rejected": int(counters["rejected"]),
                "robust_trimmed": int(counters["trimmed"]),
                "retraces": counters["retraces"],
            }
            log(f"attack {mode} x {agg}: top1 {acc:.4f} "
                f"(gap {gap:+.2f}pts, "
                f"{int(counters['byzantine'])} byz injected, "
                f"{int(counters['rejected'])} guard-rejected, "
                f"{counters['retraces']} retraces)")
            assert counters["byzantine"] > 0, \
                f"{mode} x {agg}: attack schedule injected nothing"
            assert counters["retraces"] == 0, (
                f"{mode} x {agg}: robust aggregator retraced "
                f"{counters['retraces']}x mid-run (trace-once bar)")
        report["matrix"][mode] = row

    report["wall_seconds"] = round(time.time() - t0, 1)

    # the acceptance bar rides the sign_flip row when armed
    if "sign_flip" in report["matrix"] and "mean" in aggregators:
        row = report["matrix"]["sign_flip"]
        mean_gap = row["mean"]["gap_points"]
        robust_gaps = {a: c["gap_points"] for a, c in row.items()
                       if a != "mean"}
        best = min(robust_gaps, key=robust_gaps.get)
        report["acceptance"] = {
            "mean_gap_points": mean_gap,
            "best_robust": best,
            "best_robust_gap_points": robust_gaps[best],
            "attack_bites": mean_gap > tol_points,
            "defense_holds": robust_gaps[best] <= tol_points,
        }
        log(f"attack matrix: mean gap {mean_gap:+.2f}pts (must exceed "
            f"{tol_points}); best robust {best} "
            f"{robust_gaps[best]:+.2f}pts (must be within)")
        assert mean_gap > tol_points, (
            f"negative control failed: 25% sign_flip cost plain mean "
            f"only {mean_gap:.2f}pts (<= {tol_points}) — the attack "
            "does not bite, so the matrix proves nothing")
        assert robust_gaps[best] <= tol_points, (
            f"no robust aggregator held: best ({best}) lost "
            f"{robust_gaps[best]:.2f}pts (> {tol_points})")

    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"attack matrix written to {out_path}")
    return report


def run_ledger_attack(rounds: int = 20, smoke: bool = False,
                      seed: int = 6, byzantine_rate: float = 0.25,
                      byzantine_scale: float = 3.0, aggregators=None,
                      min_precision: float = 0.66,
                      out_path: str = None) -> dict:
    """The ledger-separation drill (ISSUE 14): one REAL CLI run
    (``run_experiment`` — telemetry, the batched cohort fetch, the
    per-client ledger) per robust rule with the PR 9 persistent
    byzantine cohort armed and ``--cohort_stats`` on. Acceptance: the
    persisted ``client_ledger.json``'s cumulative-suspicion ranking
    must SEPARATE the true adversarial cohort from honest clients —
    precision/recall of the top-``n`` ranking (``n`` = cohort size)
    against the cohort mask recomputed from the seed (the cohort is a
    pure function of ``server.rng``, robustness/chaos.py). Writes
    ``COHORT_AB.json``.

    Guards stay ON in every cell (the PR 9 threat model: these attacks
    pass the benign-fault screen, so suspicion — not rejection — is
    the only record naming the adversaries)."""
    import tempfile

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from fedtorch_tpu.cli import run_experiment
    from fedtorch_tpu.config import (
        CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
        FederatedConfig, ModelConfig, OptimConfig, TelemetryConfig,
        TrainConfig,
    )
    from fedtorch_tpu.robustness.chaos import (
        BYZ_COHORT_FOLD, byzantine_cohort_mask,
    )
    from fedtorch_tpu.telemetry.ledger import (
        read_client_ledger, suspicion_ranking,
    )
    from fedtorch_tpu.telemetry.schema import iter_jsonl

    aggregators = tuple(aggregators) if aggregators else (
        ("median",) if smoke else ("median", "krum", "trimmed_mean"))
    C = 8 if smoke else 12
    rounds = max(rounds, 6 if smoke else 8)
    trim = min(byzantine_rate + 0.1, 0.45)

    # the true cohort: byzantine_cohort_mask folds BYZ_COHORT_FOLD off
    # server.rng, and init_state sets server.rng = split(key(seed))[0]
    # — replay the same two steps (pure function of the seed)
    run_key = jax.random.split(jax.random.key(seed))[0]
    cohort = np.asarray(jax.device_get(byzantine_cohort_mask(
        jax.random.fold_in(run_key, BYZ_COHORT_FOLD), C,
        byzantine_rate)))
    true = set(np.nonzero(cohort)[0].tolist())
    n = len(true)
    assert n > 0, "byzantine_rate * C rounded to an empty cohort"

    report = {
        "clients": C, "rounds": rounds, "seed": seed,
        "byzantine_rate": byzantine_rate,
        "byzantine_scale": byzantine_scale, "robust_trim_frac": trim,
        "byzantine_mode": "sign_flip", "true_cohort": sorted(true),
        "min_precision": min_precision, "cells": {},
    }
    t0 = time.time()
    for agg in aggregators:
        run_dir = tempfile.mkdtemp(prefix=f"ledger_attack_{agg}_")
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=10,
                            batch_size=8),
            federated=FederatedConfig(
                federated=True, num_clients=C, num_comms=rounds,
                online_client_rate=1.0, algorithm="fedavg",
                sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.1, weight_decay=0.0),
            train=TrainConfig(local_step=2, manual_seed=seed,
                              eval_freq=rounds),
            checkpoint=CheckpointConfig(run_dir=run_dir, debug=False),
            telemetry=TelemetryConfig(cohort_stats=True),
            fault=FaultConfig(
                byzantine_rate=byzantine_rate,
                byzantine_mode="sign_flip",
                byzantine_scale=byzantine_scale, guard_updates=True,
                robust_agg=agg, robust_trim_frac=trim),
        ).finalize()
        run_experiment(cfg)

        rows = [r for r in iter_jsonl(
            os.path.join(run_dir, "metrics.jsonl")) if "schema" not in r]
        injected = sum(r.get("byzantine", 0.0) for r in rows)
        assert injected > 0, \
            f"{agg}: the attack schedule injected nothing"
        doc = read_client_ledger(run_dir)
        assert doc["rounds"] == rounds, \
            f"{agg}: ledger recorded {doc['rounds']}/{rounds} rounds"
        ranking = suspicion_ranking(doc)
        top = {cid for cid, _ in ranking[:n]}
        hits = len(top & true)
        precision = hits / n
        recall = hits / n  # |top| == |true| == n, so the two coincide
        by_client = dict(ranking)
        byz_mean = float(np.mean([by_client.get(c, 0.0)
                                  for c in sorted(true)]))
        honest = [c for c in range(C) if c not in true]
        honest_mean = float(np.mean([by_client.get(c, 0.0)
                                     for c in honest]))
        cell = {
            "precision": round(precision, 4),
            "recall": round(recall, 4),
            "byzantine_injected": int(injected),
            "top_ranking": [[int(c), round(float(s), 4)]
                            for c, s in ranking[:n]],
            "byz_suspicion_mean": round(byz_mean, 4),
            "honest_suspicion_mean": round(honest_mean, 4),
            "separation": round(byz_mean / max(honest_mean, 1e-9), 3),
        }
        report["cells"][agg] = cell
        log(f"ledger attack x {agg}: precision {precision:.2f} "
            f"recall {recall:.2f} separation x{cell['separation']} "
            f"({int(injected)} byz injected)")
        assert precision >= min_precision, (
            f"{agg}: suspicion ranking precision {precision:.2f} < "
            f"{min_precision} — the ledger does not separate the "
            "byzantine cohort")
    best = max(report["cells"].values(), key=lambda c: c["precision"])
    report["acceptance"] = {
        "best_precision": best["precision"],
        "all_cells_pass": True,
    }
    report["wall_seconds"] = round(time.time() - t0, 1)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"ledger-attack report written to {out_path}")
    return report


# the host-fault matrix's seam axis IS the config tuple — a new seam
# landing without a drill cell fails here, not in production
from fedtorch_tpu.config import HOST_FAULT_SEAMS  # noqa: E402


def run_host_fault_matrix(rounds: int = 12, smoke: bool = False,
                          seed: int = 0, rate: float = 0.25,
                          seams=None, out_path: str = None) -> dict:
    """The host-plane chaos drill (ISSUE 10): for every seam in
    ``HOST_FAULT_SEAMS``, run the REAL CLI loop (``run_experiment`` —
    telemetry, health, checkpointing, the stream plane) with the
    seeded injector armed at that seam, and prove:

    * **run-survival** — the run completes every round where the
      pre-PR behavior was an abort (a producer gather error, an
      ENOSPC mid-checkpoint, a telemetry write failure);
    * **exact recovery** — the per-round server-param trajectory is
      BITWISE-identical to the fault-free baseline (the data path
      replays a deterministic index schedule, so recovery must be
      exact, not approximate); the checkpoint seams additionally
      prove resume-stitching: the newest durable checkpoint restores
      bitwise against the live final state;
    * **observability** — >= 1 retry/degraded counter landed on the
      metrics rows and the seam's events fired (``chaos.host_fault``
      plus ``host.recovered`` / ``ckpt.degraded`` /
      ``stream.producer_rebuilt`` where the seam implies them);
    * **trace discipline** — the round program traces exactly as often
      as the fault-free run (the sentinel sees no injection-driven
      retrace).

    One extra cell, ``stream.rebuild``, drives the gather seam at rate
    1.0 with a fire cap of ``host_retry_max + 1``: the producer's own
    retries exhaust, the thread DIES, the consumer reports it
    promptly with the seam named, and the trainer rebuilds the
    producer through the ``invalidate_stream`` resync — the
    run-recovers-instead-of-aborting bar.

    Injection is a pure hash of (seed, seam, check index), so the
    whole matrix is replayable; results land in HOST_CHAOS_AB.json.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import hashlib
    import tempfile

    import jax
    import numpy as np

    from fedtorch_tpu.cli import run_experiment
    from fedtorch_tpu.config import (
        CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
        FederatedConfig, ModelConfig, OptimConfig, TelemetryConfig,
        TrainConfig,
    )
    from fedtorch_tpu.telemetry import iter_jsonl
    from fedtorch_tpu.utils.lock_sentinel import LockOrderSentinel
    from fedtorch_tpu.utils.tracing import RecompilationSentinel

    seams = tuple(seams) if seams else HOST_FAULT_SEAMS + (
        "stream.rebuild",)
    C = 6 if smoke else 10
    B = 8 if smoke else 16
    K = 2
    rounds = max(rounds, 6)
    root = tempfile.mkdtemp(prefix="host_chaos_")

    def cell_cfg(run_dir: str, fault: FaultConfig,
                 save_all: bool = False) -> ExperimentConfig:
        return ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=20,
                            batch_size=B, data_plane="stream"),
            federated=FederatedConfig(
                federated=True, num_clients=C, num_comms=rounds,
                online_client_rate=0.5, algorithm="fedavg",
                sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.5, weight_decay=0.0),
            # eval (and therefore a checkpoint write) every round: the
            # ckpt seams need real write traffic to bite
            train=TrainConfig(local_step=K, eval_freq=1),
            # save_all (the torn cell): per-round keeps give the
            # torn-main-checkpoint resume fallback something to stitch
            # from
            checkpoint=CheckpointConfig(run_dir=run_dir,
                                        async_save=True,
                                        save_all_models=save_all),
            telemetry=TelemetryConfig(level="default"),
            fault=fault,
        ).finalize()

    def fingerprint(leaves) -> str:
        h = hashlib.sha256()
        for leaf in leaves:
            h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest()

    def one_run(name: str, fault: FaultConfig, save_all: bool = False):
        """One CLI run; returns (per-round param fingerprints,
        results, run_dir, trace count)."""
        run_dir = os.path.join(root, name.replace(".", "_"))
        fingerprints = []

        def cb(r, trainer, server, clients, metrics):
            fingerprints.append(fingerprint(
                jax.device_get(jax.tree.leaves(server.params))))

        cfg = cell_cfg(run_dir, fault, save_all)
        # the lock-order sentinel rides every drill cell: injected
        # faults exercise the writer/injector/recovery lock paths
        # under contention, exactly where an ordering inversion or a
        # re-entrant emit (the PR 10 self-deadlock) would surface
        with RecompilationSentinel() as sentinel, \
                LockOrderSentinel() as locks:
            results = run_experiment(cfg, round_callback=cb)
        return (fingerprints, results, run_dir, dict(sentinel.counts),
                locks.order_edges())

    def read_rows(run_dir):
        path = os.path.join(run_dir, "metrics.jsonl")
        if not os.path.exists(path):
            return []
        return [r for r in iter_jsonl(path) if "round" in r]

    def read_events(run_dir):
        path = os.path.join(run_dir, "events.jsonl")
        if not os.path.exists(path):
            return []
        return [r for r in iter_jsonl(path) if "event" in r]

    log(f"host-fault matrix: baseline ({rounds} rounds, C={C})")
    base_fps, base_res, base_dir, base_traces, base_lock_edges = \
        one_run("baseline", FaultConfig())
    assert len(base_fps) == rounds, "baseline did not complete"

    report = {"rounds": rounds, "clients": C, "rate": rate,
              "seed": seed, "baseline_traces": base_traces,
              "baseline_lock_order": base_lock_edges,
              "lock_order_violations": 0,
              "matrix": {}}
    t0 = time.time()
    for seam in seams:
        if seam == "stream.rebuild":
            # rate 1.0 + a fire cap of retries+1: the producer's own
            # gather retries exhaust exactly once, the thread dies,
            # and the trainer must rebuild it
            retry_max = FaultConfig().host_retry_max
            fault = FaultConfig(host_fault_seams="stream.gather",
                                host_fault_rate=1.0,
                                host_fault_seed=seed,
                                host_fault_max=retry_max + 1,
                                host_retry_backoff_s=0.0)
        else:
            fault = FaultConfig(host_fault_seams=seam,
                                host_fault_rate=rate,
                                host_fault_seed=seed,
                                host_retry_backoff_s=0.0)
        fps, results, run_dir, traces, lock_edges = one_run(
            seam, fault, save_all=seam == "ckpt.torn")

        # run-survival + bitwise trajectory (the stream plane replays
        # a deterministic schedule; recovery must be exact)
        assert len(fps) == rounds, \
            f"{seam}: faulted run aborted at round {len(fps)}"
        assert not results.get("preempted"), f"{seam}: run preempted"
        assert fps == base_fps, (
            f"{seam}: recovered trajectory diverged from the "
            "fault-free run (first mismatch at round "
            f"{[a == b for a, b in zip(base_fps, fps)].index(False)})")
        # trace-once with injection armed: the streamed round program
        # traced exactly once and NOTHING retraced (evaluate.run etc.
        # trace at most once per process — the baseline pays those)
        round_prog = "federated.round_stream[fedavg]"
        assert traces.get(round_prog) == 1, (
            f"{seam}: {round_prog} traced {traces.get(round_prog)}x "
            f"(trace-once bar); all counts: {traces}")
        assert all(v == 1 for v in traces.values()), (
            f"{seam}: a program retraced mid-run: {traces}")

        rows = read_rows(run_dir)
        events = read_events(run_dir)
        names = [e["event"] for e in events]
        last = rows[-1] if rows else {}
        fired = int(last.get("host_faults", 0))
        retries = int(last.get("host_retries", 0))
        recovered = int(last.get("host_recovered", 0))
        degraded = int(last.get("host_degraded", 0))
        rebuilds = int(last.get("stream_rebuilds", 0))
        entry = {
            "host_faults": fired, "host_retries": retries,
            "host_recovered": recovered, "host_degraded": degraded,
            "stream_rebuilds": rebuilds, "traces": traces,
            "lock_order": lock_edges,
            "bitwise_identical": True,
            "events": sorted(set(names) - {"run.start", "run.end"}),
        }

        # telemetry.write can degrade the metrics writer itself — the
        # injector fired even when the last row could not land; the
        # run dir's un-dropped rows/events still prove the drill
        if seam == "telemetry.write":
            assert fired >= 1 or "chaos.host_fault" in names or \
                degraded >= 1 or not rows, \
                f"{seam}: no observable injection"
        else:
            assert fired >= 1, f"{seam}: injector never fired " \
                f"(rows={bool(rows)})"
            assert "chaos.host_fault" in names, \
                f"{seam}: chaos.host_fault event missing"
        if seam in ("stream.gather", "stream.h2d", "ckpt.write"):
            assert retries >= 1, f"{seam}: no recovery retry counted"
            assert recovered >= 1 or degraded >= 1, \
                f"{seam}: neither recovered nor degraded"
            assert "host.recovered" in names \
                or "host.degraded" in names, \
                f"{seam}: no recovery/degrade event"
        if seam == "stream.rebuild":
            assert rebuilds >= 1, \
                "producer death did not trigger a rebuild"
            assert "stream.producer_rebuilt" in names, \
                "stream.producer_rebuilt event missing"
            rebuilt = [e for e in events
                       if e["event"] == "stream.producer_rebuilt"]
            assert any("stream.gather" in e.get("error", "")
                       for e in rebuilt), (
                "the rebuild event does not name the failing seam: "
                f"{rebuilt}")

        # checkpoint seams: resume-stitched identity — the newest
        # durable checkpoint (or, for the torn seam, the newest VALID
        # frame the resume fallback found) must restore BITWISE
        # against the live state it snapshotted at that round
        if seam in ("ckpt.write", "ckpt.torn"):
            entry["resume"] = _check_resume_stitch(
                cell_cfg(run_dir, fault), run_dir, fps, fingerprint,
                rounds, require_final=seam == "ckpt.write")
        report["matrix"][seam] = entry
        log(f"host-fault {seam}: faults={fired} retries={retries} "
            f"recovered={recovered} degraded={degraded} "
            f"rebuilds={rebuilds} bitwise=ok events={entry['events']}")

    report["wall_seconds"] = round(time.time() - t0, 1)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"host-fault matrix written to {out_path}")
    return report


def _check_resume_stitch(cfg, run_dir: str, fps, fingerprint,
                         rounds: int, require_final: bool):
    """Resume from the faulted run's directory into a fresh trainer:
    the restored params must BITWISE match the live trajectory at the
    restored round. ``require_final`` (the ENOSPC seam, where per-write
    retry absorbs every fault) additionally demands the FINAL round —
    the torn seam may legitimately stitch from an earlier round when
    the last ``checkpoint.ckpt`` write landed torn and the resume
    fallback picked the newest valid per-round keep."""
    import warnings as _warnings

    import jax

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    from fedtorch_tpu.utils.checkpoint import maybe_resume

    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                               data.train)
    server, clients = trainer.init_state(
        jax.random.key(cfg.train.manual_seed))
    with _warnings.catch_warnings():
        # the torn seam's fallback warns by design
        _warnings.simplefilter("ignore", RuntimeWarning)
        server, clients, _, resumed = maybe_resume(
            run_dir, server, clients, cfg)
    assert resumed, "no durable checkpoint survived the ckpt drill"
    resumed_round = int(jax.device_get(server.round))
    assert 1 <= resumed_round <= rounds, resumed_round
    if require_final:
        assert resumed_round == rounds, \
            f"retried writes still lost rounds ({resumed_round})"
    restored_fp = fingerprint(
        jax.device_get(jax.tree.leaves(server.params)))
    assert restored_fp == fps[resumed_round - 1], (
        f"restored round {resumed_round} params do not match the live "
        "trajectory (resume-stitch not bitwise)")
    trainer.invalidate_stream()
    return {"resumed_round": resumed_round, "bitwise": True}


def run_kill_drill(rounds: int = 150, ckpt_root: str = None) -> dict:
    """Process-lifecycle chaos (ISSUE 4): SIGTERM the REAL CLI mid-run,
    assert it drains and exits 75, then let the ElasticRunner harness
    relaunch it with --resume and finish the job. The bitwise
    trajectory-identity half of this drill lives in
    tests/test_kill_drill.py; this entry checks the operator-facing
    lifecycle end to end (drain -> restartable exit -> relaunch ->
    completion) against the production entry point."""
    import signal
    import subprocess
    import tempfile
    import threading

    from fedtorch_tpu.robustness.harness import (
        ElasticRunner, read_checkpoint_round,
    )

    run_dir = os.path.join(ckpt_root or tempfile.mkdtemp(), "run")
    cmd = [sys.executable, "-m", "fedtorch_tpu.cli",
           "--federated", "true", "-d", "synthetic", "-a",
           "logistic_regression", "--num_comms", str(rounds),
           "--num_workers", "8", "--online_client_rate", "0.5",
           "--federated_sync_type", "local_step", "--local_step", "2",
           "--batch_size", "8", "--lr", "0.1", "--eval_freq", "1",
           "--debug", "false", "--run_dir", run_dir]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    state = {"killed": False}

    def popen(c, **kw):
        proc = subprocess.Popen(c, env=env, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        if not state["killed"]:
            # watch checkpoint.json; SIGTERM once the run is mid-flight
            def killer():
                while proc.poll() is None:
                    r = read_checkpoint_round(run_dir)
                    if r is not None and r >= 3:
                        state["killed"] = True
                        try:
                            proc.send_signal(signal.SIGTERM)
                        except OSError:  # raced to exit
                            pass
                        return
                    time.sleep(0.02)

            # daemon watcher scoped to the child process: it exits as
            # soon as proc.poll() turns non-None, so there is no close
            # path to join it from
            threading.Thread(target=killer, daemon=True,  # lint: disable=FTH005 — exits with the watched proc; nothing outlives popen
                             name="chaos-kill-watcher").start()
        return proc

    runner = ElasticRunner(cmd, ckpt_dir=run_dir, max_restarts=3,
                           backoff_base_s=0.1, popen=popen, log_fn=log)
    t0 = time.time()
    rc = runner.run()
    final_round = read_checkpoint_round(run_dir)
    assert state["killed"], \
        "kill drill never landed its SIGTERM (job finished too fast — " \
        "raise rounds)"
    assert rc == 0, f"relaunched job did not complete cleanly (rc={rc})"
    assert runner.launches >= 2, \
        "child was killed but the harness never relaunched it"
    assert final_round == rounds, \
        f"resumed job stopped at round {final_round}, wanted {rounds}"
    report = {"rounds": rounds, "launches": runner.launches,
              "final_round": final_round,
              "wall_seconds": round(time.time() - t0, 1)}
    log(f"kill drill: {report}")
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI")
    ap.add_argument("--tol", type=float, default=5.0,
                    help="max accuracy-point gap vs the fault-free run")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill-drill", action="store_true",
                    help="also run the process-lifecycle kill drill "
                         "(SIGTERM -> exit 75 -> relaunch -> complete)")
    ap.add_argument("--straggler-heavy", action="store_true",
                    help="long-tail delay preset: compare SYNC vs "
                         "ASYNC (sync_mode='async') under the "
                         "straggler-heavy schedule instead of clean "
                         "vs chaos (the ISSUE 6 convergence bar)")
    ap.add_argument("--attack-matrix", action="store_true",
                    help="run the byzantine attack x robust-aggregator "
                         "grid instead of the fault suite (plain mean "
                         "as the negative control) and write "
                         "--attack-out")
    ap.add_argument("--attack-out", default="ATTACK_AB.json",
                    help="output path for the attack-matrix report")
    ap.add_argument("--host-fault-matrix", action="store_true",
                    help="run the host-plane fault drill instead: one "
                         "real CLI run per HOST_FAULT_SEAMS seam with "
                         "the seeded injector armed, asserting "
                         "run-survival, bitwise-identical recovery, "
                         "resume-stitched checkpoints, fired "
                         "retry/degraded counters+events and no "
                         "injection-driven retrace; writes --host-out "
                         "(docs/robustness.md 'Host plane')")
    ap.add_argument("--host-out", default="HOST_CHAOS_AB.json",
                    help="output path for the host-fault-matrix report")
    ap.add_argument("--host-rate", type=float, default=0.25,
                    help="per-check injection rate for the host-fault "
                         "matrix cells")
    ap.add_argument("--builder-matrix", action="store_true",
                    help="run the round-program-builder smoke instead: "
                         "three representative (source x dispatch x "
                         "execution) cells under chaos + guards — the "
                         "scanned device path, the scanned streamed "
                         "program and the feed-sourced async commit — "
                         "each trace-once and bitwise vs its reference "
                         "program; writes --builder-out")
    ap.add_argument("--builder-out", default="BUILDER_MATRIX.json",
                    help="output path for the builder-matrix report")
    ap.add_argument("--availability-matrix", action="store_true",
                    help="run the deployment-realism drill instead: "
                         "default-model draws bitwise vs the raw "
                         "legacy straggler chain, armed trace-model "
                         "lifecycle seeded-replayable + trace-once, "
                         "sub-quorum degrade-vs-abort, async "
                         "trace-model dropouts deterministic; writes "
                         "--avail-out (docs/robustness.md §7)")
    ap.add_argument("--avail-out", default="AVAIL_AB.json",
                    help="output path for the availability report")
    ap.add_argument("--ledger-attack", action="store_true",
                    help="run the ledger-separation drill instead: a "
                         "real CLI run per robust rule with the PR 9 "
                         "byzantine cohort + --cohort_stats on, "
                         "asserting the persisted client_ledger.json "
                         "suspicion ranking separates the adversarial "
                         "cohort from honest clients (precision/"
                         "recall); writes --ledger-out "
                         "(docs/observability.md 'Federation plane')")
    ap.add_argument("--ledger-out", default="COHORT_AB.json",
                    help="output path for the ledger-attack report")
    ap.add_argument("--privacy-matrix", action="store_true",
                    help="run the privacy-plane drill instead: DP-off "
                         "HLO byte-identity, the RDP accountant vs "
                         "closed-form epsilon, the measured "
                         "eps-vs-accuracy frontier (eps in {2,8,inf}, "
                         "noise calibrated against the accountant), "
                         "DP x trimmed_mean x byzantine layered leg, "
                         "and both budget-exhaustion drills through "
                         "the real CLI loop; writes --privacy-out "
                         "(docs/robustness.md §8)")
    ap.add_argument("--privacy-out", default="DP_AB.json",
                    help="output path for the privacy report")
    args = ap.parse_args()
    if args.privacy_matrix:
        report = run_privacy_matrix(rounds=args.rounds,
                                    smoke=args.smoke, seed=args.seed,
                                    out_path=args.privacy_out)
        log(json.dumps(report, indent=1, sort_keys=True))
        return
    if args.availability_matrix:
        report = run_availability_matrix(rounds=args.rounds,
                                         smoke=args.smoke,
                                         seed=args.seed,
                                         out_path=args.avail_out)
        print(json.dumps(report), flush=True)
        return
    if args.ledger_attack:
        report = run_ledger_attack(rounds=args.rounds,
                                   smoke=args.smoke, seed=args.seed,
                                   out_path=args.ledger_out)
        print(json.dumps(report), flush=True)
        return
    if args.builder_matrix:
        report = run_builder_matrix(rounds=args.rounds,
                                    smoke=args.smoke, seed=args.seed,
                                    out_path=args.builder_out)
        print(json.dumps(report), flush=True)
        return
    if args.host_fault_matrix:
        report = run_host_fault_matrix(rounds=args.rounds,
                                       smoke=args.smoke, seed=args.seed,
                                       rate=args.host_rate,
                                       out_path=args.host_out)
        print(json.dumps(report), flush=True)
        return
    if args.attack_matrix:
        report = run_attack_matrix(rounds=args.rounds, smoke=args.smoke,
                                   tol_points=args.tol, seed=args.seed,
                                   out_path=args.attack_out)
        print(json.dumps(report), flush=True)
        return
    report = run_suite(rounds=args.rounds, smoke=args.smoke,
                       tol_points=args.tol, seed=args.seed,
                       straggler_heavy=args.straggler_heavy)
    if args.kill_drill:
        report["kill_drill"] = run_kill_drill(
            rounds=60 if args.smoke else 150)
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
