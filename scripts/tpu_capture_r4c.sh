#!/bin/bash
# Round-4 requeue: stage 1 exhausted its relay patience without a
# single grant, so after stage 2 (tpu_capture_r4.sh) finishes, retry
# the FULL capture list with fresh patience — this time with the
# DEFAULT bench run first, so a late relay recovery persists the
# north-star capture (TPU_BENCH_CAPTURE.json) before anything else
# competes for chip time. Strictly serial; single-session relay.
#     nohup bash scripts/tpu_capture_r4c.sh > /tmp/tpu_capture_r4c.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

while pgrep -f "bash scripts/tpu_capture_r4.sh" > /dev/null; do
    sleep 120
done
echo "[tpu_capture_r4c] stage 2 done — requeueing the full list"

TRIES="${TPU_CAPTURE_WAIT_TRIES:-85}"
BENCH_PROBE_TRIES="$TRIES" python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture_r4c] relay never recovered; nothing captured"
    exit 1
fi

echo "[tpu_capture_r4c] relay alive — capturing (sequential)"
FAILED=0
run() {
    echo "=== $* ==="
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

run python bench.py                              # capture FIRST
run env BENCH_CONV_IMPL=matmul python bench.py   # conv A/B
run env BENCH_SINGLE_DISPATCH=0 python bench.py  # dispatch A/B
run env BENCH_SCAN_UNROLL=4 python bench.py      # unroll A/B
run python scripts/tpu_zoo_check.py              # -> TPU_ZOO.json
run python scripts/pallas_tpu_check.py           # -> PALLAS_TPU.json
run python scripts/flash_train_bench.py          # -> FLASH_TRAIN.json
run python scripts/vmap_penalty_bench.py         # -> VMAP_PENALTY.json
run python scripts/baseline_suite.py             # -> BASELINE_SUITE.json
run python bench.py                              # re-persist at default config
echo "[tpu_capture_r4c] done (failed=$FAILED)"
exit $FAILED
