#!/bin/bash
# Round-5 capture chain (VERDICT r4 item #1): keep a probe loop running
# from the round's first minute to its last, and convert a relay window
# of ANY length into the priority-ordered capture — default bench.py
# first (persists TPU_BENCH_CAPTURE.json, the north-star record the
# driver can replay), then the measurement queue ordered by information
# value: conv A/B, MFU sweep, conv-lowering sweep, MoE A/B, flash
# lowering, zoo, baseline suite. Finally certify the wedge-replay path
# against the REAL capture (VERDICT r4 item #3).
#
# Single-session relay discipline: waits for ALL round-4 stages to
# exit before issuing its own probes (two concurrent probes contend),
# runs strictly serially, and NEVER wraps a relay-touching run in
# `timeout` (a killed grant-waiter wedged the relay in round 2).
#
#     nohup bash scripts/tpu_capture_r5.sh > /tmp/tpu_capture_r5.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
trap 'touch "$R5_DONE"' EXIT

# The launch time only bounds the HARD end (stay clear of the driver's
# round-end bench, ~12 h after the round starts); the probe budget
# itself is anchored AFTER the round-4 wait below, so a long wait
# cannot eat the probing window down to zero probes.
LAUNCH="$(date +%s)"
HARD_END="${TPU_CAPTURE_HARD_END_UNIX:-$(( LAUNCH + 39600 ))}"   # 11 h

while pgrep -f "bash scripts/tpu_capture_r4.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r4c.sh" > /dev/null \
      || pgrep -f "bash scripts/tpu_capture_r4b.sh" > /dev/null; do
    sleep 120
done

# certification below must only accept a capture taken by THIS chain —
# stamped after the wait so a round-4 stage's own late capture (already
# certified by tpu_capture_r4b) cannot satisfy this chain's check
export WEDGE_MIN_CAPTURED_UNIX="$(date +%s)"

DEADLINE="${TPU_CAPTURE_DEADLINE_UNIX:-$(( $(date +%s) + 36000 ))}"  # 10 h of probing
[ "$DEADLINE" -gt "$HARD_END" ] && DEADLINE="$HARD_END"
echo "[tpu_capture_r5] round-4 stages done — probing until $(date -u -d "@$DEADLINE" +%H:%M:%S) UTC"

GRANTED=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    BENCH_PROBE_TRIES=5 python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
    if [ $? -eq 0 ]; then
        GRANTED=1
        break
    fi
    echo "[tpu_capture_r5] relay still dead at $(date -u +%H:%M:%S) UTC"
    sleep 60
done

if [ "$GRANTED" -ne 1 ]; then
    echo "[tpu_capture_r5] relay never recovered before the deadline; nothing captured"
    exit 1
fi

echo "[tpu_capture_r5] relay alive — capturing (sequential, bench first)"
FAILED=0
run() {
    echo "=== $* ==="
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

run python bench.py                              # north star (matmul default) -> TPU_BENCH_CAPTURE.json FIRST
capture_conv_side || FAILED=1                    # non-default lowering side (matmul post-flip) -> BENCH_MATMULSIDE_AB.json
run python scripts/mfu_sweep.py                  # -> MFU_SWEEP.json (lever grid)
run python scripts/vmap_penalty_bench.py         # -> VMAP_PENALTY.json (conv A/B detail)
run python scripts/moe_ab_bench.py               # -> MOE_AB.json (dense vs sparse dispatch)
run python scripts/pallas_tpu_check.py           # -> PALLAS_TPU.json (flash under real Mosaic)
run python scripts/flash_train_bench.py          # -> FLASH_TRAIN.json
run python scripts/tpu_zoo_check.py              # -> TPU_ZOO.json
run python scripts/baseline_suite.py             # -> BASELINE_SUITE.json
run python bench.py                              # re-persist at default config
echo "[tpu_capture_r5] capture done (failed=$FAILED) — certifying wedge replay"

python scripts/wedge_replay_check.py
rc=$?
echo "[tpu_capture_r5] wedge_replay_check rc=$rc (0=verified, 2=no capture)"
echo "[tpu_capture_r5] done"
exit $FAILED
