"""The BASELINE.json benchmark-config suite, runnable on one command.

BASELINE.json lists five benchmark config families the new framework is
expected to cover. This script runs ALL of them end-to-end — real
partitioners, real round programs, real models at the stated scales —
and writes BASELINE_SUITE.json with per-case throughput and learning
trajectories:

1. FedAvg · MNIST shapes · LeNet-style CNN · 10 clients IID
2. FedAvg + FedProx · CIFAR-10 shapes · ResNet-20 · 100 clients,
   Dirichlet non-IID
3. SCAFFOLD + FedGATE · CIFAR-10 shapes · ResNet-20 (control-variate /
   gradient-tracking sync)
4. FedCOMGATE (int8) + Qsparse (top-k, error feedback) · compressed
   aggregation at the same CIFAR scale
5. APFL + DRFA · EMNIST shapes (emnist_full, 62-way) · MLP
   (personalized + distributionally-robust minimax)

Zero-egress container: datasets are class-conditional Gaussian synthetics
at the exact shapes/dtypes of the named datasets (real downloads are
gated); every other component — partitioner, engine, algorithm, eval —
is the production path.

Usage:
    python scripts/baseline_suite.py [--smoke] [--cases 1,3,5]
    (JAX_PLATFORMS=cpu for a TPU-free run; --smoke shrinks shapes)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_case(name, *, dataset, shape, classes, arch, clients, alg,
               batch, local_steps, rate, rounds, partition="iid",
               n_per_client=200, momentum=True, **fed_kw):
    return dict(name=name, dataset=dataset, shape=shape, classes=classes,
                arch=arch, clients=clients, alg=alg, batch=batch,
                local_steps=local_steps, rate=rate, rounds=rounds,
                partition=partition, n_per_client=n_per_client,
                momentum=momentum, fed_kw=fed_kw)


def cases(smoke: bool):
    cif = dict(dataset="cifar10", shape=(32, 32, 3), classes=10,
               arch="resnet20", clients=10 if smoke else 100,
               batch=8 if smoke else 50, local_steps=2 if smoke else 10,
               rate=0.5 if smoke else 0.1, rounds=2 if smoke else 8,
               partition="dirichlet", n_per_client=24 if smoke else 200)
    emn = dict(dataset="emnist_full", shape=(28, 28, 1), classes=62,
               arch="mlp", clients=8 if smoke else 30,
               batch=8 if smoke else 32, local_steps=2 if smoke else 10,
               rate=1.0, rounds=2 if smoke else 15, partition="label",
               n_per_client=32 if smoke else 150)
    return [
        build_case("1_fedavg_mnist_cnn_iid", dataset="mnist",
                   shape=(28, 28, 1), classes=10, arch="cnn",
                   clients=10, alg="fedavg", batch=8 if smoke else 50,
                   local_steps=2 if smoke else 10, rate=1.0,
                   rounds=2 if smoke else 20, partition="iid",
                   n_per_client=32 if smoke else 300),
        build_case("2a_fedavg_cifar_resnet20_dirichlet", alg="fedavg",
                   **cif),
        build_case("2b_fedprox_cifar_resnet20_dirichlet", alg="fedprox",
                   **cif),
        # control-variate updates assume plain SGD (see scaffold.py note)
        build_case("3a_scaffold_cifar_resnet20", alg="scaffold",
                   momentum=False, **cif),
        build_case("3b_fedgate_cifar_resnet20", alg="fedgate",
                   momentum=False, **cif),
        build_case("4a_fedcomgate_int8", alg="fedgate", momentum=False,
                   quantized=True, quantized_bits=8, **cif),
        build_case("4b_qsparse_topk", alg="qsparse", momentum=False,
                   compressed=True, compressed_ratio=0.25, **cif),
        build_case("5a_apfl_emnist_mlp", alg="apfl", personal=True,
                   personal_alpha=0.5, **emn),
        build_case("5b_drfa_emnist_mlp", alg="fedavg", drfa=True,
                   drfa_gamma=0.1, **emn),
    ]


def synth_data(shape, classes, n_total, n_test, seed):
    import numpy as np
    rng = np.random.RandomState(seed)
    means = rng.randn(classes, *shape).astype("float32") * 0.8
    y = rng.randint(0, classes, n_total)
    x = means[y] + rng.randn(n_total, *shape).astype("float32")
    ty = rng.randint(0, classes, n_test)
    tx = means[ty] + rng.randn(n_test, *shape).astype("float32")
    return x, y, tx, ty


def run_case(c, dtype):
    import numpy as np
    import jax

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions, \
        train_val_split
    from fedtorch_tpu.data.partition import (
        dirichlet_partition, iid_partition, label_sorted_partition,
    )
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate
    # timed drains fetch-sync (block_until_ready can no-op on the
    # relay — scripts/bench_timing.py / BASELINE_REPRO.md)
    from fedtorch_tpu.utils.tracing import fetch_sync

    C = c["clients"]
    x, y, tx, ty = synth_data(c["shape"], c["classes"],
                              C * c["n_per_client"], 512, seed=11)
    if c["partition"] == "dirichlet":
        parts = dirichlet_partition(y, C, concentration=0.5, seed=1)
        parts = [p for p in parts if len(p)]
    elif c["partition"] == "label":
        parts = label_sorted_partition(y, C, num_class_per_client=4,
                                       seed=1)
    else:
        parts = iid_partition(len(y), C, seed=1)

    fed_kw = dict(c["fed_kw"])
    personal = fed_kw.pop("personal", False)
    cfg = ExperimentConfig(
        data=DataConfig(dataset=c["dataset"], batch_size=c["batch"]),
        federated=FederatedConfig(
            federated=True, num_clients=len(parts),
            online_client_rate=c["rate"], algorithm=c["alg"],
            sync_type="local_step", personal=personal, **fed_kw),
        model=ModelConfig(arch=c["arch"], mlp_hidden_size=200),
        optim=OptimConfig(lr=0.1, in_momentum=c["momentum"],
                          weight_decay=0.0),
        train=TrainConfig(local_step=c["local_steps"]),
        mesh=MeshConfig(compute_dtype=dtype),
    ).finalize()
    val = None
    if personal:
        parts, vparts = train_val_split(parts, 0.2, seed=2)
        val = stack_partitions(x, y, vparts)
    data = stack_partitions(x, y, parts)
    model = define_model(cfg, batch_size=c["batch"])
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data,
                               val_data=val)
    server, clients = trainer.init_state(jax.random.key(0))

    t0 = time.time()
    server, clients, m = trainer.run_round(server, clients)
    fetch_sync(server.params)
    compile_s = time.time() - t0
    first_loss = float(m.train_loss.sum() / m.online_mask.sum())

    t0 = time.time()
    for _ in range(c["rounds"] - 1):
        server, clients, m = trainer.run_round(server, clients)
    fetch_sync(server.params)
    dt = max(time.time() - t0, 1e-9)
    n_chips = int(trainer.mesh.devices.size)
    steps = (c["rounds"] - 1) * trainer.k_online * trainer.local_steps
    last_loss = float(m.train_loss.sum() / m.online_mask.sum())
    res = evaluate(model, server.params, tx, ty, batch_size=256)
    return {
        "ok": bool(np.isfinite(last_loss)),
        "clients": len(parts),
        "steps_per_sec_per_chip": round(steps / dt / n_chips, 2),
        "compile_plus_first_round_s": round(compile_s, 1),
        "first_round_loss": round(first_loss, 4),
        "last_round_loss": round(last_loss, 4),
        "test_top1_after": round(float(res.top1), 4),
        "rounds": c["rounds"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cases", default=None,
                    help="comma-separated case-name prefixes (1,2a,...)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from fedtorch_tpu.utils import enable_compile_cache, \
        honor_platform_env
    honor_platform_env()
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        # the TPU relay can wedge indefinitely inside jax.devices();
        # bench.py's subprocess probe (timeout + retries) detects that
        # without hanging this process. Fall back to CPU with a note
        # rather than blocking the suite forever.
        from bench import probe_device
        if not probe_device():
            log("TPU relay unavailable - running the suite on CPU "
                "(numbers will be low; rerun when the relay recovers)")
            os.environ["JAX_PLATFORMS"] = "cpu"
            honor_platform_env()
    enable_compile_cache()
    import jax

    dtype = "float32"
    if jax.devices()[0].platform not in ("cpu",):
        dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    log(f"devices: {jax.devices()}  compute dtype: {dtype}")

    want = args.cases.split(",") if args.cases else None
    out = {"platform": jax.devices()[0].device_kind,
           "smoke": args.smoke,
           "note": ("class-conditional synthetic shards at the named "
                    "datasets' exact shapes (zero-egress container)"),
           "cases": {}}
    for c in cases(args.smoke):
        if want and not any(c["name"].startswith(w) for w in want):
            continue
        log(f"--- {c['name']} ---")
        t0 = time.time()
        try:
            out["cases"][c["name"]] = run_case(c, dtype)
            log(f"{c['name']}: {out['cases'][c['name']]}")
        except Exception as e:  # record the failure, keep the suite going
            out["cases"][c["name"]] = {"ok": False, "error": repr(e)[:300]}
            log(f"{c['name']}: FAILED {e!r}")
        log(f"({time.time() - t0:.0f}s)")
    path = args.out or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BASELINE_SUITE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"cases_ok": sum(
        1 for v in out["cases"].values() if v.get("ok")),
        "cases_total": len(out["cases"])}), flush=True)


if __name__ == "__main__":
    main()
