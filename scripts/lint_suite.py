#!/usr/bin/env python
"""One lint gate: ruff (generic style) + fedtorch_tpu.lint (TPU
tracing hazards vs the checked-in baseline) + the host-plane
concurrency audit (FTH rules vs lint/concurrency_baseline.json,
FTH001 cycles unbaselineable — lint/concurrency_audit.py) + the
registry-drift checker (FTC rules: metrics catalog, event names,
fault seams, config<->CLI surface, builder-cell matrix, lint-rule
docs tables — lint/registry_audit.py).

Exit status is non-zero when any half reports NEW findings, so CI
and the tier-1 wrapper (tests/test_lint_suite.py) enforce all with a
single entry point:

    python scripts/lint_suite.py            # the gate
    python scripts/lint_suite.py --explain  # rule catalog

ruff is config-gated: the container this repo grows in does not ship
it, so when the executable is absent the generic half is SKIPPED with
a notice (the pyproject [tool.ruff] config is still the contract any
ruff-equipped environment enforces).  The custom analyzer and the
registry checker are stdlib-only and always run; the program-level
HLO audit (which needs jax) lives behind `fedtorch-tpu audit` and
its own tier-1 tests instead (docs/static_analysis.md).
"""
from __future__ import annotations

import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUFF_TARGETS = ("fedtorch_tpu", "scripts", "tests", "bench.py",
                "run_tpu.py")


def run_ruff() -> int | None:
    """ruff check over the configured targets; None = unavailable."""
    exe = shutil.which("ruff")
    if exe is None:
        return None
    proc = subprocess.run([exe, "check", *RUFF_TARGETS], cwd=REPO)
    return proc.returncode


def run_tracing_lint(argv=None) -> int:
    sys.path.insert(0, REPO)
    from fedtorch_tpu.lint.cli import main as lint_main
    return lint_main(argv or [])


def run_concurrency_audit() -> int:
    """The FTH host-plane concurrency half (stdlib-only): FTH001
    hard errors + soft findings not in concurrency_baseline.json."""
    sys.path.insert(0, REPO)
    from fedtorch_tpu.lint.concurrency_audit import concurrency_gate
    new, total = concurrency_gate(REPO)
    for f in new:
        print(f.render())
    return 1 if new else 0


def run_registry_audit() -> int:
    """The FTC registry-drift half (stdlib-only, no baseline: drift
    is fixed at the registry or the emit site, never accepted)."""
    sys.path.insert(0, REPO)
    from fedtorch_tpu.lint.registry_audit import audit_registries
    findings = audit_registries(REPO)
    for f in findings:
        print(f.render())
    return 1 if findings else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--explain":
        return run_tracing_lint(["--explain"])

    failed = False
    ruff_rc = run_ruff()
    if ruff_rc is None:
        print("lint_suite: ruff not installed — generic style half "
              "SKIPPED (pyproject [tool.ruff] is the contract; "
              "install ruff to enforce it)")
    elif ruff_rc != 0:
        print(f"lint_suite: ruff FAILED (rc={ruff_rc})")
        failed = True
    else:
        print("lint_suite: ruff clean")

    lint_rc = run_tracing_lint(argv)
    if lint_rc != 0:
        print("lint_suite: fedtorch_tpu.lint found NEW tracing "
              "hazards (fix them, suppress with a justified "
              "`# lint: disable=...`, or --write-baseline if accepted "
              "— docs/static_analysis.md)")
        failed = True
    else:
        print("lint_suite: fedtorch_tpu.lint clean vs baseline")

    fth_rc = run_concurrency_audit()
    if fth_rc != 0:
        print("lint_suite: host-plane concurrency hazards (FTH) — "
              "fix them, suppress with a justified "
              "`# lint: disable=FTHxxx — why`, or (non-FTH001 only) "
              "accept with `python -m fedtorch_tpu.lint --concurrency "
              "--write-baseline` (docs/static_analysis.md "
              "'The concurrency audit')")
        failed = True
    else:
        print("lint_suite: concurrency audit clean (FTH)")

    ftc_rc = run_registry_audit()
    if ftc_rc != 0:
        print("lint_suite: registry drift (FTC) — fix the catalog, "
              "emit site, docs table or drill it names "
              "(docs/static_analysis.md 'The registry audit')")
        failed = True
    else:
        print("lint_suite: registries in lockstep (FTC clean)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
