"""Per-host auto-restart harness (thin wrapper).

Relaunches a training command with ``--resume <ckpt_dir>`` whenever it
exits with the restartable code 75 (EX_TEMPFAIL) — the code the
preemption drain and the stall watchdog exit with — under an
exponential-backoff, progress-gated retry budget. The logic lives in
``fedtorch_tpu.robustness.harness`` (also exposed as the
``fedtorch-tpu supervise`` subcommand); see docs/robustness.md
"Process lifecycle".

Usage:
    python scripts/run_elastic.py --ckpt_dir /runs/exp1 -- \
        python -m fedtorch_tpu.cli --federated true ... --run_dir /runs/exp1
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.robustness.harness import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
