"""Telemetry overhead A/B -> TELEMETRY_AB.json (docs/observability.md).

Measures what turning ``--telemetry`` on costs a training run: the
SAME round loop the CLI drives (jitted round + the one batched scalar
fetch + the per-round telemetry emissions), A/B'd across
``off`` / ``default`` / ``costs`` / ``cohort_off`` / ``cohort`` /
``debug`` arms on one workload, same seed, best-of-``reps`` wall per
arm. The ``costs`` arm is ``default`` plus the device-side gauges
(measured MFU + the HBM watermark pair from a pre-captured
program_costs — ISSUE 8). The ``cohort`` arm (ISSUE 14) is
``default`` plus the federation-plane observability: the per-client
cohort vectors riding the batched fetch, the ledger fold, and the
cohort row gauges — measured against ``cohort_off`` (the SAME
cohort-stats program under default telemetry, no federation-plane
emission), because ``--cohort_stats`` changes the traced program and
default telemetry holds its own bar via the ``default`` arm; the
combined program+default delta vs bare off is reported separately
(``baseline_frac_vs_off``).
Acceptance bar: ``default`` AND ``costs`` AND ``cohort`` each add
<= 1% to steady-state round wall-time against their baselines
(ISSUE 7/8/14 hard bar) — telemetry that taxes the round clock would
be measuring its own overhead. The ``ledger_memory`` row additionally
proves the ledger's O(min(C, budget)) bound with a synthetic C=10^6
population.

Also records unit costs (ns/span, us/metrics-row, us/health-replace)
so a regression is attributable to a specific emitter.

Presets:
  northstar  ResNet-20, 32x32 class-conditional synthetic, B=50, K=10
             (the certified north-star shape — the on-chip arm
             scripts/tpu_capture.sh 'telemetry' runs)
  host       wide MLP on synthetic rows (CPU-friendly rounds in the
             tens of ms — the committed-artifact arm; a tiny round
             would put the 1% bar at single-digit us and measure
             filesystem noise instead of telemetry)
  smoke      seconds-fast shapes for the slow-lane pytest

Usage:
    python scripts/telemetry_bench.py [--preset auto] [--rounds N]
        [--reps R] [--capture-run DIR]

``--capture-run DIR`` additionally drives one FULL ``run_experiment``
(telemetry default) on the preset's config with ``--run_dir DIR`` so
the run dir's metrics.jsonl + trace.json land as capture artifacts.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TELEMETRY_AB.json")
ACCEPT_OVERHEAD = 0.01  # the <= 1% bar, default verbosity


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_workload(preset: str):
    import numpy as np

    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
        OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions

    rng = np.random.RandomState(7)
    if preset == "northstar":
        C, B, K, n_per = 100, 50, 10, 200
        class_means = rng.randn(10, 32, 32, 3).astype(np.float32) * 0.8
        labels = rng.randint(0, 10, C * n_per)
        feats = class_means[labels] + rng.randn(
            C * n_per, 32, 32, 3).astype(np.float32)
        arch, dataset = "resnet20", "cifar10"
        rate = 0.1
    else:
        # host: rounds in the tens of ms on one CPU core; smoke:
        # seconds-fast for the slow-lane pytest
        C, B, K, n_per = (20, 50, 10, 200) if preset == "host" \
            else (6, 8, 2, 24)
        hidden = 800 if preset == "host" else 32
        dim = 256 if preset == "host" else 16
        labels = rng.randint(0, 10, C * n_per)
        feats = rng.randn(C * n_per, dim).astype(np.float32) \
            + labels[:, None] * 0.05
        arch, dataset = "mlp", "synthetic"
        rate = 0.25 if preset == "host" else 0.5
    parts = [np.arange(i * n_per, (i + 1) * n_per) for i in range(C)]
    data = stack_partitions(feats, labels, parts)
    cfg = ExperimentConfig(
        data=DataConfig(dataset=dataset, batch_size=B,
                        synthetic_dim=feats.shape[-1]),
        federated=FederatedConfig(
            federated=True, num_clients=C, online_client_rate=rate,
            algorithm="fedavg", sync_type="local_step"),
        model=ModelConfig(
            arch=arch,
            **({"mlp_hidden_size": hidden} if arch == "mlp" else {})),
        optim=OptimConfig(lr=0.1, in_momentum=True),
        train=TrainConfig(local_step=K),
    ).finalize()
    return cfg, data


def make_trainer(cfg, data):
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data)


def timed_loop(trainer, rounds: int, tel, run_dir,
               cost_cap=None, ledger=None) -> float:
    """The CLI loop's telemetry-relevant body, per-arm: jitted round,
    ONE batched scalar fetch, row/health emission (plus, on the costs
    arm, the per-round device gauges — measured MFU + the HBM
    watermark pair; on the cohort arm, the per-client cohort vectors
    riding the same fetch + the ledger fold + the cohort gauges).
    Returns seconds for the whole loop, fetch-synced (the per-round
    scalar fetch already materializes host bytes every round — the
    queued-in-order concern does not apply)."""
    import jax

    from fedtorch_tpu.telemetry.critical_path import (
        StreamOverlapTracker,
    )

    server, clients = trainer.init_state(jax.random.key(6))
    # the ops-plane derivation the CLI loop now runs per round
    # (ISSUE 15): a no-op on the device plane, the overlap gauge on
    # the stream plane — included so every arm pays what the loop pays
    overlap = StreamOverlapTracker()
    t0 = time.perf_counter()
    for r in range(rounds):
        rd0 = time.perf_counter()
        with tel.span("round", round=r):
            server, clients, metrics = trainer.run_round(server, clients)
        rt0 = time.perf_counter()
        led = None
        with tel.span("scalar_fetch", round=r):
            if ledger is None:
                sc = trainer.round_host_scalars(clients, metrics)
            else:
                sc_dev, led = jax.device_get(
                    (trainer.round_scalars_dev(clients, metrics),
                     trainer.cohort_fetch_dev(metrics)))
                sc = {k: float(v) for k, v in sc_dev.items()}
        rt1 = time.perf_counter()
        # attribution matches the CLI loop's semantics: round_s is the
        # dispatch-to-completion wall (here the fetch is what blocks
        # on the round, so it closes the round's clock), fetch_s the
        # transfer leg alone — the two must not double-count or a
        # report over the captured run dir prints a bogus breakdown
        fetch_s = rt1 - rt0
        n = max(sc["n_online"], 1.0)
        row = {"round": r, "round_s": rt1 - rd0,
               "loss": sc["loss_sum"] / n,
               "acc": sc["acc_sum"] / n, "lr": sc["lr"],
               "n_online": sc["n_online"],
               "comm_bytes": sc["comm_bytes"],
               "mean_epoch": sc["mean_epoch"], "fetch_s": fetch_s,
               "dropped": sc["dropped"], "stragglers": sc["stragglers"],
               "rejected": sc["rejected"], "clipped": sc["clipped"],
               "staleness": sc["staleness"]}
        if led is not None:
            row["cohort_dispersion"] = sc["cohort_dispersion"]
            nq = led["norm_q"]
            row.update({
                "cohort_norm_min": float(nq[0]),
                "cohort_norm_q25": float(nq[1]),
                "cohort_norm_med": float(nq[2]),
                "cohort_norm_q75": float(nq[3]),
                "cohort_norm_max": float(nq[4])})
            ledger.update(r, led)
            row.update(ledger.stats())
        row.update(trainer.telemetry_gauges())
        eff = overlap.observe(row)
        if eff is not None:
            row["overlap_efficiency"] = eff
        if cost_cap is not None:
            row.update(cost_cap.round_gauges(rt1 - rd0))
        tel.round_row(row)
        tel.health_update("running", round_idx=r + 1,
                          staleness=sc["staleness"])
    return time.perf_counter() - t0


def unit_costs() -> dict:
    """Microbench the emitters in isolation (committed alongside the
    A/B so a future regression names its culprit)."""
    import tempfile

    from fedtorch_tpu.telemetry import Telemetry

    d = tempfile.mkdtemp(prefix="telemetry_unit_")
    tel = Telemetry(d, level="default")
    n = 5000
    t0 = time.perf_counter()
    for i in range(n):
        with tel.span("unit"):
            pass
    span_ns = (time.perf_counter() - t0) / n * 1e9
    row = {"round": 0, "round_s": 0.1, "loss": 1.0, "acc": 0.5,
           "lr": 0.1, "n_online": 5.0, "comm_bytes": 1e6}
    t0 = time.perf_counter()
    for i in range(1000):
        tel.round_row(dict(row, round=i))
    row_us = (time.perf_counter() - t0) / 1000 * 1e6
    t0 = time.perf_counter()
    for i in range(1000):
        tel.health_update("running", round_idx=i)
    health_us = (time.perf_counter() - t0) / 1000 * 1e6
    tel.close()
    # the ledger fold in isolation (dense mode, k=10 online / round):
    # the recurring host cost of the cohort arm minus the fetch — the
    # deterministic evidence when the A/B arms are noise-bound
    import numpy as np

    from fedtorch_tpu.telemetry.ledger import ClientLedger
    led = ClientLedger(tempfile.mkdtemp(prefix="ledger_unit_"),
                       num_clients=100, flush_every=10 ** 9)
    rng = np.random.RandomState(0)
    rounds_vec = [
        {"idx": rng.choice(100, size=10, replace=False),
         "online": np.ones(10), "accept": np.ones(10),
         "selected": np.ones(10), "suspicion": rng.rand(10),
         "staleness": np.zeros(10), "norm_q": np.zeros(5)}
        for _ in range(64)]
    t0 = time.perf_counter()
    for i in range(1000):
        led.update(i, rounds_vec[i % 64])
    ledger_us = (time.perf_counter() - t0) / 1000 * 1e6
    # the ops-plane gauge arm (ISSUE 15), paired per-leg like the
    # cohort verdict: the per-round overlap derivation on a stream-
    # gauge row, and the device-gauge surplus of the two critical-path
    # fields (round_gauges with a captured primary vs the same row
    # maths without them is two float ops — measure the whole gauge
    # call so the number is the honest recurring cost)
    from fedtorch_tpu.telemetry.critical_path import (
        StreamOverlapTracker,
    )
    trk = StreamOverlapTracker()
    srow = {"stream_gather_s": 0.0, "stream_h2d_s": 0.0,
            "stream_wait_s": 0.0}
    t0 = time.perf_counter()
    for i in range(5000):
        srow["stream_gather_s"] = i * 1e-3
        srow["stream_h2d_s"] = i * 5e-4
        srow["stream_wait_s"] = i * 1e-4
        trk.observe(srow)
    overlap_us = (time.perf_counter() - t0) / 5000 * 1e6
    return {"span_ns": round(span_ns, 1),
            "metrics_row_us": round(row_us, 2),
            "health_replace_us": round(health_us, 2),
            "ledger_fold_us": round(ledger_us, 2),
            "overlap_derive_us": round(overlap_us, 3)}


def cohort_fetch_delta_us(trainer_cohort, iters: int = 200) -> float:
    """PAIRED microbench of the one transfer the cohort arm changes:
    ``device_get((scalars, cohort_vectors))`` vs
    ``device_get(scalars)`` on the same materialized round outputs,
    alternated back-to-back so load drift cancels. A 1-core box's
    whole-round A/B has a multi-percent noise envelope; this paired
    per-leg delta resolves the actual microseconds."""
    import jax

    server, clients = trainer_cohort.init_state(jax.random.key(6))
    server, clients, metrics = trainer_cohort.run_round(server, clients)
    jax.block_until_ready(server.params)
    plain = both = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.device_get(trainer_cohort.round_scalars_dev(clients,
                                                        metrics))
        t1 = time.perf_counter()
        jax.device_get((trainer_cohort.round_scalars_dev(clients,
                                                         metrics),
                        trainer_cohort.cohort_fetch_dev(metrics)))
        t2 = time.perf_counter()
        plain += t1 - t0
        both += t2 - t1
    return max(both - plain, 0.0) / iters * 1e6


def ledger_memory(budget: int = 65536, k: int = 64,
                  rounds: int = 50) -> dict:
    """The ledger memory-bound measurement (ISSUE 14 acceptance):
    feed synthetic cohort rows to a dense ledger at a small C and a
    sketch ledger at C=10^6 with the same budget, and record the
    measured footprint — O(min(C, budget)), NOT O(C): the 10^6-client
    sketch must undercut what dense counters would cost at the budget
    population, by orders of magnitude vs dense-at-C."""
    import tempfile

    import numpy as np

    from fedtorch_tpu.telemetry.ledger import (
        LEDGER_COUNTERS, ClientLedger,
    )

    rng = np.random.RandomState(0)
    out = {"budget": budget, "clients_per_round": k, "rounds": rounds}
    dense_at_c = None
    for name, C in (("dense_c4096", 4096), ("sketch_c1e6", 1_000_000)):
        led = ClientLedger(tempfile.mkdtemp(prefix="ledger_mem_"),
                           num_clients=C, sketch_budget=budget,
                           flush_every=10 ** 9)
        t0 = time.perf_counter()
        for r in range(rounds):
            idx = rng.choice(C, size=k, replace=False)
            led.update(r, {
                "idx": idx, "online": np.ones(k), "accept": np.ones(k),
                "selected": np.ones(k), "suspicion": rng.rand(k),
                "staleness": np.zeros(k), "norm_q": np.zeros(5)})
        per_round_us = (time.perf_counter() - t0) / rounds * 1e6
        out[name] = {"clients": C, "mode": led.mode,
                     "bytes": led.memory_bytes(),
                     "tracked": led.tracked(),
                     "update_us_per_round": round(per_round_us, 1)}
        if name == "sketch_c1e6":
            dense_at_c = C * 8 * len(LEDGER_COUNTERS)
    # the bound: the 10^6-client sketch costs O(budget) bytes, not the
    # 56 MB dense counters at C=10^6 would
    out["dense_bytes_at_c1e6"] = dense_at_c
    out["bounded"] = bool(
        out["sketch_c1e6"]["bytes"] < dense_at_c // 10)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="auto",
                    choices=("auto", "northstar", "host", "smoke"))
    ap.add_argument("--rounds", type=int, default=0,
                    help="timed rounds per rep (0 = preset default)")
    ap.add_argument("--reps", type=int, default=3,
                    help="reps per arm; best-of wall is reported")
    ap.add_argument("--capture-run", default=None, metavar="DIR",
                    help="also run the full CLI loop once with "
                         "telemetry default into this run dir "
                         "(metrics.jsonl + trace.json artifacts)")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from fedtorch_tpu.telemetry import Telemetry
    from fedtorch_tpu.utils.tracing import fetch_sync

    preset = args.preset
    if preset == "auto":
        preset = "northstar" if jax.default_backend() == "tpu" else "host"
    rounds = args.rounds or {"northstar": 30, "host": 40, "smoke": 6}[
        preset]
    log(f"devices: {jax.devices()}  preset={preset} rounds={rounds} "
        f"reps={args.reps}")

    cfg, data = build_workload(preset)
    trainer = make_trainer(cfg, data)
    # warmup: compile the round program once, fully drained
    s, c = trainer.init_state(jax.random.key(6))
    s, c, _ = trainer.run_round(s, c)
    fetch_sync(s.params)

    # the cohort arm runs its own trainer: cohort_stats changes the
    # traced program (per-client outputs at the aggregation seam), so
    # the arm measures the WHOLE federation-plane observability cost —
    # in-program stats + the [k] vectors on the fetch + the ledger
    # fold + the extra row gauges — against the same <= 1% bar
    import dataclasses
    cfg_cohort = dataclasses.replace(
        cfg, telemetry=dataclasses.replace(cfg.telemetry,
                                           cohort_stats=True))
    trainer_cohort = make_trainer(cfg_cohort, data)
    s2, c2 = trainer_cohort.init_state(jax.random.key(6))
    s2, c2, _ = trainer_cohort.run_round(s2, c2)
    fetch_sync(s2.params)
    del s2, c2

    import tempfile

    # the costs arm: program_costs captured ONCE up front (the real
    # CLI loop pays that once at round 1, outside steady state), then
    # every row additionally carries the measured-MFU + HBM-watermark
    # gauges — the RECURRING per-round cost this arm measures against
    # the same <=1% bar (ISSUE 8)
    from fedtorch_tpu.telemetry.costs import ProgramCostCapture
    cost_cap = ProgramCostCapture(
        tempfile.mkdtemp(prefix="telemetry_ab_costs_"),
        compute_dtype="float32", arch=cfg.model.arch,
        batch_size=cfg.data.batch_size, local_steps=trainer.local_steps,
        k_online=trainer.k_online,
        num_devices=int(trainer.mesh.devices.size),
        backend=jax.default_backend(), log=log)
    s0, c0 = trainer.init_state(jax.random.key(6))
    programs, primary = trainer.lowered_cost_programs(s0, c0)
    cost_cap.capture(programs, primary=primary)
    del s0, c0

    # cohort_off = the cohort-stats PROGRAM under DEFAULT telemetry
    # with no federation-plane emission: the cohort arm's baseline.
    # cohort_stats changes the traced program (in-jit statistics at
    # the aggregation seam) and default telemetry has its own
    # separately-measured bar (the 'default' arm), so cohort vs
    # cohort_off isolates exactly what ISSUE 14's <= 1% bar governs:
    # the [k] cohort vectors riding the fetch + the ledger fold + the
    # cohort row gauges. The program change itself is reported as
    # program_frac_vs_off (informational: round compute, not
    # telemetry; a vision-scale round amortizes it where this
    # tiny-MLP arm cannot)
    levels = ("off", "default", "costs", "cohort_off", "cohort",
              "debug")
    walls = {lv: [] for lv in levels}
    # reps INTERLEAVED across arms: slow host-noise drift (another
    # tenant, thermal state) then biases every arm equally instead of
    # landing on whichever arm ran last; best-of-reps per arm rejects
    # the one-sided noise that remains
    for rep in range(args.reps):
        for level in levels:
            run_dir = tempfile.mkdtemp(prefix=f"telemetry_ab_{level}_")
            tel = Telemetry(None if level == "off" else run_dir,
                            level="default" if level in (
                                "costs", "cohort", "cohort_off")
                            else level)
            tel.install()
            try:
                if level == "cohort":
                    from fedtorch_tpu.telemetry.ledger import (
                        ClientLedger,
                    )
                    led_obj = ClientLedger(
                        run_dir,
                        num_clients=cfg.federated.num_clients)
                    wall = timed_loop(trainer_cohort, rounds, tel,
                                      run_dir, ledger=led_obj)
                elif level == "cohort_off":
                    wall = timed_loop(trainer_cohort, rounds, tel,
                                      run_dir)
                else:
                    wall = timed_loop(
                        trainer, rounds, tel, run_dir,
                        cost_cap=cost_cap if level == "costs"
                        else None)
            finally:
                tel.close()
            walls[level].append(wall)
            log(f"  rep{rep} {level}: {wall / rounds * 1e3:.3f} "
                "ms/round")
    arms = {lv: {"wall_s": min(walls[lv]),
                 "per_round_s": min(walls[lv]) / rounds,
                 "reps_ms_per_round": [round(w / rounds * 1e3, 3)
                                       for w in walls[lv]]}
            for lv in levels}

    base = arms["off"]["per_round_s"]
    for level in ("default", "costs", "debug"):
        arms[level]["overhead_frac"] = \
            (arms[level]["per_round_s"] - base) / base
    cbase = arms["cohort_off"]["per_round_s"]
    arms["cohort"]["overhead_frac"] = \
        (arms["cohort"]["per_round_s"] - cbase) / cbase
    # informational: the cohort PROGRAM + default telemetry vs the
    # bare off arm (round compute the stats add, not telemetry cost)
    arms["cohort_off"]["baseline_frac_vs_off"] = (cbase - base) / base
    # the cohort bar is JUDGED on the paired per-leg measurement: the
    # federation-plane additions are microseconds (vector-fetch delta
    # + ledger fold + gauge row surplus) and a whole-round A/B on a
    # shared 1-core box carries a multi-percent noise envelope that
    # swamps them — overhead_frac above stays recorded as the
    # (noise-bound) A/B evidence, host_frac_measured is the verdict
    uc = unit_costs()
    fetch_delta = cohort_fetch_delta_us(trainer_cohort)
    cohort_host_us = fetch_delta + uc["ledger_fold_us"] \
        + uc["metrics_row_us"]
    arms["cohort"]["fetch_delta_us"] = round(fetch_delta, 2)
    arms["cohort"]["host_us_per_round"] = round(cohort_host_us, 2)
    arms["cohort"]["host_frac_measured"] = \
        cohort_host_us * 1e-6 / cbase
    led_mem = ledger_memory()
    # the ops-plane gauges (ISSUE 15) ride the costs/default arms
    # above (timed_loop now runs the overlap tracker like the CLI
    # loop); the paired per-leg verdict is the derivation's own
    # measured microseconds against the off baseline
    ops = {"overlap_derive_us": uc["overlap_derive_us"],
           "host_frac_measured": uc["overlap_derive_us"] * 1e-6 / base}
    ok = (arms["default"]["overhead_frac"] <= ACCEPT_OVERHEAD
          and arms["costs"]["overhead_frac"] <= ACCEPT_OVERHEAD
          and arms["cohort"]["host_frac_measured"] <= ACCEPT_OVERHEAD
          and ops["host_frac_measured"] <= ACCEPT_OVERHEAD
          and led_mem["bounded"])

    result = {
        "preset": preset,
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "rounds": rounds,
        "reps": args.reps,
        "arms": arms,
        "unit_costs": uc,
        "ops_gauges": ops,
        "ledger_memory": led_mem,
        "accept_overhead_frac": ACCEPT_OVERHEAD,
        "pass": bool(ok),
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    log(f"off {base * 1e3:.3f} ms/round; default "
        f"{arms['default']['per_round_s'] * 1e3:.3f} ms/round "
        f"({arms['default']['overhead_frac'] * 100:+.3f}%); costs "
        f"{arms['costs']['overhead_frac'] * 100:+.3f}%; cohort "
        f"{arms['cohort']['host_frac_measured'] * 100:+.4f}% measured "
        f"({arms['cohort']['host_us_per_round']} us/round; A/B arm "
        f"{arms['cohort']['overhead_frac'] * 100:+.2f}%, baseline "
        f"{arms['cohort_off']['baseline_frac_vs_off'] * 100:+.2f}% vs "
        "off); debug "
        f"{arms['debug']['overhead_frac'] * 100:+.3f}%  "
        f"ledger@1e6 {led_mem['sketch_c1e6']['bytes']} B  pass={ok}")
    log(f"wrote {args.out}")

    if args.capture_run:
        # the artifact leg: one telemetry-on pass over the SAME
        # workload into a persistent run dir — metrics.jsonl +
        # trace.json (Perfetto) land as capture artifacts without a
        # dataset loader (the north-star data here is synthetic by
        # construction; zero-egress container)
        os.makedirs(args.capture_run, exist_ok=True)
        cap_rounds = min(rounds, 10)
        tel = Telemetry(args.capture_run, level="default",
                        run_meta={"preset": preset,
                                  "source": "telemetry_bench"})
        tel.install()
        try:
            timed_loop(trainer, cap_rounds, tel, args.capture_run)
            tel.health_update("complete", round_idx=cap_rounds)
        finally:
            tel.close()
        log(f"capture run -> {args.capture_run} "
            f"({cap_rounds} rounds of metrics.jsonl + trace.json)")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
