#!/bin/bash
# Round-5 stage 6 (replaces tpu_capture_r5e.sh): after the recovery
# stage (tpu_capture_r5d.sh) drains, finish the round's on-chip queue:
#   1. RE-RUN the two flash stages that failed in r5d — the old kernel
#      was rejected by Mosaic's block-mapping check; the fix (lane-
#      broadcast lse/stats, commit a3877b1) landed mid-chain, after
#      r5d's zoo stage already proved the fixed kernel executes
#      on-chip (transformer_flash_moe_bf16 green).
#   2. VALIDATE the final re-persist: bench.py exits 0 on a CPU
#      fallback without touching TPU_BENCH_CAPTURE.json, so r5d's
#      last stage can silently no-op; re-persist at the current head
#      if the capture is stale and the relay answers.
#   3. CERTIFY the wedge-replay path against the REAL capture
#      (VERDICT r4 item #3), WEDGE_MIN_CAPTURED_UNIX pinned to this
#      round's start so only a round-5 capture can satisfy it.
#     nohup bash scripts/tpu_capture_r5f.sh > /tmp/tpu_capture_r5f.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5D_DONE=/tmp/tpu_capture_r5d.done
R5F_DONE=/tmp/tpu_capture_r5f.done
rm -f "$R5F_DONE"              # stale-sentinel hygiene (review r5)
trap 'touch "$R5F_DONE"' EXIT

wait_for_done "$R5D_DONE"
echo "[tpu_capture_r5f] recovery stage done — probing"
if ! probe_relay 5; then
    echo "[tpu_capture_r5f] relay dead; flash re-run not captured"
else
    FAILED=0
    run python scripts/pallas_tpu_check.py      # -> PALLAS_TPU.json (flash under real Mosaic, fixed kernel)
    run python scripts/flash_train_bench.py     # -> FLASH_TRAIN.json
    run python scripts/seqpar_tpu_probe.py      # -> SEQPAR_TPU_PROBE.json (zoo seqpar_1chip 0.078 divergence: MXU precision or bug?)
    run env ZOO_ONLY=seqpar python scripts/tpu_zoo_check.py  # re-validate seqpar_1chip under the pinned-precision check; merges into TPU_ZOO.json
    echo "[tpu_capture_r5f] flash re-run + seqpar probe done (failed=$FAILED)"
fi

# Round-5 started 2026-07-31T01:53Z (commit 24a437a); any real capture
# after that is this round's. Rounds 3-4 had zero captures, so the
# stamp only has to exclude the round-2 session.
ROUND5_START_UNIX=1785462780

capture_head() {
    python - <<'EOF'
import json
try:
    with open("TPU_BENCH_CAPTURE.json") as f:
        print(json.load(f).get("git_head", ""))
except Exception:
    print("")
EOF
}

HEAD_NOW="$(git rev-parse HEAD)"
CAP_HEAD="$(capture_head)"
if [ "$CAP_HEAD" != "$HEAD_NOW" ]; then
    echo "[tpu_capture_r5f] capture head $CAP_HEAD != HEAD $HEAD_NOW — re-persisting"
    BENCH_PROBE_TRIES=3 python bench.py
    CAP_HEAD="$(capture_head)"
    if [ "$CAP_HEAD" != "$HEAD_NOW" ]; then
        echo "[tpu_capture_r5f] re-persist did NOT refresh the capture (relay wedged?); the prior-head capture stands (ancestry-validated at replay time)"
    fi
fi

WEDGE_MIN_CAPTURED_UNIX="$ROUND5_START_UNIX" \
    python scripts/wedge_replay_check.py
rc=$?
echo "[tpu_capture_r5f] wedge_replay_check rc=$rc (0=verified, 2=no eligible capture)"
echo "[tpu_capture_r5f] done"
exit $rc
