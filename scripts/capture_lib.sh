# Shared helpers for the capture entry points. Source, don't execute.
#
# Stage ordering uses DONE-SENTINEL files, not pgrep: a pgrep poll
# reads "predecessor not started yet" as "finished" and would let two
# stages probe the single-session relay concurrently (the documented
# wedge trigger). A chained stage traps EXIT to touch its sentinel;
# the launcher removes stale sentinels before starting a fresh chain.

wait_for_done() {
    while [ ! -f "$1" ]; do
        sleep 120
    done
}

# Shared stage-runner helper (review r5: run was copied verbatim
# across r4/r5 stage scripts; new stages call this one).
# Callers set FAILED=0 before the first call.
run() {
    echo "=== $* ==="
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    if [ $rc -ne 0 ]; then FAILED=1; fi
    return $rc
}

# One short-patience relay probe; returns 0 iff the relay answers.
probe_relay() {
    BENCH_PROBE_TRIES="${1:-3}" python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
}

# Lowering-A/B variant stage. The function names predate the round-5
# default flip (the deleted r5/r5c stage chains called them by name
# while running when the flip landed): post-flip the shipped default
# 'auto' resolves to
# native conv on TPU, so the VARIANT side of the on-chip A/B is now
# the im2col matmul lowering -> BENCH_MATMULSIDE_AB.json. The round-5
# first-window pair was captured under the pre-flip default (default
# bench = matmul -> preserved as BENCH_MATMULSIDE_AB.json;
# BENCH_CONV_IMPL=conv variant -> BENCH_CONVSIDE_AB.json).
capture_conv_side() {
    # Rejects a partial record (nonzero bench status), a relay-wedged
    # CPU-fallback record (bench exits 0 on fallback), AND a cached
    # replay of a prior capture ("cached": true — bench replays the
    # persisted capture when the relay wedges at report time; a replay
    # of an old run must not be saved as if freshly measured) — none
    # may sit under an on-chip A/B filename.
    echo "=== matmul-variant bench A/B -> BENCH_MATMULSIDE_AB.json ==="
    BENCH_PROBE_TRIES=2 env BENCH_CONV_IMPL=matmul python bench.py \
        | tee BENCH_MATMULSIDE_AB.json
    local rc=${PIPESTATUS[0]}
    if [ "$rc" -ne 0 ] || ! _ab_side_valid BENCH_MATMULSIDE_AB.json
    then
        rm -f BENCH_MATMULSIDE_AB.json
        rc=1
    fi
    echo "=== matmul-variant rc=$rc ==="
    return "$rc"
}

conv_side_captured() {
    # "is the non-default side of the on-chip A/B already recorded?"
    # Post-flip the non-default lowering is matmul, so ONLY the
    # matmul-side artifact satisfies this — a surviving legacy
    # BENCH_CONVSIDE_AB.json records what is now the DEFAULT side
    # (the default bench capture already covers it) and must not
    # suppress capturing the matmul variant in an open window.
    _ab_side_valid BENCH_MATMULSIDE_AB.json
}

_ab_side_valid() {
    [ -s "$1" ] && ! grep -q "CPU fallback" "$1" \
        && ! grep -q '"cached": true' "$1"
}
