# Shared helpers for the round-5 capture chain. Source, don't execute.
#
# Stage ordering uses DONE-SENTINEL files, not pgrep: a pgrep poll
# reads "predecessor not started yet" as "finished" and would let two
# stages probe the single-session relay concurrently (the documented
# wedge trigger). Each stage traps EXIT to touch its sentinel; the
# launcher removes stale sentinels before starting a fresh chain.

R5_DONE=/tmp/tpu_capture_r5.done
R5B_DONE=/tmp/tpu_capture_r5b.done

wait_for_done() {
    while [ ! -f "$1" ]; do
        sleep 120
    done
}

capture_conv_side() {
    # grouped-conv side of the lowering A/B -> BENCH_CONVSIDE_AB.json.
    # Rejects a partial record (nonzero bench status) AND a
    # relay-wedged CPU-fallback record (bench exits 0 on fallback) —
    # neither may sit under an on-chip A/B filename.
    echo "=== conv-side bench A/B -> BENCH_CONVSIDE_AB.json ==="
    BENCH_PROBE_TRIES=2 env BENCH_CONV_IMPL=conv python bench.py \
        | tee BENCH_CONVSIDE_AB.json
    local rc=${PIPESTATUS[0]}
    if [ "$rc" -ne 0 ] \
            || grep -q "CPU fallback" BENCH_CONVSIDE_AB.json; then
        rm -f BENCH_CONVSIDE_AB.json
        rc=1
    fi
    echo "=== conv-side rc=$rc ==="
    return "$rc"
}

conv_side_captured() {
    [ -s BENCH_CONVSIDE_AB.json ] \
        && ! grep -q "CPU fallback" BENCH_CONVSIDE_AB.json
}
