#!/bin/bash
# Wait for the TPU relay, then capture the FULL round-3 measurement
# list sequentially (supersedes tpu_capture.sh's list; one relay
# session, strictly serial — the 1-core host and single-session relay
# both forbid concurrency). Run in the background from the repo root:
#     nohup bash scripts/tpu_capture_full.sh > /tmp/tpu_capture.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

TRIES="${TPU_CAPTURE_WAIT_TRIES:-85}"   # ~5.7 h of patience by default

echo "[tpu_capture_full] waiting for the relay (up to ${TRIES}x120s probes)"
BENCH_PROBE_TRIES="$TRIES" python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture_full] relay never recovered; nothing captured"
    exit 1
fi

echo "[tpu_capture_full] relay alive — capturing (sequential)"
FAILED=0
run() {
    echo "=== $* ==="
    # probes are already done; don't let per-script probes re-wait long
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

# A/B variants FIRST; the defaults run LAST so the persisted
# TPU_BENCH_CAPTURE.json (wedged-relay report fallback) is the
# default-config number, not a variant's
run env BENCH_SINGLE_DISPATCH=0 python bench.py  # dispatch A/B
run env BENCH_SCAN_UNROLL=4 python bench.py      # unroll A/B
run python bench.py                              # -> TPU_BENCH_CAPTURE.json
run python scripts/tpu_zoo_check.py              # -> TPU_ZOO.json
run python scripts/pallas_tpu_check.py           # -> PALLAS_TPU.json (flash)
run python scripts/flash_train_bench.py          # -> FLASH_TRAIN.json
run python scripts/vmap_penalty_bench.py         # -> VMAP_PENALTY.json
run python scripts/baseline_suite.py             # -> BASELINE_SUITE.json
echo "[tpu_capture_full] done (failed=$FAILED)"
exit $FAILED
