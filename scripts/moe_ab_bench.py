"""Sparse-MoE dispatch A/B on the real TPU (VERDICT r3 #6).

The framework's Switch-MoE transformer defaults to EXACT dense dispatch
(every token visits every expert — E x the MLP FLOPs, bit-stable) with
sparse capacity dispatch (`capacity_factor > 0`, O(capacity) FLOPs,
over-capacity tokens dropped to the residual) as opt-in. The perf-
relevant mode at scale is sparse, but no measurement on any hardware has
shown the capacity-factor cost/quality trade actually realized.

This script times full training steps (loss incl. Switch aux loss +
backward + SGD, jitted, bf16) of an E=16 Switch transformer:

  dense        capacity_factor=0   (the exactness oracle)
  cf1.0 / cf1.25 / cf2.0           (sparse, growing capacity headroom)

reporting per-config step time, measured per-layer drop fraction, and a
short same-seed loss trajectory (sparse must track dense closely while
costing a fraction of its step time — that is the case for flipping the
recommended large-E training config to sparse).

Writes MOE_AB.json; prints one JSON line. Relay-gated (main() refuses
to record if the backend resolves to CPU). To smoke-test the plumbing
off-chip, do NOT run main() (its probe opens a relay session): import
``run_case`` directly under a cpu-forced interpreter (set
JAX_PLATFORMS=cpu, call fedtorch_tpu.utils.honor_platform_env() first,
then run_case("dense", 0.0) with the MOE_AB_* size overrides).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.telemetry.costs import FLOPS_XLA, lowered_cost


def log(*a):
    print(*a, file=sys.stderr, flush=True)


B = int(os.environ.get("MOE_AB_BATCH", "8"))
T = int(os.environ.get("MOE_AB_SEQ", "256"))
E = int(os.environ.get("MOE_AB_EXPERTS", "16"))
D_MODEL, HEADS, LAYERS, VOCAB = 256, 8, 4, 256
ITERS = int(os.environ.get("MOE_AB_ITERS", "10"))
LOSS_STEPS = int(os.environ.get("MOE_AB_LOSS_STEPS", "30"))
AUX_WEIGHT = 0.01


def run_case(name, capacity_factor):
    import jax
    import jax.numpy as jnp
    import optax

    from fedtorch_tpu.models.transformer import (
        TransformerLM, drop_fractions,
    )

    model = TransformerLM(vocab_size=VOCAB, d_model=D_MODEL,
                          num_heads=HEADS, num_layers=LAYERS,
                          max_len=T, dtype="bfloat16", num_experts=E,
                          capacity_factor=capacity_factor)
    toks = jax.random.randint(jax.random.key(1), (B, T), 0, VOCAB)
    tgts = jnp.roll(toks, -1, axis=1)
    # same init for every case: the dispatch mode is the only variable
    params = TransformerLM(
        vocab_size=VOCAB, d_model=D_MODEL, num_heads=HEADS,
        num_layers=LAYERS, max_len=T, dtype="bfloat16", num_experts=E,
    ).init(jax.random.key(0), toks)["params"]
    opt = optax.sgd(0.05)

    @jax.jit
    def train_step(params, state):
        def loss_fn(p):
            logits, mods = model.apply(
                {"params": p}, toks, mutable=["aux_loss"])
            logp = jax.nn.log_softmax(logits)
            ce = -jnp.mean(jnp.take_along_axis(
                logp, tgts[..., None], axis=-1))
            aux = sum(jnp.sum(v) for v in
                      jax.tree.leaves(mods.get("aux_loss", {})))
            return ce + AUX_WEIGHT * aux, ce

        (loss, ce), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        upd, state = opt.update(g, state)
        return optax.apply_updates(params, upd), state, ce

    state = opt.init(params)
    t0 = time.time()
    params, state, ce = train_step(params, state)
    jax.block_until_ready(ce)
    compile_s = time.time() - t0

    # executed FLOPs per step from XLA cost analysis (the shared
    # telemetry.costs extractor): the dense-vs-sparse FLOPs ratio is
    # hardware-independent evidence even when the wall-clock is
    # measured off-chip (VERDICT r4 #6). Persistent compile cache
    # makes the AOT re-compile cheap.
    try:
        step_flops = lowered_cost(
            train_step.lower(params, state)).get("flops")
    except Exception:
        step_flops = None

    # keep device arrays (no host sync inside the timed loop) so the
    # loss trajectory starts at step 1, not after the warmup steps
    loss_dev = [ce]
    t0 = time.time()
    for _ in range(ITERS):
        params, state, ce = train_step(params, state)
        loss_dev.append(ce)
    jax.block_until_ready(ce)
    step_ms = (time.time() - t0) / ITERS * 1e3

    for _ in range(LOSS_STEPS - ITERS - 1):
        params, state, ce = train_step(params, state)
        loss_dev.append(ce)
    losses = [float(x) for x in loss_dev]

    drops = drop_fractions(model, params, toks)
    drop = {k: round(float(v), 4) for k, v in drops.items()}
    row = {"capacity_factor": capacity_factor,
           "step_ms": round(step_ms, 2),
           "flops_per_step": step_flops,
           "flops_source": FLOPS_XLA if step_flops else None,
           "compile_s": round(compile_s, 1),
           "final_ce": round(losses[-1], 4),
           "loss_first5": [round(x, 4) for x in losses[:5]],
           "drop_fraction_per_layer": drop,
           "max_drop_fraction": round(max(drop.values()), 4)
           if drop else 0.0}
    log(f"{name:7s}: {step_ms:8.2f} ms/step  ce={losses[-1]:.4f}  "
        f"max_drop={row['max_drop_fraction']:.3f}  "
        f"(compile {compile_s:.0f}s)")
    return row


def main():
    from bench import probe_device
    if not probe_device():
        log("TPU relay unavailable — dispatch cost is only meaningful "
            "on the chip; nothing recorded")
        return 1
    import jax
    from fedtorch_tpu.utils import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    log(f"device: {dev}")
    if dev.platform == "cpu":
        # fast relay-init failure -> silent cpu fallback; a CPU step
        # time labeled as the dispatch cost would mislead the A/B
        log("backend resolved to CPU despite a passing probe — refusing "
            "to record the A/B")
        return 1

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = {"platform": str(dev),
               "config": {"batch": B, "seq": T, "experts": E,
                          "d_model": D_MODEL, "layers": LAYERS,
                          "dtype": "bfloat16",
                          "loss_steps": LOSS_STEPS},
               "cases": {}}
    for name, cf in (("dense", 0.0), ("cf1.0", 1.0),
                     ("cf1.25", 1.25), ("cf2.0", 2.0)):
        try:
            results["cases"][name] = run_case(name, cf)
        except Exception as e:
            results["cases"][name] = {"error": str(e)[:300]}
            log(f"{name}: FAIL {str(e)[:160]}")
        with open(os.path.join(repo, "MOE_AB.json"), "w") as f:
            json.dump(results, f, indent=1)

    dense = results["cases"].get("dense", {})
    sparse = results["cases"].get("cf1.25", {})
    speedup = None
    if "step_ms" in dense and "step_ms" in sparse:
        speedup = round(dense["step_ms"] / sparse["step_ms"], 2)
    print(json.dumps({"moe_ab_ok": "step_ms" in dense,
                      "sparse_cf1.25_speedup_vs_dense": speedup,
                      "platform": str(dev)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
