"""Streaming-data-plane A/B: `data_plane='device'` vs `'stream'`,
plus the SCANNED-STREAM arm (ISSUE 11).

Measures, per plane, on the north-star-shaped workload:

* steady-state round wall-time (fetch-synced — bench_timing.sync);
* bytes moved host→device per round (stream: one packed feed; device:
  zero steady-state — the store is resident, that residency being the
  thing the stream plane trades away);
* device residency (utils.tracing.live_buffer_summary — works on every
  platform — plus device_memory_stats where the allocator reports);
* retraces during the timed window (the recompilation sentinel: the
  streamed round program must trace exactly once, in warmup);
* bitwise parity of the two planes' server params after the A/B.

The acceptance bar (ISSUE 5): steady-state streamed round wall-time
within 10% of device-resident when feed-build+transfer < round compute
— i.e. the round-ahead prefetch actually hides the transfer.

The scanned-stream arm (`run_rounds` on the stream plane — the
round-program builder's feed x scan cell) times window sizes
R in {1, 4, 16}: the producer packs an [R, k, K·B, ...] feed window
while the device scans the previous one, so the stream plane gets the
single-dispatch lever on top of the producer overlap. Each window row
records per-round wall-time, the retrace count (must be 0 past the
one warmup trace per R) and bitwise parity against the DEVICE plane's
scan of the same round sequence; the headline ratios are
`stream_scan_over_stream` (scan must beat per-round stream) and
`stream_scan_over_device_walltime` (the stream-vs-device gap the scan
lever exists to close).

Writes STREAM_AB.json (STREAM_AB_PATH overrides, for the test smoke).
STREAM_BENCH_SMOKE=1 shrinks the workload for CPU CI;
STREAM_BENCH_ARCH overrides the model (e.g. `mlp` for a CPU-feasible
full-population capture — the resnet20 default is the on-chip
`stream` capture-step workload).

THE POPULATION-SCALING ARM (`STREAM_BENCH_POPULATION=1`) replaces the
plane A/B with the million-client drill (docs/performance.md "The
million-client store"): for C in {10^3, 10^5, 10^6} it materializes a
synthetic population to the sharded on-disk store (MmapStoreWriter,
chunked — the 10^6 population never exists in RAM), runs the stream
plane with `data.store='mmap'` + `participation_mode='sparse'` at a
FIXED online cohort k, and records steady round wall, retrace count
and the store-residency gauges. Acceptance: round wall flat in C
(10^6 within 10% of 10^3), host residency O(feed) not O(C) (the
resident gauge holds the sizes vector only while the mapped gauge
scales with C), bitwise parity mmap-vs-RAM at the common C, zero
retraces. Writes MILLION_CLIENT_AB.json (MILLION_CLIENT_AB_PATH
overrides) plus two compare-able run dirs (POPULATION_RUNS_DIR,
default artifacts/population_ab/{a,b} = smallest/largest C) that the
`population` capture step gates via `fedtorch-tpu compare --gate
tests/data/ops_runs/population_gates.json`.

Run:  python scripts/stream_bench.py
"""
from __future__ import annotations

import gc
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from fedtorch_tpu.utils import enable_compile_cache, \
    honor_platform_env  # noqa: E402

honor_platform_env()  # the site hook may pin jax_platforms to the proxy
enable_compile_cache()

from bench_timing import sync  # noqa: E402
from fedtorch_tpu.algorithms import make_algorithm  # noqa: E402
from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data.batching import stack_partitions  # noqa: E402
from fedtorch_tpu.data.streaming import feed_nbytes  # noqa: E402
from fedtorch_tpu.models import define_model  # noqa: E402
from fedtorch_tpu.parallel import FederatedTrainer  # noqa: E402
from fedtorch_tpu.utils.tracing import (  # noqa: E402
    RecompilationSentinel, device_memory_stats, live_buffer_summary,
)

SMOKE = os.environ.get("STREAM_BENCH_SMOKE") == "1"
# smoke: tiny MLP on MNIST-shaped synthetic rows; full: the north-star
# resnet20/cifar10-shaped workload (bench.py's config, per-round mode).
# STREAM_BENCH_ARCH overrides the full arch (a CPU-box full-population
# capture uses `mlp`; the default stays the on-chip workload).
NUM_CLIENTS = 16 if SMOKE else 100
BATCH = 8 if SMOKE else 50
K = 2 if SMOKE else 10
SPC = 64 if SMOKE else 250
ROUNDS = 3 if SMOKE else 20
ONLINE = 0.25 if SMOKE else 0.1
ARCH = "mlp" if SMOKE else os.environ.get("STREAM_BENCH_ARCH",
                                          "resnet20")
DATASET = "mnist" if (SMOKE or ARCH == "mlp") else "cifar10"
FEAT_SHAPE = (784,) if (SMOKE or ARCH == "mlp") else (32, 32, 3)
# scanned-stream window sizes (the feed x scan cell)
SCAN_WINDOWS = (1, 4) if SMOKE else (1, 4, 16)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build(plane: str):
    cfg = ExperimentConfig(
        data=DataConfig(dataset=DATASET, batch_size=BATCH,
                        data_plane=plane, augment=False),
        federated=FederatedConfig(
            federated=True, num_clients=NUM_CLIENTS,
            online_client_rate=ONLINE, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch=ARCH, mlp_num_layers=2,
                          mlp_hidden_size=128),
        optim=OptimConfig(lr=0.1, in_momentum=not SMOKE),
        train=TrainConfig(local_step=K),
        mesh=MeshConfig(),
    ).finalize()
    rng = np.random.RandomState(0)
    feats = rng.randn(NUM_CLIENTS * SPC,
                      *FEAT_SHAPE).astype(np.float32)
    labels = rng.randint(0, 10, NUM_CLIENTS * SPC)
    parts = [np.arange(i * SPC, (i + 1) * SPC)
             for i in range(NUM_CLIENTS)]
    data = stack_partitions(feats, labels, parts)
    model = define_model(cfg, batch_size=BATCH)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data)


def timed(tr):
    server, clients = tr.init_state(jax.random.key(0))
    server, clients, _ = tr.run_round(server, clients)
    sync(server.params)  # compile + first feed fully drained
    residency = live_buffer_summary()
    hbm = device_memory_stats()
    with RecompilationSentinel() as sentinel:
        t0 = time.perf_counter()
        for _ in range(ROUNDS):
            server, clients, _ = tr.run_round(server, clients)
        sync(server.params)
        dt = (time.perf_counter() - t0) / ROUNDS
    retraces = sum(sentinel.counts.values())
    params = jax.device_get(server.params)
    tr.invalidate_stream()
    return dt, residency, hbm, retraces, params


def main():
    devs = jax.devices()
    log(f"devices: {len(devs)} x {devs[0].platform}")
    out = {
        "platform": f"{len(devs)} x {devs[0].device_kind}",
        "config": {"clients": NUM_CLIENTS, "batch": BATCH, "K": K,
                   "rows_per_client": SPC, "arch": ARCH,
                   "rounds_timed": ROUNDS, "smoke": SMOKE},
        "modes": {},
    }
    finals = {}
    for plane in ("device", "stream"):
        gc.collect()
        base_bytes = live_buffer_summary()["total_bytes"]
        tr = build(plane)
        feed_bytes = 0
        if plane == "stream":
            # one packed feed = the unit of steady-state H2D traffic
            # AND of device data residency (x the double buffer)
            kb = tr.local_steps * tr.batch_size
            feed_bytes = feed_nbytes(tr.host_store.pack(
                np.arange(tr.k_online),
                np.zeros((tr.k_online, kb), np.int64), tr.batch_size))
        dt, residency, hbm, retraces, params = timed(tr)
        store_mb = tr.host_store.nbytes / 2**20 if plane == "stream" \
            else sum(np.asarray(leaf).nbytes for leaf in
                     jax.tree.leaves(tr.data.x)) / 2**20
        out["modes"][plane] = {
            "ms_per_round": round(dt * 1e3, 2),
            "h2d_mb_per_round": round(feed_bytes / 2**20, 3)
            if plane == "stream" else 0.0,
            "client_store_mb": round(store_mb, 2),
            "live_device_bytes_after_warmup": max(
                residency["total_bytes"] - base_bytes, 0),
            "retraces_during_timed_rounds": retraces,
        }
        if hbm:
            peak = max(v.get("peak_bytes_in_use") or 0
                       for v in hbm.values())
            out["modes"][plane]["peak_hbm_bytes"] = int(peak)
        finals[plane] = params
        log(f"{plane:6s}: {dt*1e3:8.2f} ms/round, "
            f"{residency['total_bytes']/2**20:7.1f} MB live on device, "
            f"{retraces} retraces")
        del tr
    # -- scanned-stream arm (the builder's feed x scan cell) -----------
    scan_rows = {}
    feed_mb = out["modes"]["stream"]["h2d_mb_per_round"]
    for R in SCAN_WINDOWS:
        gc.collect()
        tr = build("stream")
        calls = max(1, ROUNDS // R)
        server, clients = tr.init_state(jax.random.key(0))
        server, clients, _ = tr.run_rounds(server, clients, R)
        sync(server.params)  # compile + first window drained
        with RecompilationSentinel() as sentinel:
            t0 = time.perf_counter()
            for _ in range(calls):
                server, clients, _ = tr.run_rounds(server, clients, R)
            sync(server.params)
            dt = (time.perf_counter() - t0) / (calls * R)
        retraces = sum(sentinel.counts.values())
        params = jax.device_get(server.params)
        tr.invalidate_stream()
        del tr
        gc.collect()
        # the device reference scans the SAME round sequence — the
        # parity bar is bitwise against the resident scan program
        tr = build("device")
        server, clients = tr.init_state(jax.random.key(0))
        for _ in range(calls + 1):
            server, clients, _ = tr.run_rounds(server, clients, R)
        ref = jax.device_get(server.params)
        del tr
        # ref/params hold host numpy (device_get above)
        max_diff = max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(jax.tree.leaves(params),
                            jax.tree.leaves(ref)))
        scan_rows[f"R={R}"] = {
            "ms_per_round": round(dt * 1e3, 2),
            "rounds_timed": calls * R,
            "retraces_during_timed_rounds": retraces,
            "window_h2d_mb": round(feed_mb * R, 3),
            "parity_bitwise_vs_device_scan": max_diff == 0.0,
            "parity_max_abs_diff": max_diff,
        }
        log(f"stream+scan R={R:3d}: {dt*1e3:8.2f} ms/round, "
            f"{retraces} retraces, max|Δ| vs device scan {max_diff}")
    d, s = (out["modes"]["device"]["ms_per_round"],
            out["modes"]["stream"]["ms_per_round"])
    best_R = min(scan_rows, key=lambda k: scan_rows[k]["ms_per_round"])
    best = scan_rows[best_R]["ms_per_round"]
    out["scanned_stream"] = {
        "windows": scan_rows,
        "best_window": best_R,
        "best_ms_per_round": best,
        # scan must beat the per-round stream dispatch...
        "stream_scan_over_stream": round(best / s, 3),
        # ...and this is the stream-vs-device gap the lever closes
        "stream_scan_over_device_walltime": round(best / d, 3),
        "gap_closed_to_leq_1x": bool(best <= d),
    }
    out["stream_over_device_walltime"] = round(s / d, 3)
    out["overlap_within_10pct"] = bool(s <= 1.10 * d)
    # finals hold HOST numpy (device_get in timed()) — no device sync
    diffs = [float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
             for a, b in zip(jax.tree.leaves(finals["device"]),
                             jax.tree.leaves(finals["stream"]))]
    max_diff = max(diffs)  # plain Python floats from the line above
    out["parity_bitwise"] = max_diff == 0.0
    out["parity_max_abs_diff"] = max_diff
    out["residency_ratio_stream_over_device"] = round(
        out["modes"]["stream"]["live_device_bytes_after_warmup"]
        / max(out["modes"]["device"]["live_device_bytes_after_warmup"],
              1), 4)
    path = os.environ.get("STREAM_AB_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "STREAM_AB.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


# -- population-scaling arm (STREAM_BENCH_POPULATION=1) ------------------
POP_SIZES = (200, 1_000) if SMOKE else (1_000, 100_000, 1_000_000)
POP_K = 4 if SMOKE else 8          # FIXED cohort: the independent var
#                                    is C, never the per-round work
POP_NMAX = 16
POP_DIM = 16
POP_BATCH = 8 if SMOKE else 32
POP_LOCAL = 2 if SMOKE else 40
POP_ROUNDS = 3 if SMOKE else 12    # timed rounds after the warmup
POP_SETTLE = 1 if SMOKE else 6     # untimed settling rounds: right
#                                    after a ~1 GB store write the
#                                    first rounds pay the kernel's
#                                    dirty-page writeback + allocator
#                                    growth on this core — warm past it


def _pop_write_store(store_dir: str, C: int, seed: int = 1234):
    """Materialize the synthetic population chunk-wise — RAM stays
    O(chunk) however large C gets."""
    from fedtorch_tpu.data.streaming import MmapStoreWriter
    rng = np.random.RandomState(seed)
    writer = MmapStoreWriter(
        store_dir, n_max=POP_NMAX, x_feat=(POP_DIM,), y_feat=(),
        x_dtype=np.float32, y_dtype=np.int32)
    chunk = 65536
    for lo in range(0, C, chunk):
        n = min(chunk, C - lo)
        x = rng.randn(n, POP_NMAX, POP_DIM).astype(np.float32)
        y = rng.randint(0, 10, (n, POP_NMAX)).astype(np.int32)
        sizes = rng.randint(1, POP_NMAX + 1, n).astype(np.int32)
        writer.append(x, y, sizes)
    return writer.finalize()


def _pop_ram_data(C: int, seed: int = 1234):
    """The SAME population as `_pop_write_store(C, seed)`, held in RAM
    (identical RandomState stream) — the parity twin."""
    from fedtorch_tpu.data.batching import ClientData
    rng = np.random.RandomState(seed)
    xs, ys, ss = [], [], []
    chunk = 65536
    for lo in range(0, C, chunk):
        n = min(chunk, C - lo)
        xs.append(rng.randn(n, POP_NMAX, POP_DIM).astype(np.float32))
        ys.append(rng.randint(0, 10, (n, POP_NMAX)).astype(np.int32))
        ss.append(rng.randint(1, POP_NMAX + 1, n).astype(np.int32))
    return ClientData(x=np.concatenate(xs), y=np.concatenate(ys),
                      sizes=np.concatenate(ss))


def _pop_cfg(C: int, store: str, store_dir: str = ""):
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=POP_DIM,
                        batch_size=POP_BATCH, data_plane="stream",
                        store=store, store_dir=store_dir,
                        augment=False),
        federated=FederatedConfig(
            federated=True, num_clients=C,
            # rate chosen so max(int(rate*C), 1) == POP_K exactly
            online_client_rate=(POP_K + 0.5) / C,
            algorithm="fedavg", sync_type="local_step",
            participation_mode="sparse"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1),
        train=TrainConfig(local_step=POP_LOCAL),
        mesh=MeshConfig(),
    ).finalize()


def _pop_run(tr):
    """Warmup (compile + first feed) then per-round timed steady
    rounds under the recompilation sentinel. Returns (per-round rows,
    retraces, gauges, final server params, final client state)."""
    server, clients = tr.init_state(jax.random.key(0))
    # warmup: round trace + compile, then the scalar-fetch programs
    # (shape-specialized to this C — their first call compiles), then
    # the settling rounds, so the timed window starts from the steady
    # allocator / page-cache state
    server, clients, m = tr.run_round(server, clients)
    sync(server.params)
    jax.device_get(tr.round_scalars_dev(clients, m))
    for _ in range(POP_SETTLE):
        server, clients, m = tr.run_round(server, clients)
        jax.device_get(tr.round_scalars_dev(clients, m))
    rows = []
    with RecompilationSentinel() as sentinel:
        for r in range(POP_ROUNDS):
            t0 = time.perf_counter()
            server, clients, m = tr.run_round(server, clients)
            sync(server.params)
            dt = time.perf_counter() - t0
            # the CLI loop's one batched scalar fetch — never the [C]
            # metrics leaves
            sc = jax.device_get(tr.round_scalars_dev(clients, m))
            n = max(float(sc["n_online"]), 1.0)
            rows.append({"round": r, "round_s": dt,
                         "loss": float(sc["loss_sum"]) / n,
                         "acc": float(sc["acc_sum"]) / n,
                         "comm_bytes": float(sc["comm_bytes"])})
    retraces = sum(sentinel.counts.values())
    gauges = tr.telemetry_gauges()
    params = jax.device_get(server.params)
    cstate = jax.device_get(clients)
    tr.invalidate_stream()
    return rows, retraces, gauges, params, cstate


def _pop_write_run_dir(path: str, rows, meta: dict, gauges: dict):
    os.makedirs(path, exist_ok=True)
    keep = {k: v for k, v in gauges.items()
            if k.startswith("stream_store_")}
    with open(os.path.join(path, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"schema": "fedtorch_tpu.metrics/v1",
                            "created_unix": time.time(),
                            "run": meta}) + "\n")
        for row in rows:
            f.write(json.dumps(dict(row, **keep)) + "\n")


def population_main():
    import shutil
    import tempfile
    devs = jax.devices()
    log(f"devices: {len(devs)} x {devs[0].platform} (population arm)")
    runs_dir = os.environ.get("POPULATION_RUNS_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "population_ab")
    out = {
        "platform": f"{len(devs)} x {devs[0].device_kind}",
        "config": {"populations": list(POP_SIZES), "k_online": POP_K,
                   "n_max": POP_NMAX, "dim": POP_DIM,
                   "batch": POP_BATCH, "K": POP_LOCAL,
                   "rounds_timed": POP_ROUNDS, "smoke": SMOKE,
                   "store": "mmap",
                   "participation_mode": "sparse"},
        "populations": {},
    }
    steady = {}
    for i, C in enumerate(POP_SIZES):
        gc.collect()
        store_dir = tempfile.mkdtemp(prefix=f"popstore_{C}_")
        t0 = time.perf_counter()
        _pop_write_store(store_dir, C)
        build_s = time.perf_counter() - t0
        from fedtorch_tpu.data.streaming import MmapClientStore
        stub = MmapClientStore(store_dir).as_client_data()
        cfg = _pop_cfg(C, "mmap", store_dir)
        tr = FederatedTrainer(cfg, define_model(cfg, POP_BATCH),
                              make_algorithm(cfg), stub)
        assert tr.k_online == POP_K, tr.k_online
        rows, retraces, gauges, params, cstate = _pop_run(tr)
        del tr
        # steady mean excludes the first timed round, mirroring
        # report.summarize's round_s_mean_steady on the run dirs
        steady[C] = float(np.mean([r["round_s"] for r in rows[1:]]))
        row = {
            "clients": C,
            "store_build_s": round(build_s, 2),
            "ms_per_round_steady": round(steady[C] * 1e3, 2),
            "retraces_during_timed_rounds": retraces,
            "store_resident_mb": round(
                gauges.get("stream_store_resident_mb", 0.0), 3),
            "store_mapped_mb": round(
                gauges.get("stream_store_mapped_mb", 0.0), 3),
        }
        if C == POP_SIZES[0]:
            # parity twin: the SAME population in the RAM store — the
            # trajectory (server params AND client state) must be
            # bitwise-identical; only the byte source differs
            cfg_ram = _pop_cfg(C, "ram")
            tr2 = FederatedTrainer(cfg_ram,
                                   define_model(cfg_ram, POP_BATCH),
                                   make_algorithm(cfg_ram),
                                   _pop_ram_data(C))
            _, _, _, params2, cstate2 = _pop_run(tr2)
            del tr2
            diffs = [float(np.max(np.abs(np.asarray(a)
                                         - np.asarray(b))))
                     if np.asarray(a).size else 0.0
                     for a, b in zip(jax.tree.leaves((params, cstate)),
                                     jax.tree.leaves((params2,
                                                      cstate2)))]
            row["parity_bitwise_mmap_vs_ram"] = max(diffs) == 0.0
            row["parity_max_abs_diff"] = max(diffs)
            out["parity_bitwise_mmap_vs_ram"] = max(diffs) == 0.0
        meta = {"bench": "population", "clients": C, "store": "mmap",
                "participation_mode": "sparse", "k_online": POP_K}
        if i == 0:
            _pop_write_run_dir(os.path.join(runs_dir, "a"), rows,
                               meta, gauges)
        if i == len(POP_SIZES) - 1:
            _pop_write_run_dir(os.path.join(runs_dir, "b"), rows,
                               meta, gauges)
        out["populations"][f"C={C}"] = row
        log(f"C={C:>9,d}: {steady[C]*1e3:8.2f} ms/round steady, "
            f"store build {build_s:6.1f}s, resident "
            f"{row['store_resident_mb']:.3f} MB, mapped "
            f"{row['store_mapped_mb']:.1f} MB, {retraces} retraces")
        shutil.rmtree(store_dir, ignore_errors=True)
    lo, hi = POP_SIZES[0], POP_SIZES[-1]
    out["round_wall_ratio_max_over_min_pop"] = round(
        steady[hi] / steady[lo], 3)
    out["round_wall_flat_within_10pct"] = bool(
        steady[hi] <= 1.10 * steady[lo])
    big = out["populations"][f"C={hi}"]
    out["residency_mapped_not_resident"] = bool(
        big["store_resident_mb"] < 0.05 * big["store_mapped_mb"])
    out["zero_retraces"] = all(
        r["retraces_during_timed_rounds"] == 0
        for r in out["populations"].values())
    out["runs_dir"] = runs_dir
    path = os.environ.get("MILLION_CLIENT_AB_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "MILLION_CLIENT_AB.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if os.environ.get("STREAM_BENCH_POPULATION") == "1":
        population_main()
    else:
        main()
