#!/bin/bash
# Round-5 stage 9: third run of the flash block sweep, now with the
# fetch-synced timer (jax.block_until_ready does not wait for device
# execution on the axon relay backend — see _timeit's docstring in
# scripts/flash_block_sweep.py; the first two sweep captures read
# times below the MXU FLOPs floor and are flagged timing_untrusted).
#     nohup bash scripts/tpu_capture_r5i.sh > /tmp/tpu_capture_r5i.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5H_DONE=/tmp/tpu_capture_r5h.done
R5I_DONE=/tmp/tpu_capture_r5i.done
rm -f "$R5I_DONE"
trap 'touch "$R5I_DONE"' EXIT

wait_for_done "$R5H_DONE"
echo "[tpu_capture_r5i] r5h done — probing"
if ! probe_relay 5; then
    echo "[tpu_capture_r5i] relay dead; sweep not re-captured"
    exit 1
fi

FAILED=0
run python scripts/flash_block_sweep.py    # -> FLASH_BLOCK_SWEEP.json (fetch-synced timer)
echo "[tpu_capture_r5i] done (failed=$FAILED)"
exit $FAILED
