#!/bin/bash
# Round-5 stage 10 (final capture stage): re-measure every micro-timing
# artifact with the fetch-synced timer (jax.block_until_ready can no-op
# on the relay backend — flash_block_sweep._timeit docstring), and the
# flash training A/B with the kernel's new data-driven block defaults.
# bench.py and the accuracy curves were never affected (single-dispatch
# segments whose duration self-evidences real execution / per-segment
# metric fetches).
#     nohup bash scripts/tpu_capture_r5j.sh > /tmp/tpu_capture_r5j.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5I_DONE=/tmp/tpu_capture_r5i.done
R5J_DONE=/tmp/tpu_capture_r5j.done
rm -f "$R5J_DONE"
trap 'touch "$R5J_DONE"' EXIT

wait_for_done "$R5I_DONE"
echo "[tpu_capture_r5j] r5i done — probing"
if ! probe_relay 5; then
    echo "[tpu_capture_r5j] relay dead; re-measurement not captured"
    exit 1
fi

FAILED=0
run python scripts/pallas_tpu_check.py      # -> PALLAS_TPU.json (fetch-synced quantize + flash timings)
run python scripts/flash_train_bench.py     # -> FLASH_TRAIN.json (new block defaults, fetch-synced)
echo "[tpu_capture_r5j] done (failed=$FAILED)"
exit $FAILED
