"""North-star-shaped synthetic repro: FedAvg + ResNet-20, 100 clients.

The container has zero egress, so real CIFAR-10 cannot be staged
(readers accept local files; none exist). This runs the north-star
CONFIG (BASELINE.json: FedAvg, ResNet-20, 100 clients, batch 50, 10
local steps, 10% participation, Dirichlet non-IID) on class-structured
CIFAR-shaped synthetic data, so the full stack — non-IID Dirichlet
partitioner, padded client axis, participation sampling, the jitted
round program, eval — executes at the real scale with a real learning
signal (class-conditional Gaussian images are linearly separable).

Expected trajectories (measured on the v5e, 2026-07-29): plain FedAvg in
this regime — Dirichlet(0.5) label skew, 10 local steps, 10%
participation — exhibits severe client drift: local losses collapse
(clients fit their own few labels) while the server model needs ~50+
rounds to clear the 10% chance floor; full participation reaches ~35%
by round 20; SCAFFOLD's control variates counteract the drift (that is
what they are for — see the heterogeneity study in BASELINE_REPRO.md).
The engine itself is validated convergent: IID/full-participation hits
~85% in 10 rounds (scripts/../tests convergence smokes). Use
--algorithm scaffold to see the drift-corrected trajectory.

Writes one JSON line to stdout; progress to stderr. Usage:
    python scripts/northstar_synthetic.py [--rounds N] [--smoke]
        [--algorithm fedavg|scaffold|fedgate] [--participation R]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=100)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI")
    ap.add_argument("--algorithm", default="fedavg",
                    choices=["fedavg", "scaffold", "fedgate"])
    ap.add_argument("--participation", type=float, default=0.1)
    ap.add_argument("--target-acc", type=float, default=0.25,
                    help="BASELINE.json's metric is wall-clock to "
                         "target accuracy; report the time this curve "
                         "first crosses this test top-1")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax

    from fedtorch_tpu.algorithms import make_algorithm
    # timed drains fetch-sync (block_until_ready can no-op on the
    # relay — scripts/bench_timing.py / BASELINE_REPRO.md)
    from fedtorch_tpu.utils.tracing import fetch_sync
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.data.partition import dirichlet_partition
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, evaluate

    C = 10 if args.smoke else 100
    B = 8 if args.smoke else 50
    K = 2 if args.smoke else 10
    N_PER = 24 if args.smoke else 200
    log(f"devices: {jax.devices()}")

    # class-conditional Gaussian images: mean pattern per class + noise
    rng = np.random.RandomState(7)
    n_total = C * N_PER
    class_means = rng.randn(10, 32, 32, 3).astype(np.float32) * 0.8
    labels = rng.randint(0, 10, n_total)
    feats = class_means[labels] + rng.randn(
        n_total, 32, 32, 3).astype(np.float32)
    test_labels = rng.randint(0, 10, 1000)
    test_x = class_means[test_labels] + rng.randn(
        1000, 32, 32, 3).astype(np.float32)

    # the real non-IID partitioner (exact-reference Dirichlet scheme)
    parts = dirichlet_partition(labels, C, concentration=0.5, seed=1)
    parts = [p for p in parts if len(p)]  # degenerate-empty guard
    data = stack_partitions(feats, labels, parts)
    log(f"clients: {data.num_clients}, sizes "
        f"min/median/max: {int(np.min(data.sizes))}/"
        f"{int(np.median(data.sizes))}/{int(np.max(data.sizes))}")

    cfg = ExperimentConfig(
        data=DataConfig(dataset="cifar10", batch_size=B),
        federated=FederatedConfig(
            federated=True, num_clients=data.num_clients,
            online_client_rate=args.participation,
            algorithm=args.algorithm,
            sync_type="local_step"),
        model=ModelConfig(arch="resnet20"),
        # SCAFFOLD/FedGATE control-variate updates assume PLAIN local
        # SGD: (x_s - x_i)/(K*lr) is the mean gradient only without
        # momentum. With in_momentum both the reference and this engine
        # diverge identically (verified side-by-side on the reference's
        # centered scaffold, 2026-07-29) — so momentum is fedavg-only.
        optim=OptimConfig(lr=0.1,
                          in_momentum=(args.algorithm == "fedavg")),
        train=TrainConfig(local_step=K),
        mesh=MeshConfig(compute_dtype=os.environ.get(
            "BENCH_DTYPE", "float32")),
    ).finalize()
    model = define_model(cfg, batch_size=B)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))

    # (seconds, test-acc) pairs: `seconds` is cumulative TRAINING time
    # (eval excluded — the metric is wall-clock-to-accuracy of the
    # trainer, and the 10%-of-rounds eval cadence is a measurement
    # choice, not a training cost); `wall_seconds` includes everything.
    curve = []
    train_s = 0.0
    t0 = time.time()
    for r in range(args.rounds):
        t_r = time.time()
        server, clients, metrics = trainer.run_round(server, clients)
        fetch_sync(server.params)
        train_s += time.time() - t_r
        if (r + 1) % max(args.rounds // 10, 1) == 0 or r == 0:
            res = evaluate(model, server.params, test_x, test_labels,
                           batch_size=256)
            curve.append({"round": r + 1,
                          "seconds": round(train_s, 1),
                          "wall_seconds": round(time.time() - t0, 1),
                          "test_top1": round(float(res.top1), 4)})
            log(f"round {r + 1}: test top1 {float(res.top1):.4f} "
                f"({train_s:.0f}s train, "
                f"{time.time() - t0:.0f}s elapsed)")

    # first crossing of the target accuracy, linearly interpolated in
    # (seconds, acc) between the bracketing eval points
    crossing = None
    prev = None
    for pt in curve:
        if pt["test_top1"] >= args.target_acc:
            if prev is not None and prev["test_top1"] < args.target_acc:
                frac = ((args.target_acc - prev["test_top1"])
                        / (pt["test_top1"] - prev["test_top1"]))
                crossing = prev["seconds"] + frac * (
                    pt["seconds"] - prev["seconds"])
            else:
                crossing = pt["seconds"]
            break
        prev = pt
    print(json.dumps({
        "config": f"northstar_synthetic_{args.algorithm}_resnet20",
        "num_clients": data.num_clients, "batch_size": B,
        "local_steps": K, "participation": args.participation,
        "partition": "dirichlet(0.5)",
        "rounds": args.rounds,
        "final_test_top1": curve[-1]["test_top1"] if curve else None,
        "curve": curve,
        "target_acc": args.target_acc,
        "seconds_to_target": (round(crossing, 1)
                              if crossing is not None else None),
        "train_seconds": round(train_s, 1),
        "wall_seconds": round(time.time() - t0, 1),
        "note": "synthetic class-conditional data (zero-egress "
                "container; real CIFAR gated)",
    }), flush=True)


if __name__ == "__main__":
    main()
