#!/bin/bash
# Round-4 second-stage on-chip captures: the MFU sweep (VERDICT r3 #2)
# and the sparse-MoE dispatch A/B (VERDICT r3 #6). Chained behind
# tpu_capture_full.sh — waits for it to exit first (single-session
# relay + 1-core host: strictly serial), then captures with its own
# relay patience (covers the case where stage 1 exhausted its probes
# and the relay recovers later in the round).
#     nohup bash scripts/tpu_capture_r4.sh > /tmp/tpu_capture_r4.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1

while pgrep -f "bash scripts/tpu_capture_full.sh" > /dev/null; do
    sleep 60
done
echo "[tpu_capture_r4] stage 1 done (or not running) — starting stage 2"

TRIES="${TPU_CAPTURE_WAIT_TRIES:-85}"   # ~5.7 h of patience
BENCH_PROBE_TRIES="$TRIES" python - <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import probe_device
sys.exit(0 if probe_device() else 1)
EOF
if [ $? -ne 0 ]; then
    echo "[tpu_capture_r4] relay never recovered; nothing captured"
    exit 1
fi

echo "[tpu_capture_r4] relay alive — capturing (sequential)"
FAILED=0
run() {
    echo "=== $* ==="
    BENCH_PROBE_TRIES=2 "$@"
    local rc=$?
    echo "=== rc=$rc ==="
    [ $rc -ne 0 ] && FAILED=1
}

run env MFU_PROFILE=1 python scripts/mfu_sweep.py   # -> MFU_SWEEP.json
run python scripts/moe_ab_bench.py                  # -> MOE_AB.json
echo "[tpu_capture_r4] done (failed=$FAILED)"
exit $FAILED
