#!/bin/bash
# Round-5 stage 8: re-run the flash block sweep with the hardened
# per-iteration-blocking timer. The first sweep capture returned
# physically impossible per-iter times (below the MXU FLOPs floor —
# see _timeit's docstring in scripts/flash_block_sweep.py); its
# numbers were dispatch artifacts, not kernel times. The re-run also
# cross-checks pallas_tpu_check's flash timings with the safer timer.
#     nohup bash scripts/tpu_capture_r5h.sh > /tmp/tpu_capture_r5h.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5G_DONE=/tmp/tpu_capture_r5g.done
R5H_DONE=/tmp/tpu_capture_r5h.done
rm -f "$R5H_DONE"
trap 'touch "$R5H_DONE"' EXIT

wait_for_done "$R5G_DONE"
echo "[tpu_capture_r5h] r5g done — probing"
if ! probe_relay 5; then
    echo "[tpu_capture_r5h] relay dead; sweep not re-captured"
    exit 1
fi

FAILED=0
run python scripts/flash_block_sweep.py    # -> FLASH_BLOCK_SWEEP.json (trustworthy timer)
echo "[tpu_capture_r5h] done (failed=$FAILED)"
exit $FAILED
