"""On-chip flash-attention block-size sweep (round 5).

The first real-Mosaic timings (PALLAS_TPU.json) put the flash kernel
at 0.96x/0.80x vs materialized-score dense attention at T=2048/4096 —
the default 128x128 blocks give a (BH, T/128, T/128) grid of tiny
cells whose per-cell overhead eats the causal-skip FLOPs win. This
sweep times the forward kernel across block shapes (and the fwd+bwd
step at the per-T winner) against the dense oracle, so the kernel's
default blocks can be chosen from data.

Writes FLASH_BLOCK_SWEEP.json.
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BLOCKS = [(128, 128), (128, 256), (256, 256), (256, 512), (512, 512)]
SEQ_LENS = (2048, 4096, 8192)
# v5e bf16 peak ~197 TFLOP/s/chip; causal attention forward FLOPs =
# 0.5 * 2 * 2 * B*H*T^2*D (QK^T + PV, half masked). A measured time
# below flops/peak is a timing artifact, not a fast kernel.
_PEAK_FLOPS = 197e12


def _attn_flops(T, B=1, H=8, D=64, causal=True):
    full = 2 * 2 * B * H * T * T * D
    return full / 2 if causal else full


from bench_timing import timeit as _timeit  # noqa: E402  fetch-synced
# (see scripts/bench_timing.py: block_until_ready can no-op on the
# relay backend; the first two sweep captures read sub-FLOPs-floor
# times with block-based timers)


def main():
    import jax
    import jax.numpy as jnp

    from fedtorch_tpu.ops.pallas.flash_attention import flash_attention
    from fedtorch_tpu.parallel.sequence import reference_attention

    dev = jax.devices()[0]
    results = {"platform": str(dev), "config": "B=1 H=8 D=64 bf16 causal",
               "seq": {}}

    out_path = os.path.join(REPO, "FLASH_BLOCK_SWEEP.json")

    def persist():
        # incremental: a mid-sweep wedge/OOM keeps completed seq-lens
        with open(out_path, "w") as f:
            json.dump(results, f, indent=1)

    for T in SEQ_LENS:
        ks = jax.random.split(jax.random.key(11), 3)
        q, k, v = (jax.random.normal(kk, (1, T, 8, 64), jnp.bfloat16)
                   for kk in ks)
        floor_us = _attn_flops(T) / _PEAK_FLOPS * 1e6
        rec = {"blocks": {}, "mxu_floor_us": round(floor_us, 1)}
        results["seq"][str(T)] = rec

        try:
            f_dense = jax.jit(lambda q, k, v: reference_attention(
                q, k, v, causal=True))
            t_d = _timeit(f_dense, q, k, v)
            rec["dense_us"] = round(t_d * 1e6, 1)
            if t_d * 1e6 < floor_us:
                rec["dense_timing_untrusted"] = True
        except Exception as e:  # e.g. [T, T] scores OOM at long T
            rec["dense_error"] = str(e)[:200]
            t_d = None
            print(f"T={T} dense: FAIL {str(e)[:120]}")
        persist()

        best = None
        for bq, bk in BLOCKS:
            name = f"{bq}x{bk}"
            try:
                f = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk))
                t = _timeit(f, q, k, v)
                rec["blocks"][name] = {"us": round(t * 1e6, 1)}
                trusted = t * 1e6 >= floor_us
                if not trusted:
                    rec["blocks"][name]["timing_untrusted"] = True
                if t_d is not None:
                    rec["blocks"][name]["speedup_vs_dense"] = round(
                        t_d / t, 2)
                print(f"T={T} {name}: {t*1e6:.0f}us")
                # an untrusted (below-floor) reading must not elect
                # the best block
                if trusted and (best is None or t < best[1]):
                    best = ((bq, bk), t)
            except Exception as e:  # pragma: no cover - diagnostic
                rec["blocks"][name] = {"error": str(e)[:200]}
                print(f"T={T} {name}: FAIL {str(e)[:120]}")
            persist()
        if best:
            (bq, bk), t = best
            rec["best"] = f"{bq}x{bk}"
            # fwd+bwd at the winner vs dense — the training-step view;
            # differentiate ALL of (q, k, v) so the flash VJP's dk/dv
            # accumulation isn't DCE'd out of the comparison
            try:
                f_fb = jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(flash_attention(
                        q, k, v, causal=True, block_q=bq, block_k=bk)
                        .astype(jnp.float32) ** 2), argnums=(0, 1, 2)))
                d_fb = jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(reference_attention(
                        q, k, v, causal=True)
                        .astype(jnp.float32) ** 2), argnums=(0, 1, 2)))
                t_f = _timeit(f_fb, q, k, v)
                rec["fwd_bwd_best_us"] = round(t_f * 1e6, 1)
                t_dd = _timeit(d_fb, q, k, v)
                rec["fwd_bwd_dense_us"] = round(t_dd * 1e6, 1)
                rec["fwd_bwd_speedup"] = round(t_dd / t_f, 2)
                # fwd+bwd >= the forward-only floor; flag impossible
                # readings like the forward rows
                if min(t_f, t_dd) * 1e6 < floor_us:
                    rec["fwd_bwd_timing_untrusted"] = True
                print(f"T={T} fwd+bwd {bq}x{bk}: {t_f*1e6:.0f}us vs "
                      f"dense {t_dd*1e6:.0f}us ({t_dd/t_f:.2f}x)")
            except Exception as e:
                rec["fwd_bwd_error"] = str(e)[:200]
                print(f"T={T} fwd+bwd: FAIL {str(e)[:120]}")
            persist()

    return 0


if __name__ == "__main__":
    sys.exit(main())
