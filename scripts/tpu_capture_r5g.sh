#!/bin/bash
# Round-5 stage 7: after r5f, land the corrected flash evidence and a
# clean north-star capture.
#   1. Re-run pallas_tpu_check with the precision-pinned flash
#      correctness comparison (the r5f run failed its f32 cases on MXU
#      default-precision rounding, not kernel math — see the comment at
#      the flash section of scripts/pallas_tpu_check.py).
#   2. Flash block-size sweep -> FLASH_BLOCK_SWEEP.json (tune the
#      kernel's default grid from data).
#   3. Re-persist the north-star bench on a QUIET host: the r5d final
#      re-persist ran concurrently with a pytest lane + a CPU-mesh
#      dryrun on this 1-core box and recorded a host-bound 327.5
#      steps/s (vs 579 earlier in the same window). Waits for load to
#      drop before timing.
#   4. Re-certify wedge replay against the fresh capture.
#     nohup bash scripts/tpu_capture_r5g.sh > /tmp/tpu_capture_r5g.log 2>&1 &
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/capture_lib.sh
R5F_DONE=/tmp/tpu_capture_r5f.done
R5G_DONE=/tmp/tpu_capture_r5g.done
rm -f "$R5G_DONE"
trap 'touch "$R5G_DONE"' EXIT

wait_for_done "$R5F_DONE"
echo "[tpu_capture_r5g] r5f done — probing"
if ! probe_relay 5; then
    echo "[tpu_capture_r5g] relay dead; nothing captured"
    exit 1
fi

FAILED=0
run python scripts/pallas_tpu_check.py     # -> PALLAS_TPU.json (precision-pinned flash correctness)
run python scripts/flash_block_sweep.py    # -> FLASH_BLOCK_SWEEP.json
# seqpar with the vma-propagating kernel (r5f's runs predate the fix:
# ring+flash needs pallas_call out_shape vma under shard_map check_vma)
run python scripts/seqpar_tpu_probe.py     # -> SEQPAR_TPU_PROBE.json
run env ZOO_ONLY=seqpar python scripts/tpu_zoo_check.py

# Quiet-host gate for the timed north-star run (up to 10 min of
# patience; 1-min loadavg < 0.9 on this 1-core box).
for _ in $(seq 20); do
    LOAD="$(cut -d' ' -f1 /proc/loadavg)"
    QUIET="$(python -c "print(1 if float('$LOAD') < 0.9 else 0)")"
    [ "$QUIET" = "1" ] && break
    echo "[tpu_capture_r5g] host busy (load $LOAD) — waiting"
    sleep 30
done
BENCH_T0="$(date +%s)"
run python bench.py                        # quiet re-persist -> TPU_BENCH_CAPTURE.json

# bench.py exits 0 on a CPU fallback without touching the capture —
# verify the re-persist actually happened: the capture's timestamp
# must postdate this stage's bench launch (a same-HEAD stale capture
# from an earlier run would pass a head comparison)
if ! CAP_AGE_OK="$(BENCH_T0="$BENCH_T0" python - <<'EOF'
import json, os, sys
try:
    with open("TPU_BENCH_CAPTURE.json") as f:
        cap = json.load(f)
    print(1 if cap.get("captured_unix", 0) >= int(os.environ["BENCH_T0"])
          else 0)
except Exception:
    print(0)
EOF
)" || [ "$CAP_AGE_OK" != "1" ]; then
    echo "[tpu_capture_r5g] re-persist did NOT refresh the capture (no capture newer than stage start)"
    FAILED=1
fi

ROUND5_START_UNIX=1785462780
WEDGE_MIN_CAPTURED_UNIX="$ROUND5_START_UNIX" \
    python scripts/wedge_replay_check.py
rc=$?
echo "[tpu_capture_r5g] wedge_replay_check rc=$rc (0=verified)"
if [ $rc -ne 0 ]; then FAILED=1; fi
echo "[tpu_capture_r5g] done (failed=$FAILED)"
exit $FAILED
