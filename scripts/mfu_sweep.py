"""MFU sweep on the north-star workload (VERDICT r3 #2).

Round 2 measured 3.67% MFU on the north-star config (FedAvg, ResNet-20,
100 clients, batch 50, k=10 online, bf16) and hypothesized an
MXU-underfill regime (32x32 convs, small per-client batches, grouped
convs from per-client weights) without measuring any lever. This script
measures the levers: it times the REAL federated trainer
(`FederatedTrainer.run_rounds`, the same program `bench.py` times) under
a grid of configurations and reports local-steps/sec/chip + analytic
MFU for each:

  base          B=50  bf16 unroll=1 k=10   (the north-star itself)
  batch128      B=128 — 2.56x more rows per conv call
  batch256      B=256 — 5.12x
  f32           B=50 float32 — is bf16 actually buying anything?
  unroll4       B=50 unroll=4 — XLA software-pipelines local steps
  batch128u4    B=128 unroll=4 — the two levers combined
  online20      B=50 k=20 — more clients in flight per round
  matmulconv    B=50 conv_impl=matmul — im2col batched-matmul lowering
  matmulconv128 B=128 conv_impl=matmul — both levers
  resnet50      B=50 — bottleneck blocks reach 256 output channels,
                escaping the N-lane roofline bound (underfill is the
                benchmark model, not the engine)
  fused         B=50 client_fusion=fused — the k online clients packed
                into ONE feature_group_count=k grouped conv per layer
                (k x the MXU lanes per pass; CPU-proven bitwise
                equivalent, tests/test_client_fusion.py) — round 6's
                utilization lever
  fused_online20  B=50 k=20 fused — 20 x the lanes
  fused128      B=128 fused — rows AND lanes together

MFU accounting: per-local-step FLOPs come from XLA's cost analysis of
the compiled fwd+bwd of the ``conv_impl='conv'`` lowering — the
algorithmic work — for EVERY row, so matmul-conv rows don't count
im2col patch extraction as useful FLOPs and mfu_pct is comparable
across the conv A/B (each row's ``flops_source`` says so — exact for
any arch, includes norms/elementwise, memoized per
(arch, batch, dtype)); when the backend reports none,
resnet20 rows fall back to bench.py's analytic constant (fwd =
40.8e6 MACs/image, train step = 3x fwd, 2 FLOPs/MAC) and other archs
report timing without an MFU. Peak via BENCH_PEAK_TFLOPS (default
197 bf16 / 98 f32, TPU v5e).

``MFU_PROFILE=1`` additionally captures a jax.profiler trace of the
base config's timed segment to artifacts/trace_northstar/ for the
roofline note.

Writes MFU_SWEEP.json; prints one JSON line. Relay-gated (real chip
only — CPU numbers would answer nothing about the MXU; main() refuses
to record if the backend resolves to CPU). To smoke-test the plumbing
off-chip, do NOT run main() (its probe opens a relay session): import
``run_config`` directly under a cpu-forced interpreter, e.g.

    JAX_PLATFORMS=cpu MFU_CLIENTS=8 MFU_STEPS=2 MFU_ROUNDS=1 python -c "
    import sys; sys.path[:0] = ['scripts', '.']
    from fedtorch_tpu.utils import honor_platform_env
    honor_platform_env()
    from mfu_sweep import run_config
    print(run_config('smoke', batch=8, online_rate=0.25))"
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from fedtorch_tpu.telemetry.costs import (
    FLOPS_ANALYTIC, FLOPS_XLA, analytic_train_flops_per_image,
    resolve_peak_tflops, train_step_flops,
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# env-overridable for CPU smoke tests of the plumbing (the measured
# grid always runs the real sizes)
NUM_CLIENTS = int(os.environ.get("MFU_CLIENTS", "100"))
LOCAL_STEPS = int(os.environ.get("MFU_STEPS", "10"))
TIMED_ROUNDS = int(os.environ.get("MFU_ROUNDS", "5"))
TRAIN_FLOPS_PER_IMAGE = analytic_train_flops_per_image("resnet20")


_FLOPS_CACHE = {}


def measured_flops_per_step(model, batch, cache_key=None):
    """Per-local-step training FLOPs from XLA's own cost analysis of
    the compiled fwd+bwd (the compiled truth, vs the hand-derived
    resnet20 constant) — delegated to the ONE shared probe,
    ``telemetry.costs.train_step_flops``, so every bench reports the
    same ``flops_source`` accounting. None when the backend doesn't
    report flops (any failure is absorbed — a lost FLOPs count must
    never lose the config's timing). Memoized on ``cache_key`` so grid
    configs that share (arch, batch, dtype) pay one compile (callers
    always pass the conv-lowering model, whatever the timed row's
    conv_impl)."""
    if cache_key is not None and cache_key in _FLOPS_CACHE:
        return _FLOPS_CACHE[cache_key]
    out = train_step_flops(model, batch)
    if out is None:
        log("cost_analysis unavailable; using the analytic constant "
            "where applicable")
    if cache_key is not None:
        _FLOPS_CACHE[cache_key] = out
    return out


def run_config(name, *, batch, dtype="bfloat16", unroll=1,
               online_rate=0.1, conv_impl="conv", arch="resnet20",
               client_fusion="auto", num_devices=None,
               profile_dir=None):
    import jax
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer
    from fedtorch_tpu.utils.tracing import capture_round_trace

    cfg = ExperimentConfig(
        data=DataConfig(dataset="cifar10", batch_size=batch),
        federated=FederatedConfig(
            federated=True, num_clients=NUM_CLIENTS,
            online_client_rate=online_rate, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch=arch, conv_impl=conv_impl),
        optim=OptimConfig(lr=0.1, in_momentum=True),
        train=TrainConfig(local_step=LOCAL_STEPS),
        # num_devices: the sweep measures one chip; CPU smoke tests of
        # the fused plumbing pin 1 (their virtual test mesh exposes 8
        # devices, and the fused lowering never shards the client axis)
        mesh=MeshConfig(compute_dtype=dtype, scan_unroll=unroll,
                        client_fusion=client_fusion,
                        num_devices=num_devices),
    ).finalize()

    samples = max(250, batch)  # each client must cover one full batch
    rng = np.random.RandomState(0)
    feats = rng.randn(NUM_CLIENTS * samples, 32, 32, 3).astype(
        np.float32)
    labels = rng.randint(0, 10, NUM_CLIENTS * samples)
    parts = [np.arange(i * samples, (i + 1) * samples)
             for i in range(NUM_CLIENTS)]
    data = stack_partitions(feats, labels, parts)

    # fetch-synced timing (scripts/bench_timing.py): block_until_ready
    # can no-op on the relay backend (round-5 methodology finding)
    from bench_timing import sync as bench_sync

    model = define_model(cfg, batch_size=batch)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))

    t0 = time.time()
    server, clients, _ = trainer.run_rounds(server, clients,
                                            TIMED_ROUNDS)
    bench_sync(server.params)
    compile_s = time.time() - t0

    t0 = time.time()
    server, clients, _ = trainer.run_rounds(server, clients,
                                            TIMED_ROUNDS)
    bench_sync(server.params)
    dt = time.time() - t0

    if profile_dir:
        # trace capture is a SEPARATE, untimed segment after the
        # recorded one: capture_round_trace runs start_trace AND the
        # trace serialization/disk write around its call, which would
        # otherwise sit inside dt and deflate profiled rows against
        # both their unprofiled siblings and the round-5 history.
        # The hook drains the result inside the trace window with a
        # 1-element fetch (utils/tracing.py) — the on-chip artifact
        # the utilization round attributes the non-MXU time with.
        # Absorbed on failure: a profiler quirk on the relay backend
        # must never lose the config's already-measured timing row.
        try:
            server, clients, _ = capture_round_trace(
                profile_dir, trainer.run_rounds, server, clients,
                TIMED_ROUNDS)
            log(f"profiler trace captured to {profile_dir}")
        except Exception as e:
            log(f"profiler capture failed ({str(e)[:160]}); "
                "timing row kept")

    n_chips = int(trainer.mesh.devices.size)
    steps = TIMED_ROUNDS * trainer.k_online * trainer.local_steps
    steps_per_sec = steps / dt / n_chips
    peak_tflops, _peak_src = resolve_peak_tflops(dtype)
    # FLOPs per local step: XLA cost analysis of the compiled fwd+bwd
    # when available (exact for ANY arch), else the analytic resnet20
    # constant; configs with neither report no MFU rather than a made-up
    # one. The numerator is ALGORITHMIC work — always counted from the
    # conv_impl='conv' lowering, so matmul rows don't book im2col
    # patch-extraction's extra executed FLOPs (~25-55% per 3x3 stage)
    # as useful work and mfu_pct stays apples-to-apples across the
    # conv A/B (the wall-clock columns are the A/B; ADVICE r4).
    if conv_impl == "conv":
        flops_model = model
    else:
        import dataclasses
        flops_cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, conv_impl="conv"))
        flops_model = define_model(flops_cfg, batch_size=batch)
    step_flops = measured_flops_per_step(
        flops_model, batch, cache_key=(arch, batch, dtype))
    flops_src = FLOPS_XLA
    if step_flops is None:
        if arch == "resnet20":
            step_flops = batch * TRAIN_FLOPS_PER_IMAGE
            flops_src = FLOPS_ANALYTIC
        else:
            flops_src = None
    row = {
        "batch": batch, "dtype": dtype, "scan_unroll": unroll,
        "conv_impl": conv_impl, "arch": arch,
        "client_fusion": trainer.client_fusion,
        "k_online": int(trainer.k_online),
        "local_steps_per_sec_per_chip": round(steps_per_sec, 2),
        "images_per_sec": round(steps_per_sec * batch, 1),
        "peak_tflops": peak_tflops,
        "flops_source": flops_src,
        "compile_plus_first_s": round(compile_s, 1),
        "timed_s": round(dt, 2),
    }
    mfu_pct = None
    if step_flops:
        achieved = steps_per_sec * step_flops
        mfu_pct = round(100 * achieved / (peak_tflops * 1e12), 2)
        row["flops_per_step"] = step_flops
        row["achieved_tflops"] = round(achieved / 1e12, 3)
        row["mfu_pct"] = mfu_pct
    log(f"{name:12s}: {steps_per_sec:8.2f} steps/s/chip  "
        f"{row['images_per_sec']:9.1f} img/s  "
        f"MFU {mfu_pct if mfu_pct is not None else '?'}%  "
        f"(compile+1st {compile_s:.0f}s, "
        f"flops={row['flops_source']})")
    return row


def main():
    from bench import probe_device
    if not probe_device():
        log("TPU relay unavailable — MFU is only meaningful on the "
            "chip; nothing recorded")
        return 1
    import jax
    from fedtorch_tpu.utils import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    log(f"device: {dev}")
    if dev.platform == "cpu":
        # a fast relay-init failure can fall back to the cpu platform
        # with the probe still exiting 0 — CPU timings divided by a TPU
        # peak would be garbage MFU presented as an on-chip number
        log("backend resolved to CPU despite a passing probe — refusing "
            "to record MFU (tpu_zoo_check.py guard)")
        return 1

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    profiling = os.environ.get("MFU_PROFILE") == "1"
    profile_dir = os.path.join(repo, "artifacts", "trace_northstar") \
        if profiling else None
    profile_fused = os.path.join(repo, "artifacts",
                                 "trace_northstar_fused") \
        if profiling else None

    # ordered by information value: a mid-sweep relay wedge keeps the
    # most decisive configs (results persist incrementally)
    grid = [
        ("base", dict(batch=50, profile_dir=profile_dir)),
        # round 6's utilization lever: the k online clients packed
        # into ONE feature_group_count=k grouped conv per layer
        # (k x the MXU output lanes on the 16-64-channel stages;
        # docs/performance.md "Client-fused MXU execution"). Same
        # algorithmic FLOPs as base — mfu_pct is directly comparable;
        # the trace pair (base vs fused) attributes the non-MXU time.
        # num_devices=1: the fusion gate rejects multi-device meshes
        # (the packed client/channel axis must not be sharded), and a
        # relay host exposing >1 chip would otherwise turn the whole
        # fused A/B into error rows
        ("fused", dict(batch=50, client_fusion="fused", num_devices=1,
                       profile_dir=profile_fused)),
        ("fused_online20", dict(batch=50, online_rate=0.2,
                                client_fusion="fused", num_devices=1)),
        ("fused128", dict(batch=128, client_fusion="fused",
                          num_devices=1)),
        # im2col batched-matmul conv lowering (models/common.py) — the
        # model-level form of vmap_penalty_bench's conv_lowering A/B
        ("matmulconv", dict(batch=50, conv_impl="matmul")),
        ("batch128", dict(batch=128)),
        ("matmulconv128", dict(batch=128, conv_impl="matmul")),
        ("batch256", dict(batch=256)),
        ("f32", dict(batch=50, dtype="float32")),
        ("unroll4", dict(batch=50, unroll=4)),
        ("batch128u4", dict(batch=128, unroll=4)),
        ("online20", dict(batch=50, online_rate=0.2)),
        # bottleneck blocks reach 256 output channels — escapes the
        # N-lane roofline bound (docs/performance.md): high MFU here +
        # low MFU on resnet20 = the underfill is the benchmark model,
        # not the engine
        ("resnet50", dict(batch=50, arch="resnet50")),
    ]
    results = {"platform": str(dev),
               "flops_accounting":
                   "per-row flops_source: xla_cost_analysis (compiled "
                   "fwd+bwd, incl. norms/elementwise) or "
                   "analytic_resnet20 (3x fwd, 2 FLOPs/MAC, 40.8e6 "
                   "MACs/img — bench.py's accounting)",
               "configs": {}}
    best = None
    for name, kw in grid:
        try:
            row = run_config(name, **kw)
            results["configs"][name] = row
            mfu = row.get("mfu_pct")
            if mfu is not None and (best is None or mfu > best[1]):
                best = (name, mfu)
        except Exception as e:  # an OOM at B=256 is itself a datum
            results["configs"][name] = {"error": str(e)[:300]}
            log(f"{name}: FAIL {str(e)[:160]}")
        # persist incrementally — a relay wedge mid-sweep must not lose
        # the configs already measured
        with open(os.path.join(repo, "MFU_SWEEP.json"), "w") as f:
            json.dump(results, f, indent=1)

    print(json.dumps({
        "mfu_sweep_ok": best is not None,
        "best_config": best[0] if best else None,
        "best_mfu_pct": best[1] if best else None,
        "platform": str(dev)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
