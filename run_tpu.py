"""Short-flag experiment launcher.

Parity with ``run_mpi.py``: translates ~25 human-friendly short flags into
the full ``fedtorch_tpu.cli`` argument set using the same presets — the
per-dataset default model map (run_mpi.py:6-16), MLP sizing, the
multistep-LR recipe with per-epoch decay 1.01 (run_mpi.py:84-92), and the
per-algorithm coercions. Where the reference then execs
``mpirun -np N python main.py`` (run_mpi.py:111-122), this invokes the
in-process TPU entry directly — there are no worker processes to launch;
N clients live on the device mesh.

Examples (the reference README's "Running Examples", same short flags):
    python run_tpu.py -f -ft fedavg -d mnist -n 10 -b 50 -c 20 -e 1 \
        -k 1.0 -r 2 -lg 0.1
    python run_tpu.py -f -ft fedgate -q -d mnist -n 10 -c 20      # FedCOMGATE
    python run_tpu.py -f -ft apfl -pa 0.5 -fp -d mnist -n 10
    python run_tpu.py -f -ft fedavg -fd -dg 0.1 -d mnist -n 10    # DRFA
"""
from __future__ import annotations

import argparse

# per-dataset default architectures (run_mpi.py:6-16)
DEFAULT_MODEL = {
    "epsilon": "logistic_regression",
    "MSD": "robust_least_square",
    "cifar10": "logistic_regression",
    "emnist": "mlp",
    "emnist_full": "mlp",
    "mnist": "mlp",
    "synthetic": "logistic_regression",
    "fashion_mnist": "mlp",
    "adult": "logistic_regression",
    "shakespeare": "rnn",
    "higgs": "logistic_regression",
    "rcv1": "logistic_regression",
    "cifar100": "mlp",
    "stl10": "cnn",
}

# per-dataset MLP hidden sizes (run_mpi.py:16)
MLP_SIZE = {"mnist": 200, "fashion_mnist": 200, "cifar10": 200,
            "cifar100": 500, "adult": 50, "MSD": 50, "emnist": 200,
            "emnist_full": 200}


def build_parser():
    p = argparse.ArgumentParser(
        description="Short-flag launcher (run_mpi.py parity)")
    p.add_argument("-e", "--num_epochs_per_comm", default=1, type=int)
    p.add_argument("-n", "--num_clients", default=20, type=int)
    p.add_argument("-d", "--dataset", default="mnist")
    p.add_argument("-p", "--data_path", default="./data")
    p.add_argument("-b", "--batch_size", default=50, type=int)
    p.add_argument("-c", "--num_comms", default=100, type=int)
    p.add_argument("-lg", "--lr_gamma", default=1.0, type=float)
    p.add_argument("-lm", "--lr_mu", default=1.0, type=float)
    p.add_argument("-ls", "--lr_sync", default=1.0, type=float)
    p.add_argument("-w", "--weight_decay", default=1e-4, type=float)
    p.add_argument("-i", "--iid", action="store_true")
    p.add_argument("-l", "--local_steps", default=1, type=int)
    p.add_argument("-a", "--arch", default=None,
                   help="override the per-dataset default model")
    p.add_argument("-f", "--federated", action="store_true")
    p.add_argument("-ft", "--federated_type", default="fedavg")
    p.add_argument("-fd", "--federated_drfa", action="store_true")
    p.add_argument("-dg", "--drfa_gamma", default=0.1, type=float)
    p.add_argument("-fs", "--federated_sync_type", default="epoch",
                   choices=["epoch", "local_step"])
    p.add_argument("-k", "--online_client_rate", default=1.0, type=float)
    p.add_argument("-r", "--num_class_per_client", default=2, type=int)
    p.add_argument("-sp", "--synthetic_params", nargs="+", type=float,
                   default=[0.0, 0.0])
    p.add_argument("-q", "--quantized", action="store_true")
    p.add_argument("-qb", "--quantized_bits", default=8, type=int)
    p.add_argument("-cp", "--compressed", action="store_true")
    p.add_argument("-cr", "--compressed_ratio", default=1.0, type=float)
    p.add_argument("-u", "--unbalanced", action="store_true")
    p.add_argument("-fp", "--fed_personal", action="store_true")
    p.add_argument("-pa", "--fed_personal_alpha", default=0.0, type=float)
    p.add_argument("-pd", "--fed_adaptive_alpha", action="store_true")
    p.add_argument("-pm", "--fedprox_mu", default=0.002, type=float)
    p.add_argument("-sf", "--sensitive_feature", default=9, type=int)
    p.add_argument("--backend", default=None)
    p.add_argument("--dry_run", action="store_true",
                   help="print the expanded CLI argv and exit")
    return p


def expand(args) -> list:
    """Short flags -> full CLI argv (the cmd build of run_mpi.py:25-122)."""
    num_epochs = args.num_epochs_per_comm * args.num_comms
    arch = args.arch or DEFAULT_MODEL.get(args.dataset, "mlp")
    argv = [
        "--federated", str(args.federated),
        "--federated_type", args.federated_type,
        "--federated_sync_type", args.federated_sync_type,
        "--num_comms", str(args.num_comms),
        "--online_client_rate", str(args.online_client_rate),
        "--num_epochs_per_comm", str(args.num_epochs_per_comm),
        "--num_workers", str(args.num_clients),
        "--data", args.dataset,
        "--data_dir", args.data_path,
        "--batch_size", str(args.batch_size),
        "--iid_data", str(args.iid),
        "--num_class_per_client", str(args.num_class_per_client),
        "--unbalanced", str(args.unbalanced),
        "--synthetic_alpha", str(args.synthetic_params[0]),
        "--synthetic_beta", str(args.synthetic_params[1]),
        "--sensitive_feature", str(args.sensitive_feature),
        "--arch", arch,
        "--mlp_num_layers", "2",
        "--mlp_hidden_size", str(MLP_SIZE.get(args.dataset, 500)),
        "--drop_rate", "0.25",
        "--avg_model", "true",
        "--eval_freq", "1",
        "--stop_criteria", "epoch",
        "--num_epochs", str(num_epochs),
        "--weight_decay", str(args.weight_decay),
        "--local_step", str(args.local_steps),
        "--fed_personal", str(args.fed_personal),
        "--fed_personal_alpha", str(args.fed_personal_alpha),
        "--fed_adaptive_alpha", str(args.fed_adaptive_alpha),
        "--fedprox_mu", str(args.fedprox_mu),
        "--perfedavg_beta", "0.03",
        "--quantized", str(args.quantized),
        "--quantized_bits", str(args.quantized_bits),
        "--compressed", str(args.compressed),
        "--compressed_ratio", str(args.compressed_ratio),
        "--federated_drfa", str(args.federated_drfa),
        "--drfa_gamma", str(args.drfa_gamma),
        # multistep LR decaying 1.01x every epoch (run_mpi.py:84-92)
        "--lr_schedule_scheme", "custom_multistep",
        "--lr_change_epochs",
        ",".join(str(x) for x in range(1, max(num_epochs, 2))),
        "--lr", str(args.lr_gamma),
        "--lr_decay", "1.01",
        "--lr_scale_at_sync", str(args.lr_sync),
        "--checkpoint", args.data_path,
    ]
    if args.backend:
        argv += ["--backend", args.backend]
    return argv


def main(argv=None):
    # unknown --long flags pass through to the full CLI verbatim (the
    # short flags cover run_mpi.py's surface; anything else — e.g.
    # --dirichlet true — belongs to fedtorch_tpu.cli's richer parser,
    # which still rejects genuinely unknown names)
    args, extra = build_parser().parse_known_args(argv)
    if extra and not extra[0].startswith("--"):
        build_parser().error(
            f"unrecognized arguments: {' '.join(extra)}")
    full = expand(args) + extra
    print("Running fedtorch_tpu.cli with:\n  " + " ".join(full))
    if args.dry_run:
        return full
    from fedtorch_tpu.cli import main as cli_main
    return cli_main(full)


if __name__ == "__main__":
    main()
