"""Benchmark: FedAvg local-SGD throughput on the north-star config.

Workload (BASELINE.json): FedAvg, ResNet-20, CIFAR-10-shaped data, 100
clients, batch 50, 10 local steps/round, 10% participation — measured as
**local-steps/sec/chip** on the real TPU.

``vs_baseline`` compares against the reference's per-process torch-CPU
local-step rate on the same host (measured live by running the reference's
own ResNet-20 training step via /root/reference; falls back to a constant
measured on this container's 1-CPU host if the reference isn't mounted).
The reference has no published numbers (SURVEY.md §6), so its own hot loop
is the baseline.

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.
"""
from __future__ import annotations

import json
import os
import sys
import time

# Every successful real-TPU run persists its record here (with a
# timestamp). If the fragile relay is wedged at report time, bench.py
# reports this most recent LIVE capture — with full disclosure in the
# notes — instead of a meaningless CPU-fallback rate. Rationale: the
# metric is "local-steps/sec/chip on the TPU"; a CPU number measures the
# relay's mood, not the framework. Anchored to the repo (like _git), not
# the cwd, so write and read always meet.
TPU_CAPTURE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "TPU_BENCH_CAPTURE.json")

# Measured on this container (1 CPU core): reference resnet20, batch 50,
# plain SGD step loop -> 5.76 steps/s (see docstring; remeasured live when
# possible).
TORCH_CPU_FALLBACK_STEPS_PER_SEC = 5.76
# Best torch-CPU rate ever observed live on this host (round-1 bench run,
# unloaded). The live measurement is floored here so concurrent CPU load
# at bench time cannot deflate the baseline and overstate vs_baseline.
TORCH_CPU_BEST_OBSERVED = 18.20

SMOKE = os.environ.get("BENCH_SMOKE") == "1"  # tiny CPU smoke-test sizes
NUM_CLIENTS = 8 if SMOKE else 100
BATCH_SIZE = 8 if SMOKE else 50
LOCAL_STEPS = 2 if SMOKE else 10
ONLINE_RATE = 0.25 if SMOKE else 0.1
SAMPLES_PER_CLIENT = 32 if SMOKE else 250
TIMED_ROUNDS = 2 if SMOKE else 5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# The A/B env knobs and their north-star defaults — the SINGLE source
# for both the measurement read sites below and the capture gate, so a
# default changed in one place cannot silently desynchronize the other.
BENCH_AB_KNOBS = {
    # 'auto' = the SHIPPED default lowering (backend-aware — resolves
    # to native conv on TPU for the north-star resnet20/cifar10;
    # models/__init__.py resolve_conv_impl). BENCH_CONV_IMPL=matmul
    # runs the im2col variant side of the on-chip A/B
    # (BENCH_MATMULSIDE_AB.json); BENCH_CONV_IMPL=conv on a CPU host
    # pins the non-default lowering there.
    "BENCH_CONV_IMPL": "auto",
    "BENCH_DTYPE": "bfloat16",
    "BENCH_SCAN_UNROLL": "1",
    "BENCH_SINGLE_DISPATCH": "1",
    # BENCH_STREAMING=1 runs the round loop on the streaming data
    # plane (--data_plane stream): host-resident client store with
    # round-ahead feed prefetch. Composes with BENCH_SINGLE_DISPATCH
    # (the round-program builder's feed x scan cell: the producer
    # packs one [TIMED_ROUNDS, ...] feed window for the scan);
    # BENCH_SINGLE_DISPATCH=0 gives the per-round streamed loop.
    # Necessarily a variant (never persisted as the north-star
    # capture): it answers "what does the overlap cost on the real
    # chip", the number STREAM_AB.json reads against the device
    # default.
    "BENCH_STREAMING": "0",
}


def ab_knob(name: str) -> str:
    return os.environ.get(name, BENCH_AB_KNOBS[name])


# the north-star workload identity — shared by main()'s config and the
# capture-provenance stamp so they can never desynchronize
NORTH_STAR_ARCH = "resnet20"
NORTH_STAR_DATASET = "cifar10"


def _resolve_knobs(knobs: dict) -> dict:
    """Resolve a knob dict to the program identity it measures, pinned
    to ``backend='tpu'``: the north-star metric IS the TPU program —
    the capture is stamped on-chip, and the wedged-relay replay gate
    re-computes this identity on a box whose live backend is CPU, so
    resolving with the live backend would spuriously refuse every
    capture now that 'auto' is backend-aware (conv on TPU, matmul on
    CPU). Single source for resolved_bench_knobs AND the persist gate
    — they must never desynchronize."""
    knobs = dict(knobs)
    if knobs["BENCH_CONV_IMPL"] == "auto":
        from fedtorch_tpu.models import resolve_conv_impl
        knobs["BENCH_CONV_IMPL"] = resolve_conv_impl(
            "auto", NORTH_STAR_ARCH, NORTH_STAR_DATASET, backend="tpu")
    return knobs


def resolved_bench_knobs() -> dict:
    """The A/B knobs with BENCH_CONV_IMPL resolved through the model
    registry's 'auto' rule — the program identity a capture measures.
    Two configs with equal resolved knobs compile the same program,
    even across a default flip that renames 'auto''s meaning."""
    return _resolve_knobs({k: ab_knob(k) for k in BENCH_AB_KNOBS})


def is_default_bench_config() -> bool:
    """True when this run measures the north-star PROGRAM.

    Only such a run may persist the replayable capture
    (TPU_BENCH_CAPTURE.json): a variant (conv lowering, dtype, unroll,
    dispatch mode) answers a different question than the metric name
    claims, and a relay wedge between a variant run and an end-of-queue
    re-persist would leave the variant number masquerading as the
    north-star record. The comparison is on RESOLVED knob identities,
    not raw env strings: an explicit knob equal to what 'auto' resolves
    to (e.g. BENCH_CONV_IMPL=conv on TPU post-flip) compiles the
    identical program and its capture is just as replayable."""
    return resolved_bench_knobs() == _resolve_knobs(BENCH_AB_KNOBS)


def probe_device(timeout_s: int = 120) -> bool:
    """Check that the default JAX platform initializes, in a SUBPROCESS
    with a timeout: the TPU relay in this container can wedge
    indefinitely, and a hung bench is worse than a CPU fallback.

    Retries a few times (BENCH_PROBE_TRIES, default 6) with a pause —
    the relay's wedge clears on a server-side timeout (observed to take
    tens of minutes), so patience at bench time is the difference
    between a real TPU number and a CPU fallback. With the defaults the
    probe gives the relay ~22 minutes (6x120s probes + 5x120s pauses)
    to recover before giving up."""
    import subprocess
    import tempfile
    tries = int(os.environ.get("BENCH_PROBE_TRIES", "6"))
    timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT", timeout_s))
    for attempt in range(1, tries + 1):
        # stderr goes to a temp FILE, not a PIPE: a child emitting more
        # than the pipe buffer (long plugin-init tracebacks) would block
        # on write and masquerade as a relay wedge.
        with tempfile.TemporaryFile() as errf:
            p = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; print(jax.devices())"],
                stdout=subprocess.DEVNULL, stderr=errf)
            try:
                p.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                # NEVER SIGKILL a process that may hold the relay
                # session — that is the documented wedge trigger.
                # SIGTERM + grace lets it close the session; SIGKILL
                # only as a last resort.
                p.terminate()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                log(f"device probe attempt {attempt}/{tries} timed out "
                    f"after {timeout_s}s")
                if attempt < tries:
                    # the relay's server-side grant timeout is minutes,
                    # not seconds — a short pause would probe a wedge we
                    # may have just refreshed
                    time.sleep(120)
                continue
            if p.returncode == 0:
                return True
            # deterministic failure (import error, config) — retrying
            # cannot change the outcome
            errf.seek(0)
            log("device probe failed: "
                f"{errf.read().decode(errors='replace')[-200:]}")
            return False
    return False


def measure_torch_baseline() -> "tuple[float, bool]":
    try:
        import types
        sys.path.insert(0, "/root/reference")
        import torch
        import fedtorch.components.models as ref_models
        model = ref_models.resnet(
            types.SimpleNamespace(arch="resnet20", data="cifar10"))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        crit = torch.nn.CrossEntropyLoss()
        x = torch.randn(BATCH_SIZE, 3, 32, 32)
        y = torch.randint(0, 10, (BATCH_SIZE,))
        for _ in range(2):
            opt.zero_grad()
            crit(model(x), y).backward()
            opt.step()
        n = 10
        t0 = time.time()
        for _ in range(n):
            opt.zero_grad()
            crit(model(x), y).backward()
            opt.step()
        rate = n / (time.time() - t0)
        log(f"torch-cpu baseline measured live: {rate:.2f} steps/s")
        return rate, True
    except Exception as e:  # reference not mounted / torch missing
        log(f"torch baseline unavailable ({e}); using fallback constant")
        return TORCH_CPU_FALLBACK_STEPS_PER_SEC, False


def main():
    global ONLINE_RATE, TIMED_ROUNDS, SAMPLES_PER_CLIENT
    global BATCH_SIZE, LOCAL_STEPS
    fallback_cpu = not probe_device()
    if fallback_cpu:
        log("TPU unavailable — benchmarking on CPU (numbers will be low; "
            "rerun when the TPU relay recovers). Shrinking the timed "
            "workload so the run finishes promptly; steps/sec/chip stays "
            "an honest per-step rate.")
        os.environ["JAX_PLATFORMS"] = "cpu"
        ONLINE_RATE = 0.01   # 1 online client/round
        TIMED_ROUNDS = 1
        LOCAL_STEPS = 5
        BATCH_SIZE = 16
        SAMPLES_PER_CLIENT = 64

    import numpy as np
    import jax

    if fallback_cpu:
        jax.config.update("jax_platforms", "cpu")

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
        OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.utils import enable_compile_cache
    cache_dir = enable_compile_cache()
    log(f"persistent compile cache: {cache_dir}")
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer

    log(f"devices: {jax.devices()}")

    from fedtorch_tpu.config import MeshConfig
    # bf16 conv/matmul compute on the MXU (params/norms stay f32);
    # override with BENCH_DTYPE=float32 for a full-precision run.
    # CPU fallback forces f32 (bf16 is software-emulated there).
    dtype = "float32" if fallback_cpu else ab_knob("BENCH_DTYPE")
    log(f"compute dtype: {dtype}")
    streaming = ab_knob("BENCH_STREAMING") == "1"
    cfg = ExperimentConfig(
        data=DataConfig(dataset=NORTH_STAR_DATASET,
                        batch_size=BATCH_SIZE,
                        data_plane="stream" if streaming else "device"),
        federated=FederatedConfig(
            federated=True, num_clients=NUM_CLIENTS,
            online_client_rate=ONLINE_RATE, algorithm="fedavg",
            sync_type="local_step"),
        # BENCH_CONV_IMPL=matmul A/Bs the im2col conv lowering
        # (docs/performance.md "MFU roofline"). A device run resolves
        # the knob through the same TPU-pinned rule the capture stamp
        # uses, so the measured program and its stamped identity
        # cannot diverge even on a host whose live backend would
        # resolve 'auto' differently (e.g. a plain CPU box where
        # probe_device() succeeds). The CPU fallback keeps live-backend
        # resolution instead: it never persists a capture, and forcing
        # the TPU-resolved grouped conv onto XLA CPU would turn the
        # seconds-long liveness probe into a multi-minute compile
        # (CONV_AB_CPU.json: up to 787 s compile, ~7x slower steps).
        model=ModelConfig(
            arch=NORTH_STAR_ARCH,
            conv_impl=ab_knob("BENCH_CONV_IMPL") if fallback_cpu
            else resolved_bench_knobs()["BENCH_CONV_IMPL"]),
        optim=OptimConfig(lr=0.1, in_momentum=True),
        train=TrainConfig(local_step=LOCAL_STEPS),
        # BENCH_SCAN_UNROLL>1 lets XLA software-pipeline consecutive
        # local steps (tolerance-tested equivalent numerics) for A/B
        mesh=MeshConfig(compute_dtype=dtype,
                        scan_unroll=int(ab_knob("BENCH_SCAN_UNROLL"))),
    ).finalize()

    # CIFAR-10-shaped synthetic client shards (zero-egress container:
    # real CIFAR download is gated; shapes/dtypes identical).
    rng = np.random.RandomState(0)
    feats = rng.randn(NUM_CLIENTS * SAMPLES_PER_CLIENT, 32, 32,
                      3).astype(np.float32)
    labels = rng.randint(0, 10, NUM_CLIENTS * SAMPLES_PER_CLIENT)
    parts = [np.arange(i * SAMPLES_PER_CLIENT, (i + 1) * SAMPLES_PER_CLIENT)
             for i in range(NUM_CLIENTS)]
    data = stack_partitions(feats, labels, parts)

    model = define_model(cfg, batch_size=BATCH_SIZE)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))

    # timed segment: all rounds in ONE device call (lax.scan over the
    # round program — no per-round host dispatch); BENCH_SINGLE_DISPATCH=0
    # reverts to the per-round loop for A/B. Each mode warms up (and
    # compiles) only ITS OWN program — the other would be a wasted
    # 40-50s XLA compile on the relay-attached chip.
    # BENCH_STREAMING=1 composes with both dispatch modes since the
    # round-program builder (parallel/round_program.py): batched
    # streaming runs the SCANNED STREAMED program — the producer packs
    # a [TIMED_ROUNDS, ...] feed window while the device scans.
    batched = ab_knob("BENCH_SINGLE_DISPATCH") == "1"
    if batched:
        t0 = time.time()
        server, clients, _ = trainer.run_rounds(server, clients,
                                                TIMED_ROUNDS)
        jax.block_until_ready(server.params)
        log(f"compile+first batched {TIMED_ROUNDS}-round call: "
            f"{time.time() - t0:.1f}s")
        t0 = time.time()
        server, clients, metrics = trainer.run_rounds(server, clients,
                                                      TIMED_ROUNDS)
        jax.block_until_ready(server.params)
        dt = time.time() - t0
    else:
        t0 = time.time()
        server, clients, _ = trainer.run_round(server, clients)
        jax.block_until_ready(server.params)
        log(f"compile+first round: {time.time() - t0:.1f}s")
        t0 = time.time()
        for _ in range(TIMED_ROUNDS):
            server, clients, metrics = trainer.run_round(server, clients)
        jax.block_until_ready(server.params)
        dt = time.time() - t0

    n_chips = int(trainer.mesh.devices.size)
    steps = TIMED_ROUNDS * trainer.k_online * trainer.local_steps
    steps_per_sec = steps / dt / n_chips
    log(f"{steps} local steps in {dt:.2f}s over {TIMED_ROUNDS} rounds "
        f"on {n_chips} chip(s)")

    # MFU: per-local-step FLOPs from the shared XLA cost-analysis probe
    # (telemetry.costs — the same numerator mfu_sweep.py reports) when
    # the timed program is the conv lowering; the analytic resnet20
    # constant (fwd = 40.8e6 MACs/image, train step ~= 3x fwd, 2
    # FLOPs/MAC) when the backend reports no costs or the timed row is
    # the matmul lowering (whose im2col patch extraction must not be
    # booked as useful work). The record says which via flops_source.
    mfu_pct = None
    flops_source = None
    if not fallback_cpu:
        from fedtorch_tpu.telemetry.costs import (
            FLOPS_ANALYTIC, FLOPS_XLA, analytic_train_flops_per_image,
            resolve_peak_tflops, train_step_flops,
        )
        peak_tflops, _peak_src = resolve_peak_tflops(dtype)
        step_flops = train_step_flops(model, BATCH_SIZE) \
            if cfg.model.conv_impl == "conv" else None
        flops_source = FLOPS_XLA
        if step_flops is None:
            step_flops = BATCH_SIZE * analytic_train_flops_per_image(
                NORTH_STAR_ARCH)
            flops_source = FLOPS_ANALYTIC
        achieved = steps_per_sec * n_chips * step_flops
        mfu_pct = round(100 * achieved / (peak_tflops * 1e12 * n_chips), 2)
        log(f"MFU estimate: {mfu_pct}% of {peak_tflops} TFLOPs/chip "
            f"({achieved/1e12:.2f} TFLOPs/s achieved, "
            f"flops={flops_source}; small 32x32 convs "
            f"underfill the MXU — expected for this workload class)")

    baseline, baseline_is_live = measure_torch_baseline()
    note = ("zero-egress container: CIFAR-shaped synthetic shards "
            "(real CIFAR download gated); dispatch="
            + ("batched-scan" if batched else "per-round"))
    if streaming:
        note += ("; data_plane=stream (host-resident client store, "
                 "round-ahead feed prefetch overlapping H2D with "
                 "compute — docs/performance.md 'Streaming data "
                 "plane')")
    if fallback_cpu:
        # VERDICT r4 weak #6: the CPU fallback is a liveness probe, not
        # a steady-state measurement — say so in the record itself
        note += ("; TPU RELAY WEDGED - CPU fallback, not a TPU number"
                 f"; liveness probe over {TIMED_ROUNDS} round(s) x "
                 f"{LOCAL_STEPS} local steps (seconds of runtime), and "
                 "the live torch baseline swings 10.5-18.2 steps/s "
                 "build to build (steady-state conventions: "
                 "BASELINE_REPRO.md)")
    elif baseline < TORCH_CPU_BEST_OBSERVED:
        # TPU mode only: our side doesn't feel host CPU load but the
        # torch baseline does (and the import-failure fallback constant
        # 5.76 predates the better round-1 measurement), so a low
        # baseline would overstate vs_baseline. Floor at the best rate
        # observed on THIS host (round-1 unloaded run) and disclose the
        # replaced value. In CPU fallback both sides share the load - no
        # floor there.
        src = "live measurement" if baseline_is_live \
            else "import-failure fallback constant"
        note += (f"; torch baseline floored at best-observed "
                 f"{TORCH_CPU_BEST_OBSERVED} steps/s ({src} was "
                 f"{baseline:.2f})")
        log(f"flooring torch baseline {baseline:.2f} -> "
            f"{TORCH_CPU_BEST_OBSERVED} (conservative-ratio guard)")
        baseline = TORCH_CPU_BEST_OBSERVED
    record = {
        "metric": "fedavg_resnet20_cifar10_100clients_local_steps_per_sec_per_chip",
        "value": round(steps_per_sec, 2),
        "unit": "local-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / baseline, 2),
        "notes": note,
    }
    if mfu_pct is not None:
        record["mfu_pct"] = mfu_pct
        record["flops_source"] = flops_source

    if not fallback_cpu and not SMOKE and is_default_bench_config():
        # Persist the live capture for wedged-relay report fallback.
        stamp = dict(record)
        stamp["captured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        stamp["captured_unix"] = int(time.time())
        stamp["device"] = str(jax.devices()[0])
        stamp["git_head"] = _git_head()
        # what the knobs RESOLVED to at capture time: a replay must
        # only stand in for a run that would measure the same program
        # (e.g. a capture from before a lowering-default change must
        # not replay after it)
        stamp["bench_knobs"] = resolved_bench_knobs()
        with open(TPU_CAPTURE_PATH, "w") as f:
            json.dump(stamp, f, indent=1)
        log(f"live TPU capture persisted to {TPU_CAPTURE_PATH}")
    elif fallback_cpu and not SMOKE:
        # The relay is wedged NOW; if a real-TPU capture exists, is
        # FRESH (< 24h — this round), and was taken at the CURRENT
        # code revision, report THAT (it answers the metric's actual
        # question) with full provenance in the notes. Any doubt —
        # stale, other build, unreadable — falls through to the honest
        # CPU record below.
        cached = _load_fresh_capture(steps_per_sec)
        if cached is not None:
            print(json.dumps(cached), flush=True)
            return

    print(json.dumps(record), flush=True)


def _git(*args) -> "str | None":
    import subprocess
    try:
        out = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__))]
            + list(args), capture_output=True, text=True, timeout=10)
        return out.stdout.strip() if out.returncode == 0 else None
    except Exception:
        return None


def _git_head() -> str:
    return _git("rev-parse", "HEAD") or "unknown"


def _load_fresh_capture(cpu_steps_per_sec: float):
    """Validate + format the persisted live capture for wedged-relay
    reporting; None if missing/stale/corrupt/other-revision (never
    raises: a broken capture must not lose the live CPU record)."""
    try:
        with open(TPU_CAPTURE_PATH) as f:
            stamp = json.load(f)
        age_h = (time.time() - stamp["captured_unix"]) / 3600
        if age_h > 24:
            log(f"persisted TPU capture is {age_h:.0f}h old — "
                "too stale to report; using the CPU record")
            return None
        # the capture must have measured the same program this run
        # would: refuse on missing or mismatched resolved knobs (e.g.
        # a capture taken under the pre-reversal matmul default must
        # not stand in for today's native-conv default under the same
        # metric name)
        cap_knobs = stamp.get("bench_knobs")
        cur_knobs = resolved_bench_knobs()
        if cap_knobs != cur_knobs:
            log("persisted TPU capture measured different bench knobs "
                f"({cap_knobs}) than this run would ({cur_knobs}); "
                "using the CPU record")
            return None
        head = _git_head()
        cap_rev = stamp.get("git_head", "unknown")
        if cap_rev == "unknown" or head == "unknown":
            # refuse-on-doubt: without both revisions the ancestry of
            # the capture cannot be established
            log("persisted TPU capture revision unverifiable "
                f"(capture={cap_rev[:12]}, head={head[:12]}); using "
                "the CPU record")
            return None
        drift = ""
        if cap_rev != head:
            # the capture must come from an ancestor of THIS build
            # (mid-round commits advance HEAD past the capture point);
            # a diverged/foreign revision is refused outright
            if _git("merge-base", "--is-ancestor", cap_rev,
                    head) is None:
                log(f"persisted TPU capture revision {cap_rev[:12]} is "
                    f"not an ancestor of HEAD {head[:12]}; using the "
                    "CPU record")
                return None
            n_ahead = _git("rev-list", "--count",
                           f"{cap_rev}..{head}") or "?"
            drift = (f"; code has advanced {n_ahead} commit(s) since "
                     "the capture")
        # captured_at is required like the metric fields: provenance
        # with a null timestamp is not usable provenance (a missing key
        # falls into the refuse path via KeyError)
        cached = {k: stamp[k] for k in
                  ("metric", "value", "unit", "vs_baseline",
                   "captured_at")}
        if "mfu_pct" in stamp:
            cached["mfu_pct"] = stamp["mfu_pct"]
        if "flops_source" in stamp:
            cached["flops_source"] = stamp["flops_source"]
        # Machine-readable provenance: automated consumers must be able
        # to tell a replayed capture from a live measurement without
        # parsing prose (ADVICE r3).
        cached["cached"] = True
        cached["git_head"] = cap_rev
        cached["notes"] = (
            f"{stamp.get('notes', '')}; value is the live TPU capture "
            f"from {stamp.get('captured_at')} on {stamp.get('device')} "
            f"at revision {cap_rev[:12]}{drift} (relay wedged at "
            f"report time; CPU liveness run just completed at "
            f"{cpu_steps_per_sec:.2f} steps/s/core)")
        log("relay wedged at report time -> reporting persisted live "
            f"TPU capture from {stamp.get('captured_at')}")
        return cached
    except Exception as e:
        log(f"persisted TPU capture unusable ({e}); using the CPU "
            "record")
        return None


if __name__ == "__main__":
    main()
