"""``fedtorch-tpu report``: render a run dir into a summary table.

Reads the telemetry files a run emits (``metrics.jsonl`` /
``events.jsonl`` / ``health.json``, fedtorch_tpu.telemetry) and prints
the questions an operator actually asks: how fast were rounds, where
did the wall-time go (phase breakdown — the 90%-non-MXU attribution at
run granularity), how much was communicated, did accuracy move, what
did the robustness machinery do, and how did the process exit.

Supersedes regex-parsing ``record0``: the legacy text lines are still
written (reference parity — ``tools/records.py`` keeps parsing them)
and remain the FALLBACK here for pre-telemetry run dirs, so old
experiment trees stay renderable.

Stdlib-only (no jax): a monitor box can summarize a mounted run dir.

Usage::

    fedtorch-tpu report <run_dir>
    python -m fedtorch_tpu.tools.report <run_dir>
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from fedtorch_tpu.telemetry import read_health
from fedtorch_tpu.telemetry.schema import (
    count_restarts, load_jsonl, stitch_rows,
)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"


def _fmt_s(s: Optional[float]) -> str:
    return "-" if s is None else (f"{s * 1e3:.2f} ms" if s < 1.0
                                  else f"{s:.2f} s")


def load_run(run_dir: str) -> Dict:
    """Structured view of one run dir: telemetry rows when present,
    the ``record0`` regex fallback otherwise."""
    out: Dict = {"run_dir": run_dir, "source": None, "meta": {},
                 "rows": [], "events": [], "health": None,
                 "torn_lines": 0, "restarts": 0}
    mpath = os.path.join(run_dir, "metrics.jsonl")
    if os.path.exists(mpath):
        out["source"] = "telemetry"
        # torn-tail tolerant + restart-stitched (telemetry.schema): a
        # crash's truncated final line is COUNTED, not fatal, and an
        # elastic restart's re-run rounds dedupe (last write wins)
        header, records, torn = load_jsonl(mpath)
        out["meta"] = (header or {}).get("run", {}) or {}
        out["rows"] = stitch_rows(records)
        out["restarts"] = count_restarts(records)
        out["torn_lines"] = torn
        epath = os.path.join(run_dir, "events.jsonl")
        if os.path.exists(epath):
            _eh, out["events"], etorn = load_jsonl(epath)
            out["torn_lines"] += etorn
        out["health"] = read_health(run_dir)
        return out
    # legacy fallback: regex-parse the record file (reference parity)
    rpath = os.path.join(run_dir, "record0")
    if os.path.exists(rpath):
        from fedtorch_tpu.tools.records import load_record_file
        rec = load_record_file(rpath)
        out["source"] = "record0"
        for t in rec["train"]:
            out["rows"].append({
                "round": int(t["round"]), "round_s": t["computing"],
                "loss": t["loss"], "acc": t["top1"], "lr": t["lr"],
                "n_online": 0.0, "comm_bytes": t["comm_bytes"],
            })
        vals = [v for v in rec["val"] if v["mode"] == "test"]
        if vals:
            out["rows"] and out["rows"][-1].setdefault(
                "test_top1", vals[-1]["top1"])
            out["meta"]["final_test_top1"] = vals[-1]["top1"]
        return out
    raise FileNotFoundError(
        f"{run_dir}: neither metrics.jsonl nor record0 found — not a "
        "run dir (or telemetry was off and logging disabled)")


def _phase_table(rows: List[Dict]) -> List[tuple]:
    """(phase, total_s, share) over the phases the rows carry. The
    'round' phase is the jitted program's dispatch-to-completion wall;
    fetch/eval/checkpoint are the host phases around it."""
    phases = [("round", "round_s"), ("scalar_fetch", "fetch_s"),
              ("eval", "eval_s"), ("checkpoint", "checkpoint_s")]
    totals = []
    for name, key in phases:
        vals = [r[key] for r in rows if key in r]
        if vals:
            totals.append((name, sum(vals), len(vals)))
    whole = sum(t for _, t, _ in totals) or 1.0
    return [(n, t, t / whole, c) for n, t, c in totals]


def summarize(run_dir: str, run: Optional[Dict] = None) -> Dict:
    """The machine-readable summary the text report renders (tests
    assert on this dict, not on formatting). ``run`` accepts an
    already-``load_run``-ed dict so callers that need both (the
    compare tool) don't parse the JSONL files twice."""
    if run is None:
        run = load_run(run_dir)
    rows = run["rows"]
    if not rows:
        return {"run_dir": run_dir, "source": run["source"],
                "rounds": 0, "meta": run["meta"],
                "health": run["health"],
                "torn_lines": run.get("torn_lines", 0),
                "restarts": run.get("restarts", 0)}
    round_s = [r["round_s"] for r in rows]
    total = sum(round_s)
    # steady-state rate excludes the first round (it pays compilation);
    # with one round there is no steady state to report
    steady = round_s[1:] or round_s
    evals = [r for r in rows if "test_top1" in r]
    s = {
        "run_dir": run_dir,
        "source": run["source"],
        "meta": run["meta"],
        "rounds": len(rows),
        "first_round": rows[0]["round"],
        "last_round": rows[-1]["round"],
        "round_s_total": total,
        "round_s_mean_steady": sum(steady) / len(steady),
        "rounds_per_s_steady": len(steady) / sum(steady)
        if sum(steady) > 0 else float("inf"),
        "compile_round_s": round_s[0],
        "comm_bytes_total": sum(r["comm_bytes"] for r in rows),
        "comm_bytes_per_round": sum(r["comm_bytes"] for r in rows)
        / len(rows),
        "final_loss": rows[-1]["loss"],
        "final_acc": rows[-1]["acc"],
        "phases": _phase_table(rows),
        "health": run["health"],
        "events": {},
        "last_gauges": {},
        "torn_lines": run.get("torn_lines", 0),
        "restarts": run.get("restarts", 0),
    }
    if evals:
        s["final_test_top1"] = evals[-1]["test_top1"]
        s["best_test_top1"] = max(r["test_top1"] for r in evals)
    for ev in run["events"]:
        name = ev.get("event", "?")
        s["events"][name] = s["events"].get(name, 0) + 1
    # the Robustness section: every guard/chaos/byzantine counter the
    # rounds recorded (docs/robustness.md threat-model table) — summed
    # over rounds, plus the rounds each fired in and the attack events.
    # The legacy total_* event entries derive from the same scan.
    rob: Dict = {}
    for key in ("dropped", "stragglers", "rejected", "clipped",
                "byzantine", "robust_selected", "robust_trimmed"):
        vals = [r[key] for r in rows if key in r]
        if vals and sum(vals):
            rob[key] = {"total": sum(vals),
                        "rounds": sum(1 for v in vals if v)}
    for key in ("dropped", "stragglers", "rejected", "clipped"):
        if key in rob:
            s["events"][f"total_{key}"] = rob[key]["total"]
    for name in ("guards.all_rejected", "chaos.byzantine_attack",
                 "supervisor.rollback", "supervisor.round_skipped"):
        if s["events"].get(name):
            rob.setdefault("events", {})[name] = s["events"][name]
    for ev in run["events"]:
        if ev.get("event") == "chaos.byzantine_attack":
            rob["attack"] = {k: ev[k] for k in
                             ("mode", "rate", "scale", "robust_agg")
                             if k in ev}
            break
    s["robustness"] = rob
    # the Federation section (docs/observability.md "Federation
    # plane"): cohort heterogeneity gauges, the per-client ledger's
    # suspicion ranking, and the anomaly detector's verdicts — the
    # federation-plane answer to "who participated and who looked
    # wrong", machine-readable for `report --json` consumers.
    fed: Dict = {}
    disp = [r["cohort_dispersion"] for r in rows
            if "cohort_dispersion" in r]
    if disp:
        fed["cohort"] = {"rounds": len(disp),
                         "dispersion_last": disp[-1],
                         "dispersion_mean": sum(disp) / len(disp)}
        meds = [r["cohort_norm_med"] for r in rows
                if "cohort_norm_med" in r]
        if meds:
            fed["cohort"]["norm_med_last"] = meds[-1]
    anomalies: Dict = {}
    for ev in run["events"]:
        if ev.get("event") == "anomaly.detected":
            f = ev.get("field", "?")
            anomalies[f] = anomalies.get(f, 0) + 1
    if anomalies:
        fed["anomalies"] = anomalies
    for ev in reversed(run["events"]):
        if ev.get("event") == "async.staleness_hist":
            fed["staleness_hist"] = ev.get("hist", {})
            break
    try:
        from fedtorch_tpu.telemetry.ledger import (
            read_client_ledger, suspicion_ranking,
        )
        doc = read_client_ledger(run_dir)
        fed["ledger"] = {
            "mode": doc["mode"], "rounds": doc["rounds"],
            "num_clients": doc["num_clients"],
            "tracked": doc["num_clients"] if doc["mode"] == "dense"
            else len(doc.get("top", {})),
            "top_suspicion": suspicion_ranking(doc, top=5),
        }
    except FileNotFoundError:
        pass
    except ValueError as e:
        # the file exists but does not validate: a broken ledger is a
        # finding, not a non-ledger
        fed["ledger_error"] = str(e)
    if fed:
        s["federation"] = fed
    # the Privacy section (docs/robustness.md "Privacy plane"): spent
    # (eps, delta) from the durable accountant file (authoritative) or
    # the last row's streamed gauge, clip saturation over the run, and
    # the budget-exhaustion outcome — the answer to "what privacy
    # claim does this run support".
    priv: Dict = {}
    try:
        # privacy.ACCOUNTANT_FILE, spelled inline: the ops tools never
        # import the robustness package (its __init__ pulls jax)
        with open(os.path.join(run_dir, "privacy_accountant.json")) as f:
            acc_doc = json.load(f)
        priv["epsilon_spent"] = acc_doc.get("epsilon_spent")
        priv["delta"] = acc_doc.get("delta")
        priv["noise_multiplier"] = acc_doc.get("noise_multiplier")
        priv["charged_rounds"] = acc_doc.get("charged_rounds")
    except (OSError, json.JSONDecodeError):
        pass
    eps_rows = [r["dp_epsilon_spent"] for r in rows
                if "dp_epsilon_spent" in r]
    if eps_rows and "epsilon_spent" not in priv:
        priv["epsilon_spent"] = eps_rows[-1]
    clip = [r["dp_clipped_frac"] for r in rows
            if "dp_clipped_frac" in r]
    if clip:
        priv["clipped_frac_last"] = clip[-1]
        priv["clipped_frac_mean"] = sum(clip) / len(clip)
    sig = [r["dp_noise_sigma"] for r in rows if "dp_noise_sigma" in r]
    if sig:
        priv["noise_sigma_last"] = sig[-1]
    for ev in reversed(run["events"]):
        if ev.get("event") == "privacy.budget_exhausted":
            priv["exhausted"] = {
                k: ev[k] for k in ("round", "action", "epsilon_spent",
                                   "epsilon_budget")
                if k in ev}
            break
    if priv:
        s["privacy"] = priv
    # round-wall critical path (telemetry/critical_path.py;
    # docs/observability.md "Operating and comparing runs"): the
    # stream plane's overlap efficiency and the host/device wall
    # decomposition against the captured program-cost device floor
    from fedtorch_tpu.telemetry import critical_path
    ov = critical_path.overlap_summary(rows)
    if ov is not None:
        s["overlap"] = ov
    costs_doc = None
    try:
        from fedtorch_tpu.telemetry.costs import read_program_costs
        costs_doc = read_program_costs(run_dir)
    except (ValueError, OSError):
        pass  # a broken capture already surfaces via report --device
    dec = critical_path.round_wall_decomposition(rows, costs_doc)
    if dec is not None:
        s["critical_path"] = dec
    if costs_doc is not None:
        # the program-cost summary compare/runs key on — surfaced here
        # so they don't re-read and re-validate the document
        primary = (costs_doc.get("programs") or {}).get(
            costs_doc.get("primary")) or {}
        s["program_costs"] = {
            "primary": costs_doc.get("primary"),
            "backend": costs_doc.get("backend"),
            "flops": primary.get("flops"),
            "bytes_accessed": primary.get("bytes_accessed"),
            "peak_hbm_bytes": primary.get("peak_hbm_bytes")}
    last = rows[-1]
    for key in sorted(last):
        if key.startswith(("stream_", "async_", "ckpt_", "sup_",
                           "cohort_", "ledger_", "dp_")) \
                or key in ("overlap_efficiency", "round_device_min_s",
                           "round_host_frac",
                           "model_flops_utilization",
                           "hbm_program_peak_bytes", "hbm_live_bytes",
                           "client_shards"):
            s["last_gauges"][key] = last[key]
    return s


def render(run_dir: str) -> str:
    s = summarize(run_dir)
    lines = [f"run: {s['run_dir']}  (source: {s['source']})"]
    meta = s.get("meta") or {}
    if meta:
        kv = " ".join(f"{k}={v}" for k, v in sorted(meta.items())
                      if v is not None and k != "final_test_top1")
        if kv:
            lines.append(f"config: {kv}")
    if not s["rounds"]:
        lines.append("no completed rounds recorded")
        return "\n".join(lines)
    lines.append(
        f"rounds: {s['rounds']} "
        f"(r{s['first_round']}..r{s['last_round']})  "
        f"steady-state: {_fmt_s(s['round_s_mean_steady'])}/round "
        f"({s['rounds_per_s_steady']:.2f} rounds/s)  "
        f"first (compile): {_fmt_s(s['compile_round_s'])}")
    lines.append(
        f"comm: {_fmt_bytes(s['comm_bytes_total'])} total, "
        f"{_fmt_bytes(s['comm_bytes_per_round'])}/round")
    acc = (f"final test top1: {s['final_test_top1']:.4f} "
           f"(best {s['best_test_top1']:.4f})  "
           if "final_test_top1" in s else "")
    lines.append(f"{acc}final train loss: {s['final_loss']:.4f}  "
                 f"acc: {s['final_acc']:.4f}")
    if s.get("torn_lines") or s.get("restarts"):
        lines.append(
            f"warning: {s.get('torn_lines', 0)} torn JSONL line(s) "
            f"skipped; {s.get('restarts', 0)} elastic-restart "
            "boundar(ies) stitched (last write per round wins)")
    if s["phases"]:
        lines.append("phase breakdown (host wall, summed over rounds):")
        for name, t, share, count in s["phases"]:
            lines.append(f"  {name:<13} {_fmt_s(t):>10}  "
                         f"{share * 100:5.1f}%  ({count} rounds)")
    cp = s.get("critical_path") or {}
    if "device_floor_s" in cp:
        lines.append(
            "critical path (mean steady round): wall "
            f"{_fmt_s(cp['round_s_mean'])} = device floor "
            f"{_fmt_s(cp['device_floor_s'])} "
            f"({cp['device_floor_frac'] * 100:.1f}%) + host/dispatch "
            f"{_fmt_s(cp['unattributed_s'])} "
            f"({cp['host_frac'] * 100:.1f}%)")
    ov = s.get("overlap") or {}
    if ov:
        lines.append(
            f"stream overlap: efficiency mean {ov['mean']:.2f} "
            f"(min {ov['min']:.2f}, last {ov['last']:.2f}) over "
            f"{ov['rounds']} rounds; producer wall "
            f"{_fmt_s(ov['producer_wall_s'])}, exposed "
            f"{ov['exposed_frac'] * 100:.1f}%")
    rob = s.get("robustness") or {}
    if rob:
        lines.append("robustness (chaos/guards/byzantine — summed "
                     "over rounds):")
        labels = {
            "dropped": "chaos-crashed clients",
            "stragglers": "straggler step cuts / delays",
            "rejected": "guard-rejected updates",
            "clipped": "guard-norm-clipped updates",
            "byzantine": "byzantine uploads injected",
            "robust_selected": "robust-agg updates kept",
            "robust_trimmed": "robust-agg updates trimmed",
        }
        for key, label in labels.items():
            if key in rob:
                c = rob[key]
                lines.append(f"  {label:<28} {c['total']:g}  "
                             f"(in {c['rounds']} rounds)")
        if "attack" in rob:
            a = rob["attack"]
            lines.append(
                "  attack: mode={mode} rate={rate} scale={scale} "
                "defense=robust_agg:{robust_agg}".format(
                    **{k: a.get(k, "?") for k in
                       ("mode", "rate", "scale", "robust_agg")}))
        for name, n in (rob.get("events") or {}).items():
            lines.append(f"  event {name:<22} x{n}")
    fed = s.get("federation") or {}
    if fed:
        lines.append("federation plane (cohort stats / ledger / "
                     "anomalies):")
        if "cohort" in fed:
            c = fed["cohort"]
            line = (f"  dispersion: last {c['dispersion_last']:.4f}  "
                    f"mean {c['dispersion_mean']:.4f}  "
                    f"({c['rounds']} rounds)")
            if "norm_med_last" in c:
                line += f"  median update norm {c['norm_med_last']:.4g}"
            lines.append(line)
        if "ledger" in fed:
            led = fed["ledger"]
            lines.append(
                f"  ledger: {led['mode']} mode, "
                f"{led['tracked']}/{led['num_clients']} clients "
                f"tracked over {led['rounds']} rounds")
            if led.get("top_suspicion"):
                tops = "  ".join(f"c{cid}:{sus:.2f}"
                                 for cid, sus in led["top_suspicion"])
                lines.append(f"  top suspicion: {tops}")
        if "ledger_error" in fed:
            lines.append(f"  ledger: unreadable ({fed['ledger_error']})")
        if "anomalies" in fed:
            kv = " ".join(f"{k}={v}" for k, v in
                          sorted(fed["anomalies"].items()))
            lines.append(f"  anomalies: {kv}")
        if "staleness_hist" in fed:
            kv = " ".join(f"{k}:{v}" for k, v in
                          sorted(fed["staleness_hist"].items(),
                                 key=lambda p: int(p[0])))
            lines.append(f"  staleness histogram: {kv}")
    priv = s.get("privacy") or {}
    if priv:
        lines.append("privacy plane (DP-FedAvg + RDP accountant):")
        if priv.get("epsilon_spent") is not None:
            line = f"  spent epsilon {priv['epsilon_spent']:.4f}"
            if priv.get("delta") is not None:
                line += f" at delta {priv['delta']:g}"
            if priv.get("charged_rounds") is not None:
                line += f"  ({priv['charged_rounds']} charged rounds)"
            lines.append(line)
        if "clipped_frac_last" in priv:
            lines.append(
                f"  clipped frac: last {priv['clipped_frac_last']:.3f}"
                f"  mean {priv['clipped_frac_mean']:.3f}")
        if "noise_sigma_last" in priv:
            lines.append(
                f"  noise sigma (last): {priv['noise_sigma_last']:.4g}")
        if "exhausted" in priv:
            ex = priv["exhausted"]
            lines.append(
                f"  budget exhausted at round {ex.get('round')} "
                f"(action={ex.get('action')}, budget="
                f"{ex.get('epsilon_budget')})")
    if s["last_gauges"]:
        lines.append("subsystem gauges (last round):")
        for k, v in s["last_gauges"].items():
            lines.append(f"  {k:<20} {v:g}")
    if s["events"]:
        ev = " ".join(f"{k}={v}" for k, v in sorted(s["events"].items()))
        lines.append(f"events: {ev}")
    h = s.get("health")
    if h:
        lines.append(
            f"health: intent={h['intent']} round={h['round']} "
            f"pid={h['pid']} since_progress="
            f"{_fmt_s(h.get('since_progress_s'))}")
    return "\n".join(lines)


def render_device(run_dir: str) -> str:
    """The device-side section (docs/observability.md "Device-side"):
    compiled-program costs from ``program_costs.json`` plus the
    profiler-trace attribution table over any
    ``plugins/profile/*/...trace.json(.gz)`` captures under the dir.
    Works on bare capture dirs too (no metrics.jsonl required)."""
    from fedtorch_tpu.telemetry.costs import read_program_costs
    from fedtorch_tpu.tools import trace_attrib

    lines = []
    costs_seen = False
    try:
        doc = read_program_costs(run_dir)
    except (ValueError, OSError) as e:
        # the file exists but doesn't validate: surface the actual
        # error — this dir IS a (broken) capture, not a non-capture
        doc = None
        costs_seen = True
        lines.append(f"program costs: unreadable ({e})")
    if doc is not None:
        costs_seen = True
        lines.append(
            f"program costs (schema {doc['schema']}, backend "
            f"{doc['backend']}, peak {doc['peak_tflops_per_chip']} "
            f"TFLOPs/chip x {doc['num_devices']} [{doc['peak_source']}])")
        for name, rec in sorted(doc["programs"].items()):
            fl = rec.get("flops")
            ba = rec.get("bytes_accessed")
            pk = rec.get("peak_hbm_bytes")
            lines.append(
                f"  {name:<18} flops="
                f"{f'{fl:.3e}' if fl is not None else 'unreported'}  "
                f"bytes="
                f"{_fmt_bytes(ba) if ba is not None else 'unreported'}  "
                f"peak_hbm="
                f"{_fmt_bytes(pk) if pk is not None else 'unreported'}"
                + (f"  [{rec['error']}]" if rec.get("error") else ""))
        analytic = doc.get("analytic") or {}
        if analytic.get("round_flops"):
            lines.append(f"  analytic roofline ({analytic['arch']}): "
                         f"{analytic['round_flops']:.3e} FLOPs/round")
    attrib = trace_attrib.attribute(run_dir)
    lines.append(trace_attrib.render(attrib))
    if not costs_seen and not attrib.get("categories"):
        raise FileNotFoundError(
            f"{run_dir}: neither program_costs.json nor profiler "
            "trace events found — not a device-observability capture")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fedtorch-tpu report",
        description="Summarize a run dir's telemetry "
                    "(docs/observability.md)")
    p.add_argument("run_dir", help="directory holding metrics.jsonl "
                                   "(or a legacy record0)")
    p.add_argument("--device", action="store_true",
                   help="additionally render the device-side section: "
                        "program_costs.json + profiler-trace "
                        "attribution (works on bare capture dirs too)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print summarize()'s machine-readable dict "
                        "(incl. the Federation section) as JSON — the "
                        "CI-consumable form; mutually additive with "
                        "the text report being suppressed")
    args = p.parse_args(argv)
    if args.as_json:
        import json as _json
        try:
            s = summarize(args.run_dir)
        except FileNotFoundError as e:
            print(f"report: {e}", file=sys.stderr)
            return 2
        # phases are tuples (not JSON-stable): make them objects
        s["phases"] = [
            {"phase": n, "total_s": t, "share": share, "rounds": c}
            for n, t, share, c in (s.get("phases") or [])]
        print(_json.dumps(s, indent=2, sort_keys=True, default=str))
        return 0
    rendered = False
    try:
        print(render(args.run_dir))
        rendered = True
    except FileNotFoundError as e:
        if not args.device:
            print(f"report: {e}", file=sys.stderr)
            return 2
    if args.device:
        try:
            print(render_device(args.run_dir))
            rendered = True
        except (FileNotFoundError, ValueError) as e:
            print(f"report: {e}", file=sys.stderr)
    return 0 if rendered else 2


if __name__ == "__main__":
    raise SystemExit(main())
