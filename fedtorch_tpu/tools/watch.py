"""``fedtorch-tpu watch <run_dir>``: live console over a running run.

Tails the run dir's ``health.json`` + ``metrics.jsonl`` +
``events.jsonl`` incrementally (byte-offset resume, no re-parse of the
whole file per tick) and renders the operator loop's live questions:
round rate and ETA, loss/accuracy sparklines, health intent and
time-since-progress, stream overlap efficiency, and the
retry/degraded/anomaly counters. On a non-tty (CI, a pipe) — or with
``--once`` — it degrades to a one-shot snapshot and exits.

Robust by construction against everything a live run dir does:

* **torn tails** — a partial final line stays buffered until the
  writer completes it; a line that was durably torn (crash mid-append,
  then more rows after restart) is skipped with a counted warning;
* **atomic-replace rotation** — ``health.json`` is re-read whole every
  tick (it is atomically replaced, never appended); a truncated or
  rotated JSONL file resets the tail offset instead of mis-seeking;
* **elastic restarts** — the same run dir is appended to by a fresh
  writer; the per-writer ``seq`` stamp drop marks the boundary, re-run
  rounds dedupe (last write wins), and the restart count is displayed.

Keybinds (tty): ``q`` quits; Ctrl-C quits. The watch exits on its own
once the health intent goes terminal (complete/error/preempted/
stalled), after a final render.

Stdlib-only, never imports jax (asserted in tests, like ``report``).

Usage::

    fedtorch-tpu watch <run_dir> [--interval S] [--once]
    python -m fedtorch_tpu.tools.watch <run_dir>
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

from fedtorch_tpu.telemetry.critical_path import StreamOverlapTracker
from fedtorch_tpu.telemetry.health import read_health
from fedtorch_tpu.telemetry.schema import HEALTH_INTENTS

TERMINAL_INTENTS = ("complete", "error", "preempted", "stalled")
assert set(TERMINAL_INTENTS) <= set(HEALTH_INTENTS)
SPARK_CHARS = "▁▂▃▄▅▆▇█"


class JsonlTail:
    """Incremental append-only JSONL reader.

    Byte-offset based: each :meth:`poll` reads only what was appended
    since the last one. A partial final line (the writer is mid-
    append, or a crash tore it) is held in the carry buffer — it is
    only counted ``torn`` once later bytes prove it will never parse
    (a newline arrived and the line still isn't JSON). A file that
    shrank (rotation, truncation) resets the offset and re-reads."""

    def __init__(self, path: str):
        self.path = path
        self.torn = 0
        self._pos = 0
        self._carry = b""

    def poll(self) -> List[Dict]:
        try:
            size = os.stat(self.path).st_size
        except OSError:
            return []  # not written yet (or just rotated away)
        if size < self._pos:
            # atomic-replace rotation / truncation: start over
            self._pos = 0
            self._carry = b""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except OSError:
            return []
        data = self._carry + chunk
        lines = data.split(b"\n")
        # the final element has no newline yet: carry it — the writer
        # may still be mid-append; it parses (or counts torn) when the
        # terminating newline lands
        self._carry = lines.pop()
        out: List[Dict] = []
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                out.append(json.loads(raw.decode("utf-8",
                                                 errors="replace")))
            except json.JSONDecodeError:
                self.torn += 1
        return out

    @property
    def pending_partial(self) -> bool:
        """A non-empty carry at end-of-run IS a torn tail (no writer
        will ever finish it)."""
        return bool(self._carry.strip())


class WatchState:
    """Accumulated view of one run dir's streams."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self.metrics_tail = JsonlTail(
            os.path.join(run_dir, "metrics.jsonl"))
        self.events_tail = JsonlTail(
            os.path.join(run_dir, "events.jsonl"))
        self.meta: Dict = {}
        self.rows_by_round: Dict = {}
        self.recent: List[Dict] = []  # arrival order, bounded
        self.event_counts: Dict[str, int] = {}
        self.restarts = 0
        self._last_seq: Optional[int] = None
        self._overlap = StreamOverlapTracker()
        self.overlap_last: Optional[float] = None

    @property
    def torn(self) -> int:
        return self.metrics_tail.torn + self.events_tail.torn

    def poll(self) -> None:
        for rec in self.metrics_tail.poll():
            if "schema" in rec:
                self.meta = rec.get("run", {}) or {}
                continue
            seq = rec.get("seq")
            if isinstance(seq, int) and not isinstance(seq, bool):
                # seq is strictly increasing per writer: a repeat is a
                # restart boundary too (schema.count_restarts rule)
                if self._last_seq is not None \
                        and seq <= self._last_seq:
                    self.restarts += 1
                self._last_seq = seq
            rnd = rec.get("round")
            if isinstance(rnd, (int, float)) \
                    and not isinstance(rnd, bool):
                self.rows_by_round[rnd] = rec
            self.recent.append(rec)
            del self.recent[:-512]
            # ALWAYS feed the tracker (its baseline must advance every
            # row) but prefer the loop's own emitted gauge — same rule
            # as critical_path.replay_overlap; feeding only gauge-less
            # rows would leave a stale baseline and fabricate a
            # multi-round efficiency at the next idle-producer round
            derived = self._overlap.observe(rec)
            eff = rec.get("overlap_efficiency")
            if not isinstance(eff, (int, float)) \
                    or isinstance(eff, bool):
                eff = derived
            if eff is not None:
                self.overlap_last = float(eff)
        for rec in self.events_tail.poll():
            if "schema" in rec:
                continue
            name = rec.get("event", "?")
            self.event_counts[name] = self.event_counts.get(name, 0) + 1

    def rows(self) -> List[Dict]:
        return [self.rows_by_round[k]
                for k in sorted(self.rows_by_round)]

    def rate_rounds_per_s(self) -> Optional[float]:
        """Steady round rate over the most recent window: wall-clock
        ``t`` stamps when the window is restart-free (they include the
        dispatch gaps the per-round walls miss), falling back to the
        ``round_s`` walls when a restart boundary sits inside the
        window — a t-span across the boundary would count the outage
        downtime as round time and deflate the rate."""
        window = self.recent[-21:]
        seqs = [r["seq"] for r in window
                if isinstance(r.get("seq"), int)
                and not isinstance(r.get("seq"), bool)]
        straddles_restart = any(b <= a for a, b in zip(seqs, seqs[1:]))
        if len(window) >= 2 and not straddles_restart:
            ts = [r["t"] for r in window
                  if isinstance(r.get("t"), (int, float))]
            if len(ts) >= 2 and ts[-1] > ts[0]:
                return (len(ts) - 1) / (ts[-1] - ts[0])
        walls = [float(r.get("round_s", 0.0)) for r in window]
        total = sum(walls)
        return len(walls) / total if walls and total > 0 else None


def sparkline(values: List[float], width: int = 24) -> str:
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        SPARK_CHARS[min(int((v - lo) / span * (len(SPARK_CHARS) - 1)),
                        len(SPARK_CHARS) - 1)] for v in vals)


def _fmt_eta(seconds: Optional[float]) -> str:
    if seconds is None or seconds < 0:
        return "-"
    s = int(seconds)
    if s >= 3600:
        return f"{s // 3600}h{(s % 3600) // 60:02d}m"
    return f"{s // 60}m{s % 60:02d}s"


def render_watch(state: WatchState, health: Optional[Dict],
                 now: Optional[float] = None) -> str:
    """The snapshot text (also the non-tty one-shot output) — the
    output contract docs/observability.md documents; tests pin the
    labelled fields, not the layout."""
    now = time.time() if now is None else now
    rows = state.rows()
    lines = [f"watch: {state.run_dir}"]
    meta = state.meta
    if meta:
        kv = " ".join(f"{k}={v}" for k, v in sorted(meta.items())
                      if v is not None)
        lines.append(f"config: {kv}")
    intent = (health or {}).get("intent", "unknown")
    hline = f"intent={intent}"
    if health:
        hline += (f" round={health.get('round')} "
                  f"pid={health.get('pid')}")
        since = health.get("since_progress_s")
        if since is not None:
            hline += f" since_progress={since:.1f}s"
        age = now - health.get("updated_unix", now)
        hline += f" health_age={max(age, 0.0):.1f}s"
    lines.append(f"health: {hline}")
    rate = state.rate_rounds_per_s()
    done = len(rows)
    total = meta.get("num_comms")
    prog = f"rounds: {done}"
    if isinstance(total, (int, float)) and total:
        prog += f"/{int(total)}"
    if rate:
        prog += f"  rate={rate:.2f} rounds/s"
        if isinstance(total, (int, float)) and total and rows:
            remaining = max(int(total) - 1 - rows[-1]["round"], 0)
            prog += f"  eta={_fmt_eta(remaining / rate)}"
    lines.append(prog)
    if rows:
        last = rows[-1]
        losses = [r["loss"] for r in rows if "loss" in r]
        accs = [r["acc"] for r in rows if "acc" in r]
        lines.append(f"loss: {last.get('loss', float('nan')):.4f} "
                     f"{sparkline(losses)}")
        line = (f"acc:  {last.get('acc', float('nan')):.4f} "
                f"{sparkline(accs)}")
        evals = [r for r in rows if "test_top1" in r]
        if evals:
            line += (f"   test_top1={evals[-1]['test_top1']:.4f} "
                     f"(best {evals[-1].get('best_top1', 0.0):.4f})")
        lines.append(line)
        gauges = []
        if state.overlap_last is not None:
            gauges.append(f"overlap_eff={state.overlap_last:.2f}")
        for key, label in (("stream_depth", "depth"),
                           ("model_flops_utilization", "mfu"),
                           ("round_host_frac", "host_frac"),
                           ("staleness", "staleness")):
            if key in last:
                v = last[key]
                gauges.append(f"{label}={v:.3g}")
        if gauges:
            lines.append("gauges: " + "  ".join(gauges))
        counters = []
        for key in ("host_retries", "host_degraded", "sup_rollbacks",
                    "ckpt_lost_writes"):
            if last.get(key):
                counters.append(f"{key}={last[key]:g}")
        anom = state.event_counts.get("anomaly.detected", 0)
        counters.append(f"anomalies={anom}")
        counters.append(f"torn={state.torn}")
        counters.append(f"restarts={state.restarts}")
        lines.append("counters: " + "  ".join(counters))
    else:
        lines.append(f"no metrics rows yet  torn={state.torn}")
    interesting = {n: c for n, c in sorted(state.event_counts.items())
                   if n not in ("run.start",)}
    if interesting:
        lines.append("events: " + "  ".join(
            f"{n}={c}" for n, c in interesting.items()))
    return "\n".join(lines)


def _stdin_quit(timeout_s: float) -> bool:
    """tty keybind: wait up to ``timeout_s`` for a 'q' keypress (raw,
    no Enter needed where termios exists; line-buffered fallback
    elsewhere). Never raises — a weird terminal degrades to sleep."""
    try:
        import select
        import termios
        import tty
        fd = sys.stdin.fileno()
        old = termios.tcgetattr(fd)
        try:
            tty.setcbreak(fd)
            r, _w, _x = select.select([sys.stdin], [], [], timeout_s)
            if r:
                return sys.stdin.read(1).lower() == "q"
            return False
        finally:
            termios.tcsetattr(fd, termios.TCSADRAIN, old)
    except Exception:
        time.sleep(timeout_s)
        return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fedtorch-tpu watch",
        description="Live console over a run dir's telemetry "
                    "(docs/observability.md 'Operating and comparing "
                    "runs'); one-shot snapshot on non-tty")
    p.add_argument("run_dir", help="the run dir to tail")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll/redraw interval, seconds (tty mode)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit (the non-tty "
                        "default, forced)")
    p.add_argument("--max-ticks", type=int, default=0,
                   help="exit after N redraws even if the run is "
                        "still going (0 = until terminal intent/q)")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"watch: {args.run_dir}: not a directory",
              file=sys.stderr)
        return 2
    state = WatchState(args.run_dir)
    live = sys.stdout.isatty() and not args.once
    ticks = 0
    while True:
        state.poll()
        # health.json is atomically replaced, never appended: re-read
        # whole each tick (read_health returns None mid-rotation race
        # only if the file is absent — os.replace keeps it continuous)
        try:
            health = read_health(args.run_dir)
        except ValueError as e:
            print(f"watch: health.json: {e}", file=sys.stderr)
            return 2
        text = render_watch(state, health)
        if live:
            sys.stdout.write("\x1b[H\x1b[2J" + text
                             + "\n[q to quit]\n")
            sys.stdout.flush()
        else:
            print(text)
        ticks += 1
        intent = (health or {}).get("intent")
        if not live or intent in TERMINAL_INTENTS \
                or (args.max_ticks and ticks >= args.max_ticks):
            if live and intent in TERMINAL_INTENTS:
                print(f"watch: run reached terminal intent "
                      f"{intent!r}")
            return 0
        try:
            if _stdin_quit(args.interval):
                return 0
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
