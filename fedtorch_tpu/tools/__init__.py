from fedtorch_tpu.tools.plots import (  # noqa: F401
    build_legend, configure_figure, determine_color_and_lines,
    plot_one_case, plot_runs, reject_outliers,
)
from fedtorch_tpu.tools.records import (  # noqa: F401
    load_record_file, parse_records, smoothing,
)
from fedtorch_tpu.tools.report import (  # noqa: F401
    load_run, render, summarize,
)
