from fedtorch_tpu.tools.records import (  # noqa: F401
    load_record_file, parse_records, smoothing,
)
