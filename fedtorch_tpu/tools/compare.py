"""``fedtorch-tpu compare A B``: noise-aware diff of two run dirs.

The repo's dozens of A/B artifacts (STREAM_AB, ASYNC_AB, TELEMETRY_AB,
BENCH_r0x) were compared by eyeball; this tool makes "did run B
regress run A" a machine decision — FedScale's point that an FL
benchmark is only as good as its cross-run evaluation harness (Lai et
al. 2022). It diffs everything the telemetry records: round/commit
rate and per-phase walls, comm volume, the accuracy trajectory (round-
aligned, with a measured max gap for a tolerance gate to judge),
MFU/HBM gauges, overlap efficiency, event counts, and the captured
program costs (FLOPs, bytes accessed, peak-HBM watermark).

Noise-awareness lives in the GATE FILE, not in hidden thresholds: the
compare document records raw values, deltas and fractional deltas; a
``--gate gates.json`` names which metrics are binding and how much
drift is tolerated (wall-clock gates in fractions wide enough for a
shared box's noise envelope; byte/count gates exact). Exit code is the
contract: 0 = compared, nothing gated regressed; 1 = >= 1 gated
regression; 2 = unusable input (missing run dir, invalid gate file).

Stdlib-only, never imports jax (the ``tools/report.py`` rule,
asserted in tests); torn-tail and restart-stitching tolerant via the
shared ``telemetry.schema`` loader.

Usage::

    fedtorch-tpu compare A B [--gate gates.json] [--json] [--out F]
    python -m fedtorch_tpu.tools.compare A B
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

COMPARE_SCHEMA = "fedtorch_tpu.run_compare/v1"
GATES_SCHEMA = "fedtorch_tpu.compare_gates/v1"

# the gate-file condition vocabulary (anything else is a hard error —
# a typo'd gate that silently never fires is worse than no gate)
GATE_CHECKS = ("max_increase_frac", "max_decrease_frac",
               "max_increase_abs", "max_decrease_abs",
               "max_b", "min_b")

_EPS = 1e-12


def _entry(a: Optional[float], b: Optional[float]) -> Optional[Dict]:
    """One compared metric: raw sides, absolute and fractional delta
    (fraction relative to |a|; None when a side is missing)."""
    if a is None and b is None:
        return None
    out: Dict = {"a": a, "b": b}
    if a is not None and b is not None:
        out["delta"] = b - a
        out["frac"] = (b - a) / max(abs(a), _EPS)
    return out


def _mean_gauge(rows: List[Dict], key: str) -> Optional[float]:
    vals = [float(r[key]) for r in rows
            if isinstance(r.get(key), (int, float))
            and not isinstance(r.get(key), bool)]
    return sum(vals) / len(vals) if vals else None


def _summary(run_dir: str) -> Tuple[Dict, List[Dict]]:
    from fedtorch_tpu.tools.report import load_run, summarize
    run = load_run(run_dir)  # parsed once; summarize reuses it
    return summarize(run_dir, run=run), run["rows"]


def _trajectory(rows_a: List[Dict], rows_b: List[Dict]) -> Dict:
    """Round-aligned accuracy comparison over the common rounds: the
    max and final gaps a tolerance gate judges — two same-config runs
    differing only in noise track each other; a regressed one drifts."""
    by_a = {r["round"]: r for r in rows_a}
    by_b = {r["round"]: r for r in rows_b}
    common = sorted(set(by_a) & set(by_b))
    out: Dict = {"rounds_compared": len(common)}
    for field in ("acc", "loss", "test_top1"):
        gaps = [float(by_b[r][field]) - float(by_a[r][field])
                for r in common
                if field in by_a[r] and field in by_b[r]]
        if gaps:
            out[f"{field}_max_abs_gap"] = max(abs(g) for g in gaps)
            out[f"{field}_final_delta"] = gaps[-1]
    return out


def compare_runs(dir_a: str, dir_b: str) -> Dict:
    """The compare document (schema ``fedtorch_tpu.run_compare/v1``).
    Raises ``FileNotFoundError`` when either side is not a run dir."""
    sum_a, rows_a = _summary(dir_a)
    sum_b, rows_b = _summary(dir_b)
    metrics: Dict[str, Dict] = {}

    def add(name: str, a, b) -> None:
        e = _entry(
            float(a) if isinstance(a, (int, float))
            and not isinstance(a, bool) else None,
            float(b) if isinstance(b, (int, float))
            and not isinstance(b, bool) else None)
        if e is not None:
            metrics[name] = e

    for key in ("rounds", "round_s_mean_steady", "rounds_per_s_steady",
                "compile_round_s", "comm_bytes_total",
                "comm_bytes_per_round", "final_loss", "final_acc",
                "final_test_top1", "best_test_top1", "torn_lines",
                "restarts"):
        add(key, sum_a.get(key), sum_b.get(key))
    # per-phase mean wall per covered round (the summarize table holds
    # totals + counts; a run with more eval rounds must not read as an
    # eval regression)
    for side_sum, side in ((sum_a, "a"), (sum_b, "b")):
        side_sum["_phase_mean"] = {
            name: total / count
            for name, total, _share, count in side_sum.get("phases")
            or [] if count}
    for name in sorted(set(sum_a["_phase_mean"])
                       | set(sum_b["_phase_mean"])):
        add(f"phase.{name}_mean_s", sum_a["_phase_mean"].get(name),
            sum_b["_phase_mean"].get(name))
    # per-round gauges, mean over the rows that carry them
    for key in ("model_flops_utilization", "hbm_program_peak_bytes",
                "hbm_live_bytes", "round_device_min_s",
                "round_host_frac", "stream_depth",
                "stream_store_resident_mb", "stream_store_mapped_mb",
                "ckpt_queue_depth",
                "async_commit_rate", "async_dropouts",
                "cohort_dispersion", "avail_dropped", "deadline_missed",
                "quorum_degraded",
                "client_shards", "cohort_allreduce_bytes",
                "stream_shard_pack_s", "stream_shard_rows"):
        add(f"gauge.{key}", _mean_gauge(rows_a, key),
            _mean_gauge(rows_b, key))
    ov_a, ov_b = sum_a.get("overlap"), sum_b.get("overlap")
    add("overlap_efficiency_mean",
        (ov_a or {}).get("mean"), (ov_b or {}).get("mean"))
    add("overlap_exposed_frac",
        (ov_a or {}).get("exposed_frac"), (ov_b or {}).get("exposed_frac"))
    cp_a = sum_a.get("critical_path") or {}
    cp_b = sum_b.get("critical_path") or {}
    for key in ("device_floor_s", "unattributed_s", "host_frac"):
        add(f"critical_path.{key}", cp_a.get(key), cp_b.get(key))
    pc_a = sum_a.get("program_costs")
    pc_b = sum_b.get("program_costs")
    for key in ("flops", "bytes_accessed", "peak_hbm_bytes"):
        add(f"pc.{key}", (pc_a or {}).get(key), (pc_b or {}).get(key))
    events: Dict[str, Dict] = {}
    ev_a, ev_b = sum_a.get("events") or {}, sum_b.get("events") or {}
    for name in sorted(set(ev_a) | set(ev_b)):
        events[name] = {"a": ev_a.get(name, 0), "b": ev_b.get(name, 0),
                        "delta": ev_b.get(name, 0) - ev_a.get(name, 0)}
    return {
        "schema": COMPARE_SCHEMA,
        "a": {"run_dir": dir_a, "meta": sum_a.get("meta") or {},
              "health_intent": (sum_a.get("health") or {}).get("intent")},
        "b": {"run_dir": dir_b, "meta": sum_b.get("meta") or {},
              "health_intent": (sum_b.get("health") or {}).get("intent")},
        "metrics": metrics,
        "events": events,
        "trajectory": _trajectory(rows_a, rows_b),
    }


# -- gate files ----------------------------------------------------------

def load_gates(path: str) -> Dict:
    """Parse + validate a gate file; raises ``ValueError`` on an
    unknown check name or a non-numeric limit — a typo'd gate must
    fail loudly, not silently never fire."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != GATES_SCHEMA:
        raise ValueError(
            f"gate-file schema {doc.get('schema')!r} != {GATES_SCHEMA!r}")
    gates = doc.get("gates")
    if not isinstance(gates, dict) or not gates:
        raise ValueError("gate file carries no 'gates' object")
    for metric, spec in gates.items():
        if not isinstance(spec, dict):
            raise ValueError(f"gate {metric!r} must be an object")
        checks = [k for k in spec if k != "required"]
        if not checks:
            raise ValueError(f"gate {metric!r} names no condition")
        for k in checks:
            if k not in GATE_CHECKS:
                raise ValueError(
                    f"gate {metric!r} uses unknown check {k!r} "
                    f"(known: {GATE_CHECKS})")
            if isinstance(spec[k], bool) \
                    or not isinstance(spec[k], (int, float)):
                raise ValueError(
                    f"gate {metric!r} check {k!r} limit must be a "
                    f"number, got {spec[k]!r}")
    return doc


def _resolve_metric(cmp_doc: Dict, name: str) -> Optional[Dict]:
    if name.startswith("events."):
        rec = cmp_doc["events"].get(name[len("events."):])
        if rec is None:
            return None
        e = dict(rec)
        e["frac"] = (e["delta"] / max(abs(e["a"]), _EPS)
                     if e["a"] is not None else None)
        return e
    if name.startswith("trajectory."):
        v = cmp_doc["trajectory"].get(name[len("trajectory."):])
        return None if v is None else {"a": None, "b": v, "delta": v,
                                       "frac": None}
    return cmp_doc["metrics"].get(name)


def evaluate_gates(cmp_doc: Dict, gates_doc: Dict
                   ) -> Tuple[List[Dict], List[str], List[str]]:
    """``(failures, checked, skipped)``: every gate either fails with
    a named reason, passes (checked), or is skipped because the metric
    is absent on one side (unless ``"required": true`` — then absence
    IS the failure: a regression that deletes the gauge must not pass
    the gate that watches it)."""
    failures: List[Dict] = []
    checked: List[str] = []
    skipped: List[str] = []
    for metric, spec in gates_doc["gates"].items():
        entry = _resolve_metric(cmp_doc, metric)
        required = bool(spec.get("required", False))
        have_pair = entry is not None and entry.get("b") is not None \
            and (entry.get("a") is not None
                 or not any(k.startswith(("max_increase",
                                          "max_decrease"))
                            for k in spec))
        if not have_pair:
            if required:
                failures.append({
                    "metric": metric, "check": "required",
                    "message": f"{metric}: required metric missing "
                               "from one or both runs"})
            else:
                skipped.append(metric)
            continue
        checked.append(metric)
        a, b = entry.get("a"), entry["b"]
        delta, frac = entry.get("delta"), entry.get("frac")
        for check, limit in spec.items():
            if check == "required":
                continue
            bad = None
            if check == "max_increase_frac" and frac is not None \
                    and frac > limit:
                bad = f"+{frac * 100:.2f}% > +{limit * 100:.2f}%"
            elif check == "max_decrease_frac" and frac is not None \
                    and -frac > limit:
                bad = f"{frac * 100:.2f}% < -{limit * 100:.2f}%"
            elif check == "max_increase_abs" and delta is not None \
                    and delta > limit:
                bad = f"delta {delta:g} > {limit:g}"
            elif check == "max_decrease_abs" and delta is not None \
                    and -delta > limit:
                bad = f"delta {delta:g} < -{limit:g}"
            elif check == "max_b" and b > limit:
                bad = f"b={b:g} > {limit:g}"
            elif check == "min_b" and b < limit:
                bad = f"b={b:g} < {limit:g}"
            if bad is not None:
                failures.append({
                    "metric": metric, "check": check, "limit": limit,
                    "a": a, "b": b, "delta": delta, "frac": frac,
                    "message": f"{metric}: {bad}"})
    return failures, checked, skipped


# -- rendering -----------------------------------------------------------

def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render(cmp_doc: Dict, failures: Optional[List[Dict]] = None) -> str:
    failed = {f["metric"] for f in failures or []}
    lines = [f"compare: A={cmp_doc['a']['run_dir']} "
             f"(intent={cmp_doc['a']['health_intent']})  vs  "
             f"B={cmp_doc['b']['run_dir']} "
             f"(intent={cmp_doc['b']['health_intent']})"]
    lines.append(f"{'metric':<32} {'A':>14} {'B':>14} "
                 f"{'delta':>12} {'frac':>9}")
    for name, e in cmp_doc["metrics"].items():
        frac = e.get("frac")
        mark = "  FAIL" if name in failed else ""
        lines.append(
            f"{name:<32} {_fmt(e.get('a')):>14} {_fmt(e.get('b')):>14} "
            f"{_fmt(e.get('delta')):>12} "
            f"{(f'{frac * 100:+.2f}%' if frac is not None else '-'):>9}"
            f"{mark}")
    tr = cmp_doc["trajectory"]
    lines.append(
        f"trajectory: {tr.get('rounds_compared', 0)} common rounds"
        + "".join(f"  {k}={v:.4g}" for k, v in sorted(tr.items())
                  if k != "rounds_compared"))
    diff_ev = {n: e for n, e in cmp_doc["events"].items()
               if e["delta"] or f"events.{n}" in failed}
    if diff_ev:
        lines.append("event deltas: " + "  ".join(
            f"{n} {e['a']}->{e['b']}"
            + (" FAIL" if f"events.{n}" in failed else "")
            for n, e in sorted(diff_ev.items())))
    for f in failures or []:
        lines.append(f"GATE FAIL [{f.get('check')}] {f['message']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fedtorch-tpu compare",
        description="Noise-aware diff of two run dirs, optionally "
                    "gated (docs/observability.md 'Operating and "
                    "comparing runs'). Exit 0 = no gated regression, "
                    "1 = gated regression, 2 = unusable input.")
    p.add_argument("run_a", help="baseline run dir (A)")
    p.add_argument("run_b", help="candidate run dir (B)")
    p.add_argument("--gate", default=None, metavar="GATES_JSON",
                   help="gate file (schema "
                        "fedtorch_tpu.compare_gates/v1); without it "
                        "the diff is informational and always exits 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the compare document (plus gate "
                        "results) as JSON instead of the table")
    p.add_argument("--out", default=None, metavar="FILE",
                   help="also write the JSON document to FILE")
    args = p.parse_args(argv)
    try:
        cmp_doc = compare_runs(args.run_a, args.run_b)
    except (OSError, ValueError) as e:
        # FileNotFoundError (not a run dir), PermissionError (a
        # mis-permissioned artifact mount), a corrupt document — all
        # "unusable input" (exit 2), never a fake gated regression
        print(f"compare: {e}", file=sys.stderr)
        return 2
    failures: List[Dict] = []
    if args.gate is not None:
        try:
            gates = load_gates(args.gate)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"compare: gate file {args.gate}: {e}",
                  file=sys.stderr)
            return 2
        failures, checked, skipped = evaluate_gates(cmp_doc, gates)
        cmp_doc["gate"] = {
            "path": args.gate, "failures": failures,
            "checked": checked, "skipped": skipped,
            "pass": not failures}
    if args.out:
        try:
            with open(args.out, "w") as f:
                json.dump(cmp_doc, f, indent=2, sort_keys=True)
        except OSError as e:
            print(f"compare: --out {args.out}: {e}", file=sys.stderr)
            return 2
    if args.as_json:
        print(json.dumps(cmp_doc, indent=2, sort_keys=True,
                         default=str))
    else:
        print(render(cmp_doc, failures))
    if failures:
        print(f"compare: {len(failures)} gated regression(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
