"""Figure helpers for parsed record tables.

Parity with ``fedtorch/tools/plot_utils.py``: deterministic
color/line/marker assignment per curve (plot_utils.py:80-103),
axis/legend styling (configure_figure, :107-122), single-curve plotting
(plot_one_case, :125-133), legend construction from run hyperparameters
(build_legend, :136-143), outlier rejection (:42-43), and a
``plot_runs`` convenience that turns :func:`parse_records` output
directly into a comparison figure.

matplotlib is imported lazily so headless/metrics-only installs never
pay for it.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from fedtorch_tpu.tools.records import smoothing

_LINE_STYLES = ["-", "--", "-.", ":"]
# colorblind-safe palette (plot_utils.py:82-85)
_COLOR_STYLES = ["#377eb8", "#ff7f00", "#4daf4a", "#f781bf", "#a65628",
                 "#984ea3", "#999999", "#e41a1c", "#dede00"]


def reject_outliers(data, threshold: float = 3.0) -> np.ndarray:
    """Drop points further than ``threshold`` stds from the mean
    (plot_utils.py:42-43)."""
    data = np.asarray(data)
    return data[np.abs(data - data.mean()) < threshold * data.std()]


def determine_color_and_lines(ind: int):
    """Deterministic (line style, color, marker) for curve ``ind``
    (plot_utils.py:80-103 without the grid-shape special cases)."""
    from matplotlib.lines import Line2D
    markers = Line2D.filled_markers
    return (_LINE_STYLES[(ind // len(_COLOR_STYLES)) % len(_LINE_STYLES)],
            _COLOR_STYLES[ind % len(_COLOR_STYLES)],
            markers[ind % len(markers)])


def configure_figure(ax, xlabel: str, ylabel: str,
                     title: Optional[str] = None, has_legend: bool = True,
                     legend_loc: str = "lower right",
                     legend_ncol: int = 2):
    """Axis labels / legend / tick styling (plot_utils.py:107-122)."""
    if has_legend:
        ax.legend(loc=legend_loc, ncol=legend_ncol, shadow=True,
                  fancybox=True, fontsize=12)
    ax.set_xlabel(xlabel, fontsize=14)
    ax.set_ylabel(ylabel, fontsize=14)
    if title is not None:
        ax.set_title(title, fontsize=14)
    ax.xaxis.set_tick_params(labelsize=12)
    ax.yaxis.set_tick_params(labelsize=12)
    return ax


def plot_one_case(ax, x, y, label: str, ind: int = 0,
                  line_width: float = 2.0, markevery: int = 50):
    """One styled curve (plot_one_case, plot_utils.py:125-133)."""
    line, color, marker = determine_color_and_lines(ind)
    ax.plot(np.asarray(x), np.asarray(y), label=label,
            linewidth=line_width, linestyle=line, color=color,
            marker=marker, markevery=markevery, markersize=8)
    return ax


def build_legend(run_name: str, keys: Sequence[str]) -> str:
    """Legend text from the hyperparam-encoded run-folder name
    (build_legend, plot_utils.py:136-143): run folders are
    ``key-value`` parts joined by underscores (checkpoint.py naming)."""
    parts = dict(p.split("-", 1) for p in run_name.split("_")
                 if "-" in p)
    return ", ".join(f"{k}={parts[k]}" for k in keys if k in parts)


def plot_runs(runs: List[dict], metric: str = "top1", mode: str = "test",
              x_key: str = "round", legend_keys: Sequence[str] = ("alg",),
              smooth_window: int = 1, out_path: Optional[str] = None,
              title: Optional[str] = None):
    """Comparison figure across parsed runs (parse_records output):
    one styled curve per run of ``metric`` vs ``x_key`` from the val
    table (or the train table when ``mode='train'``). Saves to
    ``out_path`` when given; returns the matplotlib figure."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    for ind, run in enumerate(runs):
        if mode == "train":
            rows: List[Dict] = run["records"]["train"]
        else:
            rows = [r for r in run["records"]["val"]
                    if r.get("mode") == mode]
        if not rows:
            continue
        x = [r[x_key] for r in rows]
        y = [r[metric] for r in rows]
        if smooth_window > 1:
            y = smoothing(y, smooth_window)
            # trailing averages align to their window END (the reference
            # smoothing_func anchors at the same x, plot_utils.py:10-30)
            x = x[len(x) - len(y):]
        label = build_legend(run["name"], legend_keys) or run["name"]
        plot_one_case(ax, x, y, label, ind=ind,
                      markevery=max(len(x) // 10, 1))
    configure_figure(ax, xlabel=x_key, ylabel=metric, title=title)
    fig.tight_layout()
    if out_path is not None:
        fig.savefig(out_path, dpi=120)
    return fig
