"""Profiler-trace attribution: bucket a captured XLA trace's device
time into named op categories (device-side observability, pillar 2 of
docs/observability.md "Device-side").

``utils.tracing.capture_round_trace`` writes a Chrome-trace
``plugins/profile/<ts>/<host>.trace.json.gz`` under its capture dir.
Through round 8 that artifact was raw material an operator had to read
by hand in Perfetto — the ~90%-non-MXU headroom question (ROADMAP item
3) stayed "unattributed". This tool turns any capture dir into an
attribution table: every device op event — the events carrying XLA's
``hlo_op``/``hlo_module`` args (the CPU backend's Eigen/TfrtCpuClient
lanes emit them too, which is what makes this testable in tier-1), or
living on a ``/device:*`` "XLA Ops" lane (TPU/GPU) — is bucketed by
HLO op name into the taxonomy below, nested events are self-time
split, and the per-lane gap becomes the ``idle_gap`` category.

Taxonomy (ordered; first match wins — so ``reduce-scatter`` is
collective, ``reduce_add_fusion`` is reduce, a bare ``fusion.N`` loop
fusion is elementwise):

* ``collective``         — all-reduce/all-gather/reduce-scatter/
                           all-to-all/collective-permute (ICI/DCN time)
* ``infeed_outfeed_h2d`` — infeed/outfeed/copy-start/copy-done/
                           send/recv (host<->device transfers)
* ``matmul_conv_mxu``    — convolution/dot/matmul/einsum (MXU work —
                           the only bucket the roofline counts)
* ``reduce``             — reduce(-window)/arg-min-max/sort/cumsum/
                           select-and-scatter
* ``copy_reshape_transpose`` — copy/reshape/transpose/bitcast/slice/
                           gather/scatter/pad/concatenate/broadcast
* ``elementwise``        — pointwise math, converts, RNG, loop fusions
* ``control_flow``       — while/conditional/call shells (self time:
                           loop bookkeeping a scanned round pays every
                           local step)
* ``idle_gap``           — device-lane wall not covered by any op
* ``other``              — anything unmatched (the invariant keeps
                           this < 5%)

**Invariant**: ``attributed_frac`` (everything except ``other``) must
cover >= 95% of device time. ``fedtorch-tpu report --device <dir>``
renders the same table; standalone:

    python -m fedtorch_tpu.tools.trace_attrib <capture_dir> \\
        [--out attrib.json] [--render attrib.txt]

Stdlib-only (gzip + json): runs on a monitor box against a mounted
capture dir, never initializes JAX.
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

TRACE_ATTRIB_SCHEMA = "fedtorch_tpu.trace_attrib/v1"

ATTRIBUTED_MIN_FRAC = 0.95

# ordered (category, name-pattern) rules; matched case-insensitively
# against the HLO op/event name, first hit wins
CATEGORY_RULES: List[Tuple[str, "re.Pattern"]] = [
    ("collective", re.compile(
        r"all-reduce|all-gather|reduce-scatter|all-to-all|"
        r"collective|cross-replica", re.I)),
    ("infeed_outfeed_h2d", re.compile(
        r"infeed|outfeed|copy-start|copy-done|\bsend\b|\brecv\b|"
        r"transfer", re.I)),
    ("matmul_conv_mxu", re.compile(
        r"conv(?!ert)|\bdot\b|dot[._\-]|gemm|matmul|einsum", re.I)),
    ("reduce", re.compile(
        r"reduce|arg-?max|arg-?min|\bsort\b|sort[._\-]|cumsum|"
        r"cumulative|select-and-scatter|top-?k", re.I)),
    ("copy_reshape_transpose", re.compile(
        r"copy|reshape|transpose|bitcast|slice|gather|scatter|\bpad\b|"
        r"pad[._\-]|concat|reverse|broadcast|tuple", re.I)),
    ("elementwise", re.compile(
        r"fusion|add|sub|mul|div|max|min|tanh|exp\b|exp[._\-]|"
        r"exponential|expm1|log|pow|sqrt|rsqrt|sigmoid|logistic|"
        r"select|compare|convert|clamp|\band\b|\bor\b|\bxor\b|"
        r"\bnot\b|neg|abs|sign|shift|floor|ceil|round|rem\b|"
        r"remainder|sin|cos|atan|erf|rng|threefry|iota|constant|"
        r"is-finite|relu|softmax|map\b|map[._\-]", re.I)),
    # the while/conditional shells around lax.scan bodies: their SELF
    # time (loop-condition eval, iteration buffer shuffling) is real
    # device time a scan-shaped round program pays every local step —
    # a named line item, not "other". custom-call stays unknown.
    ("control_flow", re.compile(
        r"\bwhile\b|conditional|(?<!custom-)\bcall\b|\bcase\b", re.I)),
]

CATEGORIES = tuple(c for c, _ in CATEGORY_RULES) + ("idle_gap", "other")


def categorize(name: str) -> str:
    for cat, pat in CATEGORY_RULES:
        if pat.search(name):
            return cat
    return "other"


# -- trace discovery and parsing ----------------------------------------


def find_trace_files(path: str) -> List[str]:
    """Every trace file under ``path``: the jax profiler's
    ``plugins/profile/<ts>/*.trace.json.gz`` layout at any depth, plus
    plain ``*.trace.json`` twins (checked-in fixtures), plus ``path``
    itself when it already names a trace file."""
    if os.path.isfile(path):
        return [path]
    found: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json",
                "**/trace.json.gz"):
        found.extend(glob.glob(os.path.join(glob.escape(path), pat),
                               recursive=True))
    return sorted(set(found))


def load_trace_events(path: str) -> List[Dict]:
    """The ``traceEvents`` list of one (possibly gzipped) Chrome trace.
    Raises ``ValueError`` with the offending path on malformed input —
    a truncated capture must say so, not attribute garbage."""
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8", errors="replace"))
    except (OSError, json.JSONDecodeError, EOFError) as e:
        raise ValueError(f"{path}: not a readable Chrome trace "
                         f"({type(e).__name__}: {e})") from e
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    if not isinstance(evs, list):
        raise ValueError(f"{path}: no traceEvents list — not a Chrome "
                         "trace export")
    return evs


def _select_device_events(events: List[Dict]) -> List[Dict]:
    """The device op events: complete (``ph='X'``) events that carry
    XLA's ``hlo_op``/``hlo_module`` args (every backend), or sit on an
    'XLA Ops' lane of a ``/device:*`` process (TPU/GPU traces, where
    per-op args can be elided)."""
    procs: Dict = {}
    threads: Dict = {}
    for e in events:
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                procs[e.get("pid")] = str(
                    (e.get("args") or {}).get("name", ""))
            elif e.get("name") == "thread_name":
                threads[(e.get("pid"), e.get("tid"))] = str(
                    (e.get("args") or {}).get("name", ""))
    out = []
    for e in events:
        if e.get("ph") != "X" or "ts" not in e:
            continue
        args = e.get("args") or {}
        if "hlo_op" in args or "hlo_module" in args:
            out.append(e)
            continue
        proc = procs.get(e.get("pid"), "")
        lane = threads.get((e.get("pid"), e.get("tid")), "")
        if "/device:" in proc and "XLA Ops" in lane:
            out.append(e)
    return out


def _merge_intervals(intervals: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    """Union of [start, end) intervals as a sorted disjoint list."""
    merged: List[List[float]] = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], end)
        else:
            merged.append([start, end])
    return [(s, e) for s, e in merged]

# the idle window keeps >= this share of device busy time: the
# profiler occasionally flushes a stray event from a pre-window
# execution into the buffer, and a microsecond op seconds away from
# the real cluster must not read as seconds of device idle
_IDLE_TRIM_FRAC = 0.005


def _busy_span_idle(intervals: List[Tuple[float, float]]
                    ) -> Tuple[float, float, float]:
    """(busy, span, idle) microseconds. ``busy`` is the union of all
    op intervals; ``span``/``idle`` are measured over the trimmed
    window holding >= 99% of the busy mass (up to 0.5% dropped per
    side), so stray out-of-window events don't inflate the gap."""
    merged = _merge_intervals(intervals)
    if not merged:
        return 0.0, 0.0, 0.0
    busy = sum(e - s for s, e in merged)
    lo, hi = 0, len(merged) - 1
    lead = trail = 0.0
    while lo < hi and lead + (merged[lo][1] - merged[lo][0]) \
            <= _IDLE_TRIM_FRAC * busy:
        lead += merged[lo][1] - merged[lo][0]
        lo += 1
    while hi > lo and trail + (merged[hi][1] - merged[hi][0]) \
            <= _IDLE_TRIM_FRAC * busy:
        trail += merged[hi][1] - merged[hi][0]
        hi -= 1
    span = merged[hi][1] - merged[lo][0]
    in_window = busy - lead - trail
    return busy, span, max(span - in_window, 0.0)


def _lane_self_times(lane_events: List[Dict]
                     ) -> List[Tuple[str, float]]:
    """(name, self-duration) per event on one lane: a nested child's
    duration is subtracted from its enclosing parent, so module- or
    region-level wrappers don't double-count the ops they contain."""
    evs = sorted(lane_events,
                 key=lambda e: (e["ts"], -(e.get("dur") or 0.0)))
    rows: List[List] = []   # [name, dur, child_dur]
    stack: List[int] = []   # indices into rows, innermost last
    ends: List[float] = []
    for e in evs:
        ts = float(e["ts"])
        dur = float(e.get("dur") or 0.0)
        while stack and ts >= ends[stack[-1]] - 1e-9:
            stack.pop()
        if stack:
            rows[stack[-1]][2] += dur
        rows.append([str(e.get("name", "?")), dur, 0.0])
        ends.append(ts + dur)
        stack.append(len(rows) - 1)
    return [(name, max(dur - child, 0.0)) for name, dur, child in rows]


# -- attribution --------------------------------------------------------


def attribute_events(events: List[Dict]) -> Dict:
    """Attribute a flat device-event list (one trace file's worth)."""
    by_lane: Dict[Tuple, List[Dict]] = {}
    for e in events:
        by_lane.setdefault((e.get("pid"), e.get("tid")), []).append(e)

    cat_us: Dict[str, float] = {}
    cat_events: Dict[str, int] = {}
    op_us: Dict[str, float] = {}
    op_cat: Dict[str, str] = {}
    op_events: Dict[str, int] = {}
    intervals: List[Tuple[float, float]] = []
    for lane_events in by_lane.values():
        for e in lane_events:
            ts = float(e["ts"])
            intervals.append((ts, ts + float(e.get("dur") or 0.0)))
        for name, self_us in _lane_self_times(lane_events):
            cat = categorize(name)
            cat_us[cat] = cat_us.get(cat, 0.0) + self_us
            cat_events[cat] = cat_events.get(cat, 0) + 1
            # op key without the SSA suffix, so conv.1/conv.2 pool
            op = re.sub(r"[.\d]+$", "", name) or name
            op_us[op] = op_us.get(op, 0.0) + self_us
            op_events[op] = op_events.get(op, 0) + 1
            op_cat.setdefault(op, cat)

    busy, span, idle = _busy_span_idle(intervals)
    return {"cat_us": cat_us, "cat_events": cat_events, "op_us": op_us,
            "op_cat": op_cat, "op_events": op_events, "span_us": span,
            "busy_us": busy, "idle_us": idle,
            "lanes": len(by_lane), "events": len(events)}


def attribute(path: str) -> Dict:
    """The full attribution document for a capture dir (or a single
    trace file): every trace file's device events bucketed, summed,
    and checked against the >= 95%-attributed invariant."""
    files = find_trace_files(path)
    parts = []
    for f in files:
        evs = _select_device_events(load_trace_events(f))
        if evs:
            parts.append(attribute_events(evs))

    doc: Dict = {
        "schema": TRACE_ATTRIB_SCHEMA,
        "source": path,
        "trace_files": files,
        "device_lanes": sum(p["lanes"] for p in parts),
        "device_events": sum(p["events"] for p in parts),
    }
    if not parts:
        doc.update(total_us=0.0, categories={}, top_ops=[],
                   attributed_frac=None, attributed_ok=False,
                   note=("no device op events found (no trace files, "
                         "or none carrying hlo_op/XLA Ops lanes) — "
                         "nothing to attribute"))
        return doc

    cat_us: Dict[str, float] = {}
    cat_events: Dict[str, int] = {}
    op_us: Dict[str, float] = {}
    op_cat: Dict[str, str] = {}
    op_events: Dict[str, int] = {}
    idle = busy = span = 0.0
    for p in parts:
        for c, v in p["cat_us"].items():
            cat_us[c] = cat_us.get(c, 0.0) + v
        for c, v in p["cat_events"].items():
            cat_events[c] = cat_events.get(c, 0) + v
        for o, v in p["op_us"].items():
            op_us[o] = op_us.get(o, 0.0) + v
            op_events[o] = op_events.get(o, 0) + p["op_events"][o]
            op_cat.setdefault(o, p["op_cat"][o])
        idle += p["idle_us"]
        busy += p["busy_us"]
        span += p["span_us"]
    cat_us["idle_gap"] = idle
    cat_events.setdefault("idle_gap", 0)

    total = sum(cat_us.values())
    categories = {
        c: {"time_us": round(cat_us.get(c, 0.0), 3),
            "frac": round(cat_us.get(c, 0.0) / total, 6) if total else 0.0,
            "events": cat_events.get(c, 0)}
        for c in CATEGORIES if c in cat_us or c == "idle_gap"}
    attributed = 1.0 - (cat_us.get("other", 0.0) / total) if total \
        else None
    top = sorted(op_us.items(), key=lambda kv: -kv[1])[:15]
    doc.update(
        span_us=round(span, 3), busy_us=round(busy, 3),
        total_us=round(total, 3),
        categories=categories,
        attributed_frac=round(attributed, 6)
        if attributed is not None else None,
        attributed_ok=bool(attributed is not None
                           and attributed >= ATTRIBUTED_MIN_FRAC),
        top_ops=[{"name": o, "category": op_cat[o],
                  "time_us": round(us, 3), "events": op_events[o]}
                 for o, us in top],
    )
    return doc


# -- rendering ----------------------------------------------------------


def _fmt_us(us: float) -> str:
    if us >= 1e6:
        return f"{us / 1e6:.3f} s"
    if us >= 1e3:
        return f"{us / 1e3:.2f} ms"
    return f"{us:.1f} us"


def render(doc: Dict) -> str:
    lines = [f"device-time attribution: {doc['source']}"]
    if not doc.get("categories"):
        lines.append(f"  {doc.get('note', 'nothing to attribute')}")
        return "\n".join(lines)
    lines.append(
        f"  {doc['device_events']} device op events on "
        f"{doc['device_lanes']} lane(s); span {_fmt_us(doc['span_us'])}"
        f", busy {_fmt_us(doc['busy_us'])}")
    lines.append("  category                  time          share  events")
    for cat in CATEGORIES:
        rec = doc["categories"].get(cat)
        if rec is None:
            continue
        lines.append(f"  {cat:<24} {_fmt_us(rec['time_us']):>12}  "
                     f"{rec['frac'] * 100:5.1f}%  {rec['events']:6d}")
    frac = doc["attributed_frac"]
    if frac is None:
        # events selected but every duration zero/absent: nothing to
        # apportion — say so instead of dividing by the zero total
        lines.append("  attributed: n/a (device events carry no "
                     "durations)")
    else:
        flag = "OK" if doc["attributed_ok"] else \
            f"BELOW the {ATTRIBUTED_MIN_FRAC * 100:.0f}% invariant"
        lines.append(f"  attributed: {frac * 100:.1f}% of device time "
                     f"into named categories ({flag})")
    if doc.get("top_ops"):
        lines.append("  top ops by self time:")
        for op in doc["top_ops"][:8]:
            lines.append(
                f"    {op['name'][:36]:<36} {_fmt_us(op['time_us']):>12}"
                f"  [{op['category']}] x{op['events']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fedtorch_tpu.tools.trace_attrib",
        description="Attribute a jax.profiler capture dir's device "
                    "time into op categories "
                    "(docs/observability.md 'Device-side')")
    p.add_argument("capture_dir",
                   help="dir holding plugins/profile/*/... (or a "
                        "trace.json[.gz] file directly)")
    p.add_argument("--out", default=None,
                   help="also write the attribution JSON here")
    p.add_argument("--render", default=None,
                   help="also write the rendered table here")
    args = p.parse_args(argv)
    try:
        doc = attribute(args.capture_dir)
    except ValueError as e:
        print(f"trace_attrib: {e}", file=sys.stderr)
        return 2
    text = render(doc)
    print(text)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
    if args.render:
        os.makedirs(os.path.dirname(args.render) or ".", exist_ok=True)
        with open(args.render, "w") as f:
            f.write(text + "\n")
    if not doc.get("categories"):
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
