"""Post-hoc log parsing & summarization.

Parity with ``fedtorch/tools/``: regex-parse record files back into
structured tables (load_console_records.py:13-25), aggregate runs under a
checkpoint root with condition filtering (get_summary.py:100-158), and
smoothing for plots (plot_utils.py:10-60). Tables are plain dicts of numpy
arrays (pandas-compatible via ``pd.DataFrame(table)``).
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

import numpy as np

# matches RunLogger.log_train lines
_TRAIN_RE = re.compile(
    r"Round: (?P<round>\d+)\. Epoch: (?P<epoch>[\d.]+)\. "
    r"Local index: \d+\. Load: (?P<load>[\d.]+)s \| "
    r"Computing: (?P<computing>[\d.]+)s \| Sync: (?P<sync>[\d.]+)s \| "
    r"Global: (?P<global>[\d.]+)s \| Loss: (?P<loss>[-\d.einf]+) \| "
    r"top1: (?P<top1>[\d.]+) \| lr: (?P<lr>[\d.e-]+) \| "
    r"CommBytes: (?P<comm_bytes>[\d.]+)")

# matches RunLogger.log_val lines
_VAL_RE = re.compile(
    r"Round: (?P<round>\d+)\. Mode: (?P<mode>\w+)\. "
    r"Loss: (?P<loss>[-\d.einf]+) \| top1: (?P<top1>[\d.]+) \| "
    r"top5: (?P<top5>[\d.]+)")

_COMM_RE = re.compile(
    r"This round communication time is: (?P<seconds>[\d.e-]+)")


def load_record_file(path: str) -> Dict[str, List[dict]]:
    """Parse one record file into train/val/comm row lists
    (load_console_records.py:13-25 equivalent for our formats)."""
    out = {"train": [], "val": [], "comm": []}
    with open(path) as f:
        for line in f:
            m = _TRAIN_RE.search(line)
            if m:
                out["train"].append(
                    {k: float(v) for k, v in m.groupdict().items()})
                continue
            m = _VAL_RE.search(line)
            if m:
                row = m.groupdict()
                out["val"].append({
                    "round": float(row["round"]), "mode": row["mode"],
                    "loss": float(row["loss"]),
                    "top1": float(row["top1"]),
                    "top5": float(row["top5"])})
                continue
            m = _COMM_RE.search(line)
            if m:
                out["comm"].append({"seconds": float(m.group("seconds"))})
    return out


def parse_records(checkpoint_root: str,
                  conditions: Optional[Dict[str, str]] = None
                  ) -> List[dict]:
    """Walk a checkpoint tree, parse every record file, and filter by
    substring conditions on the run-folder name (get_summary.py:100-158).

    Returns a list of {"path", "name", "records"} entries."""
    runs = []
    for dirpath, _, files in os.walk(checkpoint_root):
        for fname in files:
            if not fname.startswith("record"):
                continue
            name = os.path.basename(dirpath)
            if conditions and not all(
                    f"{k}-{v}" in name for k, v in conditions.items()):
                continue
            runs.append({
                "path": dirpath,
                "name": name,
                "records": load_record_file(os.path.join(dirpath, fname)),
            })
    return runs


def smoothing(values, window: int = 10) -> np.ndarray:
    """Moving-average smoothing for plotting (plot_utils.py:10-60)."""
    values = np.asarray(values, np.float64)
    if len(values) == 0 or window <= 1:
        return values
    kernel = np.ones(min(window, len(values))) / min(window, len(values))
    return np.convolve(values, kernel, mode="valid")
