"""The asynchronous buffered federation plane (FedBuff-style server).

``cfg.federated.sync_mode='async'`` replaces the blocking round with a
COMMIT loop (Nguyen et al., arXiv:2106.06639; FedScale's async mode,
Lai et al. 2022): ``concurrency`` clients are always training, each
against the server snapshot current at its dispatch; the server folds
finished updates into a buffer of ``m = async_buffer_size`` and commits
when it fills — so the commit clock follows the FASTEST m arrivals and
a straggler delays only itself, not the round.

Execution shape (everything trace-once and deterministic):

* **Event schedule** (:mod:`.scheduler`): completion order is a pure
  function of (seed, commit) — threefry-derived delays reusing the
  chaos subsystem's straggler knobs. No update is materialized before
  its commit; the jitted COMMIT PROGRAM computes all m buffered local
  trainings at once, each against its own snapshot.
* **Snapshot ring**: ``server.aux`` is wrapped as ``{'alg': <algorithm
  aux>, 'ring': {'params', 'aux'}}`` — the last ``snapshot_ring``
  committed (params, server-aux) versions as stacked [R] trees, indexed
  in-program by each job's dispatch version. The wrap rides the
  existing checkpoint path, which is what makes a preempted async run
  resumable bitwise (tests/test_preemption.py).
* **Staleness weighting** (:mod:`.staleness`): each update's
  aggregation weight is damped by s(commit - version) and the composed
  weights flow through the guard renormalization
  (robustness/guards.py) — a rejected stale update hands back exactly
  its damped weight.
* **Commit program** = the sync engine's ``_round_core`` re-dispatched
  through its commit seam (parallel/federated.py): per-job base
  params/aux threaded through every local hook — SCAFFOLD's control
  step ``g + c - c_i`` and its control update both read the STALE
  server control the client actually trained against, which is the
  stale-snapshot correction async SCAFFOLD needs — then guards,
  renormalization, server step against the CURRENT params, and the
  ring rotates.

Algorithm gate: FedAvg/FedProx/FedAdam (server-side adaptivity) and
SCAFFOLD are wired; families whose hooks read global round structure
the buffer breaks (AFL/qFFL losses over the full cohort, DRFA's dual
phase and lambda participation, the personalized families' val
streams, qsparse's post-round tracking variate) raise a single
ValueError at construction naming the commit cell — the refusals live
in ``parallel/round_program.py`` with the rest of the composition
matrix, never deep in tracing. The commit PROGRAM itself is built
there too (the one-step member of the round-program family); this
module owns only the host side: the event scheduler, the snapshot-ring
state wrap, and the commit-keyed feed producer.
"""
from __future__ import annotations

import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.async_plane.scheduler import AsyncSchedule
from fedtorch_tpu.config import ExperimentConfig
from fedtorch_tpu.core.state import tree_broadcast_clients
from fedtorch_tpu.data.batching import ClientData, round_row_plan
from fedtorch_tpu.data.streaming import (
    StreamFeedProducer, _cpu_device, _cpu_scope,
)
from fedtorch_tpu.models.common import ModelDef
from fedtorch_tpu.parallel.federated import (
    FederatedTrainer, podscale_feed_placer,
)
from fedtorch_tpu.parallel.mesh import local_cohort_rows, replicate
from fedtorch_tpu.parallel.round_program import (
    ASYNC_ALGORITHMS, ASYNC_TRAIN_SALT, CommitJobs,
)
from fedtorch_tpu.robustness.availability import make_availability_model
from fedtorch_tpu.utils.tracing import instrument_trace

__all__ = ["ASYNC_ALGORITHMS", "AsyncFederatedTrainer", "CommitJobs"]


def _gate(why: str) -> ValueError:
    """Host-scheduler feasibility refusals (buffer/population sizing);
    the composition-matrix gates live in round_program.validate_cell."""
    return ValueError(
        f"sync_mode='async' is unsupported here: {why}; "
        "use --sync_mode sync")


class _AsyncRowPlan:
    """Host replica of the commit program's row plan (the async twin of
    ``data.streaming.RoundSchedule``): given the dispatch ids and
    client ids of one commit, reproduces EXACTLY the per-job training
    rngs (``fold_in(server.rng, ASYNC_TRAIN_SALT)`` then the dispatch
    fold) and ``round_row_plan`` rows the device commit program derives
    — threefry is backend-deterministic, so the CPU replay is
    bit-exact."""

    def __init__(self, key_data, key_impl, n_max: int, num_rows: int,
                 sizes: np.ndarray):
        self._cpu = _cpu_device()
        sizes = np.asarray(sizes, np.int32)

        def rows_fn(key, dispatch, idx):
            rngs = jax.vmap(lambda d: jax.random.fold_in(
                jax.random.fold_in(key, ASYNC_TRAIN_SALT), d))(dispatch)
            on_sizes = jnp.take(jnp.asarray(sizes), idx)
            return jax.vmap(lambda r, s: round_row_plan(
                r, s, n_max, num_rows))(rngs, on_sizes)

        with self._scope():
            self._key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(key_data)), impl=key_impl)
            # the key input is reused by every commit's replay
            # lint: disable=FTL004 — key reused every commit
            self._jit = jax.jit(rows_fn)

    def _scope(self):
        return _cpu_scope(self._cpu)

    def __call__(self, dispatch: np.ndarray, idx: np.ndarray):
        with self._scope():
            rows = self._jit(self._key,
                             np.asarray(dispatch, np.int32),
                             np.asarray(idx, np.int32))
            return np.asarray(jax.device_get(rows))


class AsyncFederatedTrainer(FederatedTrainer):
    """Drop-in trainer for ``sync_mode='async'``: :meth:`run_round`
    executes one COMMIT (``server.round`` counts commit versions, so
    the CLI round loop, checkpointing, eval cadence, preemption drain
    and the supervisor all work unchanged)."""

    supports_async = True
    # run_round serves the COMMIT dispatch: the base constructor
    # validates the (source x commit x execution) cell — algorithm,
    # val-stream, fused and shard-gather refusals all ride the one
    # validator in parallel/round_program.py
    construction_dispatch = "commit"

    def __init__(self, cfg: ExperimentConfig, model: ModelDef,
                 algorithm: FedAlgorithm, data: ClientData,
                 val_data=None, mesh=None, gather_mode: str = "auto"):
        fed = cfg.federated
        k_online = max(int(fed.online_client_rate * data.num_clients), 1)
        self.concurrency = fed.async_concurrency or k_online
        self.buffer_size = fed.async_buffer_size or max(
            1, self.concurrency // 2)
        if self.buffer_size > self.concurrency:
            raise _gate(
                f"async_buffer_size ({self.buffer_size}) exceeds the "
                f"in-flight concurrency ({self.concurrency}) — a commit "
                "could never fill")
        if data.num_clients < self.concurrency + self.buffer_size:
            raise _gate(
                f"num_clients ({data.num_clients}) must be >= "
                f"concurrency + buffer ({self.concurrency} + "
                f"{self.buffer_size}) so every arrival has a distinct "
                "replacement to dispatch")
        self.snapshot_ring = fed.snapshot_ring

        super().__init__(cfg, model, algorithm, data, val_data=val_data,
                         mesh=mesh, gather_mode=gather_mode)

        # commits always consume packed rows (round_row_plan order)
        self.gather_mode = "batch"
        # async stragglers are arrival DELAYS (the scheduler), not step
        # cuts — the freeze mask is epoch-sync-only here
        self.mask_steps = self.epoch_sync

        self._sched: Optional[AsyncSchedule] = None
        # the commit programs come from the round-program builder (the
        # degenerate one-step scan of the family) — no commit-specific
        # device code lives in this module anymore
        self.commit_trace_name = \
            f"federated.commit[{algorithm.name}]"
        self._commit_jit = jax.jit(
            instrument_trace(self.commit_trace_name,
                             self.programs.build("commit")),
            donate_argnums=(0, 1)) \
            if self.data_plane == "device" else None
        self.commit_stream_trace_name = \
            f"federated.commit_stream[{algorithm.name}]"
        self._commit_stream_jit = jax.jit(
            instrument_trace(self.commit_stream_trace_name,
                             self.programs.build("commit")),
            donate_argnums=(0, 1)) \
            if self.data_plane == "stream" else None
        # last scheduler's staleness histogram, preserved across
        # invalidate_stream teardowns so run-end/drain telemetry can
        # still emit it (the CLI's finally reads it AFTER the stream
        # teardown; a rebuilt scheduler's fast-forward replays every
        # commit, so a later live histogram supersedes the stash)
        self._hist_stash: Optional[dict] = None

    @property
    def metrics_width(self) -> int:
        """Sparse-mode commits emit [m]-wide cohort metrics — the m
        buffered jobs ARE the commit's cohort (perm keeps [C])."""
        return self.buffer_size if self.participation_mode == "sparse" \
            else self.num_clients

    # -- state -----------------------------------------------------------
    def init_state(self, rng: jax.Array):
        """Sync init, then wrap the server aux with the snapshot ring:
        every slot starts as version 0 (the init params/aux), which is
        exactly what the initial in-flight cohort trains against."""
        server, clients = super().init_state(rng)
        R = self.snapshot_ring
        ring = {"params": tree_broadcast_clients(server.params, R),
                "aux": tree_broadcast_clients(server.aux, R)}
        server = server._replace(aux={"alg": server.aux, "ring": ring})
        return replicate(server, self.mesh), clients

    # -- host-side commit loop -------------------------------------------
    def _schedule_args(self) -> dict:
        flt = self.fault
        return dict(
            num_clients=self.num_clients, concurrency=self.concurrency,
            buffer_size=self.buffer_size, ring_size=self.snapshot_ring,
            # 'sparse' keeps selection O(1) per dispatch at
            # million-client populations (scheduler rejection draw)
            participation_mode=self.participation_mode,
            straggler_rate=flt.straggler_rate,
            straggler_step_frac=flt.straggler_step_frac,
            # the arrival model (robustness/availability.py): the
            # default reproduces the legacy draws bitwise, 'trace'
            # arms device classes + diurnal dropout. Built fresh per
            # schedule so a rebuilt scheduler replays identically.
            model=make_availability_model(flt))

    def _server_key_state(self, server):
        """One batched fetch of (raw key data, commit) — paid only at
        (re)start, exactly like the sync stream plane's resync."""
        key_data, round0 = jax.device_get(
            (jax.random.key_data(server.rng), server.round))
        return key_data, jax.random.key_impl(server.rng), int(round0)

    def _ensure_schedule(self, server) -> None:
        if self._sched is not None:
            return
        key_data, key_impl, commit0 = self._server_key_state(server)
        self._sched = AsyncSchedule(key_data, key_impl,
                                    start_commit=commit0,
                                    **self._schedule_args())

    def _ensure_async_stream(self, server) -> None:
        if self._stream is not None:
            return
        key_data, key_impl, commit0 = self._server_key_state(server)
        sched = AsyncSchedule(key_data, key_impl, start_commit=commit0,
                              **self._schedule_args())
        # visible to schedule_stats / commit_times consumers on this
        # plane too (scripts/async_bench.py reads both); the producer
        # thread owns the simulation, so counters may run up to the
        # prefetch depth AHEAD of the last consumed commit
        self._sched = sched
        rows_fn = _AsyncRowPlan(
            key_data, key_impl, self.host_store.n_max,
            self.local_steps * self.batch_size, self.host_store.sizes)

        def plan_fn(step: int):
            plan = sched.next_commit()
            rows = rows_fn(plan.dispatch, plan.idx)
            jobs = CommitJobs(idx=plan.idx, version=plan.version,
                              dispatch=plan.dispatch,
                              straggler=plan.straggler)
            return plan.commit, plan.idx, rows, jobs

        # plan_fn must not close over self (producer-thread leak guard,
        # see FederatedTrainer._next_stream_feed)
        mesh = self.mesh
        if self.podscale_armed:
            # pod-scale commit plane: the m-wide buffer is the commit's
            # cohort — each host packs only its m/S block and the
            # placer assembles the cohort-sharded device feed (the
            # CommitJobs extras ride along replicated)
            place = podscale_feed_placer(mesh, self.buffer_size)
            cohort_rows = local_cohort_rows(mesh, self.buffer_size,
                                            self.client_shards)
        else:
            place = lambda t: replicate(t, mesh)  # noqa: E731
            cohort_rows = None
        self._stream = StreamFeedProducer(
            self.host_store, batch_size=self.batch_size,
            start_round=commit0, plan_fn=plan_fn,
            place_fn=place, cohort_rows=cohort_rows)
        self._stream_finalizer = weakref.finalize(
            self, StreamFeedProducer.close, self._stream)

    def run_round(self, server, clients):
        """One COMMIT: pop the scheduler's next m arrivals, run the
        commit program. Sequential-consumption contract and
        :meth:`invalidate_stream` resync semantics are the stream
        plane's (the scheduler replays from the live device state on
        (re)start, so supervisor rollback/reseed, checkpoint resume and
        the CLI drain all work unchanged)."""
        if self.data_plane == "stream":
            def pop():
                # re-ensures after an invalidate_stream teardown: the
                # rebuild wrapper's contract is that pop reconstructs
                # the producer (and the event scheduler with it) from
                # the live device state
                self._ensure_async_stream(server)
                return self._stream.next_feed()
            feed, jobs = self._pop_stream_with_rebuild(pop)
            return self._commit_stream_jit(server, clients, jobs, feed)
        self._ensure_schedule(server)
        plan = self._sched.next_commit()
        jobs = CommitJobs(idx=plan.idx, version=plan.version,
                          dispatch=plan.dispatch,
                          straggler=plan.straggler)
        return self._commit_jit(server, clients, jobs, self.data)

    # NOTE: run_rounds is NOT overridden — the base method's scan-cell
    # validation (parallel/round_program.py) raises the one cell-named
    # ValueError at call time: async commits are host-scheduled events,
    # so no R-commit program exists for run_rounds to scan.

    def lowered_cost_programs(self, server, clients,
                              num_scan_rounds: int = 0):
        """The async twin of the base trainer's cost-capture handles:
        the COMMIT program (per data plane) from the round-program
        builder, lowered uninstrumented against abstract [m] job
        inputs — no scheduler state is consumed and the sentinel sees
        nothing. ``num_scan_rounds`` is ignored (the scan cell is
        refused on this plane)."""
        m = self.buffer_size
        sds = lambda shape, dt: jax.ShapeDtypeStruct(shape, dt)
        jobs = CommitJobs(idx=sds((m,), jnp.int32),
                          version=sds((m,), jnp.int32),
                          dispatch=sds((m,), jnp.int32),
                          straggler=sds((m,), jnp.float32))
        commit_fn = self.programs.build("commit")
        if self.data_plane == "stream":
            primary = "commit_stream"
            lowered = jax.jit(
                commit_fn, donate_argnums=(0, 1)).lower(
                server, clients, jobs, self._feed_struct(k=m))
        else:
            primary = "commit"
            lowered = jax.jit(
                commit_fn, donate_argnums=(0, 1)).lower(
                server, clients, jobs, self.data)
        return {primary: lowered}, primary

    def invalidate_stream(self) -> None:
        """Also drop the event scheduler: any rewrite of host-visible
        training state (supervisor rollback/reseed, resume, drain)
        desyncs the replay; the next commit re-syncs from the live
        (rng, round) device state. The staleness histogram is stashed
        first — it is pure telemetry over ALREADY-committed updates,
        so it survives the teardown unchanged."""
        if self._sched is not None and self._sched.staleness_hist:
            self._hist_stash = dict(self._sched.staleness_hist)
        super().invalidate_stream()
        self._sched = None

    @property
    def schedule_stats(self):
        """Scheduler counters (dispatches/stragglers/ring clamps) —
        None before the first commit."""
        return self._sched.stats if self._sched is not None else None

    def telemetry_gauges(self) -> dict:
        """Stream gauges (when on that plane) plus the async commit
        plane's: buffer occupancy, scheduler dispatch/straggler/ring-
        clamp counters, and the commit rate in virtual time units
        (commits so far / last commit's virtual clock — the quantity
        ASYNC_AB.json compares against the sync round clock). All host
        counters; zero device syncs."""
        out = super().telemetry_gauges()
        sched = self._sched
        if sched is None:
            return out
        st = sched.stats
        ct = sched.commit_times
        out.update({
            "async_dispatches": float(st.dispatches),
            "async_stragglers": float(st.stragglers),
            "async_ring_clamped": float(st.staleness_clamped),
            "async_dropouts": float(st.dropouts),
            "async_buffer": float(self.buffer_size),
            "async_commit_rate": (len(ct) / ct[-1])
            if ct and ct[-1] > 0 else 0.0,
        })
        return out

    def staleness_histogram(self):
        """{commits-stale: count} over every committed update so far
        (post ring-clamp) — emitted as ``events.jsonl`` snapshot
        records (drain path, debug cadence, run end) rather than
        per-row (it is a dict, not a scalar gauge). Falls back to the
        pre-``invalidate_stream`` stash so the run-end emission — which
        runs after the stream teardown — still sees it."""
        if self._sched is not None and self._sched.staleness_hist:
            return dict(self._sched.staleness_hist)
        return dict(self._hist_stash) if self._hist_stash else None
