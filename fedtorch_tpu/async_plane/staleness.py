"""Staleness-weighted aggregation for the async commit plane.

A buffered update that trained against a snapshot ``tau`` commits old
carries less information about the CURRENT server model than a fresh
one; FedBuff (Nguyen et al., arXiv:2106.06639 §4) damps it with a
staleness weight ``s(tau)`` before averaging. Three standard shapes:

* ``poly`` — ``(1 + tau)^-a`` (the FedBuff polynomial default, a=0.5);
* ``inv``  — ``1 / (1 + tau)`` (harmonic; ``poly`` with a=1);
* ``const``— 1 (no damping; async ordering effects only).

Every shape satisfies ``s(0) == 1`` — a zero-staleness update is never
damped.

:func:`normalized_staleness_weights` rescales a commit's weights to
MEAN 1, so the composed aggregation weight (algorithm base weight x
staleness, ``parallel/federated.py:_round_core``) sums to the same
total as the sync round's — the server step keeps its sync magnitude,
and an all-fresh commit (every tau = 0) reproduces the sync weighting
exactly. Composition with the update guards is by construction: the
composed weights feed ``guards.renormalize_accepted``, so a REJECTED
stale update hands back exactly the damped weight it would have
contributed (tested in tests/test_async_plane.py).
"""
from __future__ import annotations

import jax.numpy as jnp

STALENESS_MODES = ("const", "poly", "inv")


def staleness_weight(tau, mode: str, exponent: float = 0.5):
    """Raw ``s(tau)`` over a [k] staleness vector (commits, >= 0)."""
    tau = jnp.asarray(tau, jnp.float32)
    if mode == "const":
        return jnp.ones_like(tau)
    if mode == "poly":
        return (1.0 + tau) ** (-exponent)
    if mode == "inv":
        return 1.0 / (1.0 + tau)
    raise ValueError(
        f"unknown staleness_weight mode {mode!r}; expected one of "
        f"{STALENESS_MODES}")


def normalized_staleness_weights(tau, mode: str, exponent: float = 0.5):
    """``s(tau)`` normalized to mean 1 over the commit buffer — the
    multiplier the engine composes into the aggregation weights."""
    s = staleness_weight(tau, mode, exponent)
    return s * (s.shape[0] / jnp.sum(s))
