"""Deterministic event schedule for the async commit plane.

The asynchronous server is simulated as an in-program discrete-event
system: ``concurrency`` clients are always training ("in flight"), each
against the snapshot version current at its dispatch; per-dispatch
completion delays, straggler flags and mid-round dropouts come from a
pluggable :class:`~fedtorch_tpu.robustness.availability
.AvailabilityModel` — all threefry draws off the experiment key, so
**client completion order is a pure function of (seed, commit)** — the
async plane stays testable, resumable, and trace-once like every other
plane. The default model reproduces the historical draws bitwise: the
chaos subsystem's straggler knobs reinterpreted as wall-clock long
tails (``fault.straggler_rate`` the probability a dispatch lands in
the tail, ``1/fault.straggler_step_frac`` its slowdown). That aliasing
is DEPRECATED spelling (config.finalize warns): ``fault
.avail_model='trace'`` selects the synthetic deployment trace —
device-class speed multipliers + diurnal dropout
(docs/robustness.md "Deployment realism").

One :meth:`AsyncSchedule.next_commit` pops the next ``buffer_size``
arrivals, immediately re-dispatching each arrived client's replacement
(sampled uniformly from the clients neither in flight nor already
buffered) against the current commit version, exactly FedBuff's server
loop (Nguyen et al., arXiv:2106.06639, Alg. 1). No update is ever
materialized before its commit: "in flight" is bookkeeping, and the
jitted commit program computes all m buffered local trainings at once —
which is what makes a preempted async run replayable: a resumed
scheduler fast-forwards the event simulation (cheap, no training FLOPs)
to the checkpoint's commit and the future is bitwise identical.

Like :class:`~fedtorch_tpu.data.streaming.RoundSchedule`, all draws run
jitted on the CPU backend: threefry is backend-deterministic, so the
host replay and the device program cannot diverge.
"""
from __future__ import annotations

import heapq
from typing import List, NamedTuple, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedtorch_tpu.data.streaming import _cpu_device, _cpu_scope
# the per-dispatch local-training salt lives with the round-program
# family whose PRNG contract it is (parallel/round_program.py);
# re-exported here for the host-replay twins that import it
from fedtorch_tpu.parallel.round_program import ASYNC_TRAIN_SALT  # noqa: F401
from fedtorch_tpu.robustness.availability import (
    LEGACY_DELAY_SALT, AvailabilityModel, DefaultAvailability,
)

# fold constants separating the scheduler's PRNG streams from the
# round streams (chaos_salt 0x7FFFFFFD, the augmentation parent
# 0x7FFFFFFF and ASYNC_TRAIN_SALT 0x7FFFFFF9 are taken; all are
# < 2^31 so fold_in accepts them). The delay salt's source of truth
# moved to robustness/availability.py with the model that owns the
# legacy fold chain; re-exported for the A/B twins that import it.
_DELAY_SALT = LEGACY_DELAY_SALT  # per-dispatch completion delay
_SELECT_SALT = 0x7FFFFFF5        # per-replacement client selection


class HostCommitPlan(NamedTuple):
    """One commit's buffered arrivals, in arrival order (host numpy).

    ``commit`` is the version this commit was built against (== the
    server round that consumes it); committing produces ``commit+1``."""
    commit: int
    idx: np.ndarray        # [m] int32 client ids (distinct)
    version: np.ndarray    # [m] int32 snapshot version each trained on
                           # (clamped into the ring window)
    dispatch: np.ndarray   # [m] int32 global dispatch counter (rng fold)
    straggler: np.ndarray  # [m] float32 {0,1} — tail-delay dispatches
    arrival_times: np.ndarray  # [m] float64 virtual arrival times
    commit_time: float     # virtual time the buffer filled


class ScheduleStats(NamedTuple):
    dispatches: int
    stragglers: int
    staleness_clamped: int  # arrivals older than the snapshot ring
    dropouts: int = 0       # mid-round dropouts (arrival discarded,
                            # replacement dispatched)


class AsyncSchedule:
    """The event simulation. Pure function of (key, constructor args);
    two instances with equal arguments produce identical commit
    sequences (the stream-plane producer and the trainer each hold
    one), and ``start_commit > 0`` fast-forwards a fresh instance to a
    resumed run's commit."""

    def __init__(self, key_data, key_impl, *, num_clients: int,
                 concurrency: int, buffer_size: int, ring_size: int,
                 straggler_rate: float, straggler_step_frac: float,
                 jitter: float = 0.25, start_commit: int = 0,
                 model: AvailabilityModel = None,
                 participation_mode: str = "perm"):
        if buffer_size < 1 or concurrency < 1:
            raise ValueError("buffer_size and concurrency must be >= 1")
        if participation_mode not in ("perm", "sparse"):
            raise ValueError(
                f"participation_mode must be 'perm' or 'sparse', got "
                f"{participation_mode!r}")
        if num_clients < concurrency + buffer_size:
            raise ValueError(
                f"async plane needs num_clients >= concurrency + "
                f"buffer_size ({concurrency} + {buffer_size}) so every "
                f"arrival has a distinct replacement to dispatch; got "
                f"{num_clients} clients")
        self.num_clients = num_clients
        self.concurrency = concurrency
        self.buffer_size = buffer_size
        self.ring_size = ring_size
        # 'perm' draws a [C] uniform score vector per selection (the
        # legacy bitwise-pinned stream); 'sparse' draws SCALAR uniform
        # ids with rejection — O(1) memory per draw, the
        # million-client mode (config.PARTICIPATION_MODES)
        self.participation_mode = participation_mode
        self._rate = float(straggler_rate)
        self._tail = 1.0 / float(straggler_step_frac)
        self._jitter = float(jitter)
        # no model = the pre-availability scheduler, bitwise: the
        # default model owns the exact legacy fold chain
        self._model = model if model is not None else \
            DefaultAvailability(straggler_rate=straggler_rate,
                                straggler_step_frac=straggler_step_frac,
                                jitter=jitter)

        self._cpu = _cpu_device()
        with self._scope():
            self._key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(key_data)), impl=key_impl)

            delays = self._model.traced

            if participation_mode == "sparse":
                def select(key, select_id):
                    r = jax.random.fold_in(
                        jax.random.fold_in(key, _SELECT_SALT),
                        select_id)
                    return jax.random.randint(r, (), 0, num_clients,
                                              dtype=jnp.int32)
            else:
                def select(key, select_id):
                    r = jax.random.fold_in(
                        jax.random.fold_in(key, _SELECT_SALT),
                        select_id)
                    return jax.random.uniform(r, (num_clients,))

            # the key input is reused by every draw — donation would
            # invalidate it; outputs are a few bytes
            # lint: disable=FTL004 — key reused by every event draw
            self._delays_jit = jax.jit(delays)
            # lint: disable=FTL004 — key reused by every event draw
            self._select_jit = jax.jit(select)

        # event state: min-heap of (finish_time, dispatch_id, client,
        # version, straggler, dropped) — dispatch_id breaks
        # (measure-zero) ties deterministically
        self._heap: List[Tuple[float, int, int, int, bool, bool]] = []
        self._inflight: Set[int] = set()
        self._dispatch_count = 0
        self._select_count = 0
        self._commit = 0
        self._stragglers = 0
        self._dropouts = 0
        self._clamped = 0
        self.commit_times: List[float] = []
        # staleness histogram: {commits-stale: count} over every
        # buffered update committed so far (post ring-clamp — the
        # staleness the aggregation actually damped). Host-only
        # telemetry (docs/observability.md); a fast-forwarded resume
        # rebuilds it exactly, since the sim replays every commit.
        self.staleness_hist: dict = {}

        # initial cohort: ``concurrency`` distinct clients against
        # version 0 at time 0
        if participation_mode == "sparse":
            cohort: List[int] = []
            taken: Set[int] = set()
            while len(cohort) < concurrency:
                c = self._select_id()
                if c not in taken:
                    taken.add(c)
                    cohort.append(c)
            for c in cohort:
                self._dispatch(c, version=0, now=0.0)
        else:
            scores = self._select_scores()
            for c in np.argsort(scores, kind="stable")[:concurrency]:
                self._dispatch(int(c), version=0, now=0.0)
        for _ in range(start_commit):
            self.next_commit()

    def _scope(self):
        return _cpu_scope(self._cpu)

    def _select_scores(self) -> np.ndarray:
        with self._scope():
            s = self._select_jit(self._key, np.int32(self._select_count))
            self._select_count += 1
            return np.asarray(jax.device_get(s))

    def _select_id(self) -> int:
        """One SCALAR uniform client draw ('sparse' mode) — same
        (salt, count) fold chain as the perm scores, but O(1) memory;
        the count advances per DRAW, so rejections consume entropy
        deterministically."""
        with self._scope():
            c = self._select_jit(self._key, np.int32(self._select_count))
            self._select_count += 1
            return int(jax.device_get(c))

    def _draw_delays(self, dispatch_ids: np.ndarray,
                     clients: np.ndarray, versions: np.ndarray):
        """One jitted model draw per dispatch batch -> float64 host
        math in the model's ``finish`` (the default model's split is
        bitwise-identical to the historical inline computation)."""
        versions = np.asarray(versions, np.int32)
        with self._scope():
            u = jax.device_get(self._delays_jit(
                self._key, np.asarray(dispatch_ids, np.int32),
                np.asarray(clients, np.int32), versions))
        return self._model.finish(np.asarray(u, np.float64), versions)

    def _dispatch(self, client: int, version: int, now: float) -> None:
        did = self._dispatch_count
        self._dispatch_count += 1
        delay, straggler, dropped = self._draw_delays(
            np.asarray([did]), np.asarray([client]),
            np.asarray([version]))
        if straggler[0]:
            self._stragglers += 1
        heapq.heappush(self._heap, (now + float(delay[0]), did, client,
                                    version, bool(straggler[0]),
                                    bool(dropped[0])))
        self._inflight.add(client)

    def _pick_replacement(self, exclude: Set[int]) -> int:
        if self.participation_mode == "sparse":
            # rejection sampling: |exclude| <= concurrency +
            # buffer_size - 1 < num_clients (constructor guard), so
            # acceptance probability is > 0 and at million-client
            # scale is ~1 — expected O(1) scalar draws, never a [C]
            # score vector
            while True:
                c = self._select_id()
                if c not in exclude:
                    return c
        scores = self._select_scores()
        for c in np.argsort(scores, kind="stable"):
            if int(c) not in exclude:
                return int(c)
        raise RuntimeError("no dispatchable client (guarded by the "
                           "num_clients >= concurrency + buffer check)")

    def next_commit(self) -> HostCommitPlan:
        """Pop the next ``buffer_size`` arrivals; re-dispatch each
        arrival's replacement immediately (against the CURRENT commit
        version — the buffer is not yet full, so no new version exists
        for it to see)."""
        m = self.buffer_size
        buffer: List[Tuple[float, int, int, int, bool]] = []
        buffered: Set[int] = set()
        while len(buffer) < m:
            t, did, client, version, straggler, dropped = \
                heapq.heappop(self._heap)
            self._inflight.discard(client)
            if dropped:
                # mid-round dropout: the arrival never reports — the
                # update is discarded (it was never materialized; "in
                # flight" is bookkeeping) and the slot re-fills. The
                # dropped client is offline, so it is excluded from
                # its own replacement draw.
                self._dropouts += 1
                repl = self._pick_replacement(
                    self._inflight | buffered | {client})
                self._dispatch(repl, version=self._commit, now=t)
                continue
            buffer.append((t, did, client, version, straggler))
            buffered.add(client)
            repl = self._pick_replacement(self._inflight | buffered)
            self._dispatch(repl, version=self._commit, now=t)

        floor = max(self._commit - (self.ring_size - 1), 0)
        versions = np.asarray([v for _, _, _, v, _ in buffer], np.int64)
        clamped = np.maximum(versions, floor)
        self._clamped += int(np.sum(clamped != versions))
        for s in (self._commit - clamped).tolist():
            self.staleness_hist[int(s)] = \
                self.staleness_hist.get(int(s), 0) + 1
        plan = HostCommitPlan(
            commit=self._commit,
            idx=np.asarray([c for _, _, c, _, _ in buffer], np.int32),
            version=clamped.astype(np.int32),
            dispatch=np.asarray([d for _, d, _, _, _ in buffer],
                                np.int32),
            straggler=np.asarray([s for *_, s in buffer], np.float32),
            arrival_times=np.asarray([t for t, *_ in buffer]),
            commit_time=buffer[-1][0])
        self._commit += 1
        self.commit_times.append(plan.commit_time)
        return plan

    @property
    def commit(self) -> int:
        return self._commit

    @property
    def stats(self) -> ScheduleStats:
        return ScheduleStats(dispatches=self._dispatch_count,
                             stragglers=self._stragglers,
                             staleness_clamped=self._clamped,
                             dropouts=self._dropouts)


def simulate_sync_round_times(key_data, key_impl, *, rounds: int,
                              k_online: int, straggler_rate: float,
                              straggler_step_frac: float,
                              jitter: float = 0.25) -> np.ndarray:
    """Virtual duration of each SYNC round under the same delay model:
    the server blocks on all k online clients, so a round costs the MAX
    of its k dispatch delays — the straggler sets the round clock. The
    async A/B (scripts/async_bench.py) compares this against
    :attr:`AsyncSchedule.commit_times`."""
    with _cpu_scope(_cpu_device()):
        key = jax.random.wrap_key_data(
            jnp.asarray(np.asarray(key_data)), impl=key_impl)
        ids = jnp.arange(rounds * k_online, dtype=jnp.int32)
        rngs = jax.vmap(lambda d: jax.random.fold_in(
            jax.random.fold_in(key, _DELAY_SALT), d))(ids)
        u = np.asarray(jax.device_get(jax.vmap(
            lambda r: jax.random.uniform(r, (2,)))(rngs)), np.float64)
    base = 1.0 + jitter * u[:, 1]
    tail = 1.0 / float(straggler_step_frac)
    delays = np.where(u[:, 0] < straggler_rate, base * tail, base)
    return delays.reshape(rounds, k_online).max(axis=1)
