"""Asynchronous buffered federation plane (FedBuff-style server).

Selected via ``cfg.federated.sync_mode='async'`` / ``--sync_mode
async``; ``sync`` (the default) is the round-synchronous engine,
bitwise-identical to the pre-async build. See docs/robustness.md
"Asynchronous federation" and docs/performance.md for the buffer
semantics, staleness math, snapshot-ring memory cost, and when sync
still wins.
"""
from fedtorch_tpu.async_plane.commit import (  # noqa: F401
    ASYNC_ALGORITHMS, AsyncFederatedTrainer, CommitJobs,
)
from fedtorch_tpu.async_plane.scheduler import (  # noqa: F401
    AsyncSchedule, HostCommitPlan, simulate_sync_round_times,
)
from fedtorch_tpu.async_plane.staleness import (  # noqa: F401
    STALENESS_MODES, normalized_staleness_weights, staleness_weight,
)
