"""ctypes bindings + background prefetcher for the native host pipeline.

Auto-compiles ``pipeline.cpp`` with g++ on first use (cached next to the
source); every entry point falls back to numpy when the toolchain or the
library is unavailable, so the Python-only path always works.
"""
from __future__ import annotations

import contextlib
import ctypes
import fcntl
import os
import queue
import subprocess
import threading
import time
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "pipeline.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__),
                         "libfedtorch_host.so")
_lib = None
_lib_tried = False


def _lib_fresh() -> bool:
    return (os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC))


def _build_library(run=subprocess.run) -> Optional[str]:
    """Compile pipeline.cpp to the shared library, safely under races.

    Two processes can reach here at once (an ElasticRunner relaunch
    racing a worker, multi-process gloo tests), and a ``dlopen`` of a
    half-written .so aborts the process — so the compiler writes to a
    private temp path and the result lands via atomic ``os.replace``,
    serialized by an exclusive per-path file lock. A process that waited
    on the lock re-checks freshness and adopts the winner's build
    instead of compiling twice. ``run`` is injectable for tests."""
    lock_path = _LIB_PATH + ".lock"
    tmp_path = f"{_LIB_PATH}.tmp.{os.getpid()}"
    try:
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f.fileno(), fcntl.LOCK_EX)
            try:
                if _lib_fresh():
                    return _LIB_PATH  # a racing builder finished first
                run(["g++", "-O3", "-shared", "-fPIC", "-o", tmp_path,
                     _SRC, "-lpthread"],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp_path, _LIB_PATH)
                return _LIB_PATH
            finally:
                fcntl.flock(lock_f.fileno(), fcntl.LOCK_UN)
    except Exception:
        return None
    finally:
        with contextlib.suppress(OSError):
            os.unlink(tmp_path)  # a failed compile's partial output


def _load_fault_injected() -> bool:
    """The 'native.load' host-chaos seam: an armed injector forces this
    load to report failure, driving the caller onto the numpy fallback
    (bitwise-identical output — the parity tests pin it). Lazy import
    keeps this module importable with ctypes+numpy alone."""
    try:
        from fedtorch_tpu.robustness import host_chaos
    except ImportError:  # partial install / standalone use
        return False
    return host_chaos.fire("native.load")


def load_library():
    """Load (building if needed) the native library; None on failure
    (or when the 'native.load' host-fault seam fires — a per-call
    forced numpy fallback that never poisons the cached handle)."""
    global _lib, _lib_tried
    if _load_fault_injected():
        return None
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    path = _LIB_PATH if _lib_fresh() else _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.ft_seeded_perm.argtypes = [
            ctypes.c_int64, ctypes.c_uint64,
            np.ctypeslib.ndpointer(np.int32)]
        lib.ft_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int32]
        lib.ft_cyclic_pad_indices.argtypes = [
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64]
        # POINTER(c_char) rather than c_char_p so a mutable bytearray
        # (via (c_char * n).from_buffer) passes zero-copy alongside bytes
        lib.ft_svmlight_count.argtypes = [
            ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
        lib.ft_svmlight_count.restype = ctypes.c_int64
        lib.ft_svmlight_scan.argtypes = [
            ctypes.POINTER(ctypes.c_char), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        lib.ft_svmlight_parse.argtypes = [
            ctypes.POINTER(ctypes.c_char), ctypes.c_int64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.float32),
            np.ctypeslib.ndpointer(np.float32), ctypes.c_int32]
        lib.ft_svmlight_parse.restype = ctypes.c_int32
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def native_available() -> bool:
    return load_library() is not None


def seeded_permutation(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of [0, n). Native Fisher-Yates when
    available, numpy otherwise (different but equally valid streams)."""
    lib = load_library()
    out = np.empty(n, np.int32)
    if lib is None:
        return np.random.RandomState(seed).permutation(n).astype(np.int32)
    lib.ft_seeded_perm(n, seed & 0xFFFFFFFFFFFFFFFF, out)
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray,
                num_threads: int = 0) -> np.ndarray:
    """dst[k] = src[idx[k]] over leading-axis rows, multithreaded."""
    lib = load_library()
    idx = np.ascontiguousarray(idx, np.int32)
    if lib is None:
        return np.ascontiguousarray(src[idx])
    src = np.ascontiguousarray(src)
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], initial=1))
    lib.ft_gather_rows(src.ctypes.data, row_bytes, idx, len(idx),
                       out.ctypes.data, num_threads)
    return out


def cyclic_pad_indices(idx: np.ndarray, n_out: int) -> np.ndarray:
    lib = load_library()
    idx = np.ascontiguousarray(idx, np.int32)
    if lib is None:
        reps = -(-n_out // len(idx))
        return np.tile(idx, reps)[:n_out]
    out = np.empty(n_out, np.int32)
    lib.ft_cyclic_pad_indices(idx, len(idx), out, n_out)
    return out


def parse_svmlight(data: "bytes | bytearray",
                   n_features: Optional[int] = None,
                   num_threads: int = 0):
    """Parse svmlight/libsvm text into a dense [n, f] float32 matrix
    and float32 labels — the native multithreaded replacement for
    sklearn's parser on the real-data path (data/datasets.py
    load_libsvm). ``None`` when the native library is unavailable (the
    caller falls back to sklearn). Raises ValueError on malformed
    input (bad separator, out-of-range or non-ascending index)."""
    lib = load_library()
    if lib is None:
        return None
    if not data.endswith(b"\n"):
        if isinstance(data, bytearray):
            data += b"\n"  # in place, no copy of a multi-GB buffer
        else:
            data = data + b"\n"  # the parser's line walker requires it
    if isinstance(data, bytearray):
        # zero-copy view for the POINTER(c_char) params (bytes objects
        # pass as-is)
        cbuf = (ctypes.c_char * len(data)).from_buffer(data)
    else:
        cbuf = data
    if n_features is None:
        n_rows = ctypes.c_int64()
        max_index = ctypes.c_int64()
        lib.ft_svmlight_scan(cbuf, len(data), ctypes.byref(n_rows),
                             ctypes.byref(max_index))
        n, f = int(n_rows.value), int(max_index.value)
    else:
        # known width: the cheap line count, no scan tokenization
        n, f = int(lib.ft_svmlight_count(cbuf, len(data))), \
            int(n_features)
    labels = np.empty(n, np.float32)
    dense = np.empty((n, f), np.float32)
    rc = lib.ft_svmlight_parse(cbuf, len(data), f, labels,
                               dense.reshape(-1), num_threads)
    if rc != 0:
        raise ValueError(
            "malformed svmlight input (bad 'index:value' pair, index "
            f"out of [1, {f}], or non-ascending indices)")
    return dense, labels


class HostPrefetcher:
    """Background-thread double buffering: overlaps the host-side gather
    of the next work item with device compute (the role of the
    reference's DataLoader worker processes)."""

    def __init__(self, produce_fn, depth: int = 2,
                 name: str = "host-prefetcher"):
        self._produce = produce_fn
        self.name = name
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        # the producer's fatal exception, kept BESIDE the queued copy:
        # the queue delivers it once, but every later next() (a
        # supervisor retry, a second consumer poll) must still raise
        # the real error immediately instead of a generic 120s timeout
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name=name)
        self._thread.start()

    def _worker(self):
        step = 0
        while not self._stop.is_set():
            try:
                item = self._produce(step)
            except StopIteration:
                self._put(None)
                return
            except BaseException as e:  # surface producer errors
                self._error = e
                self._put(e)
                return
            if not self._put(item):
                return  # stopped while waiting for queue space
            step += 1

    def _put(self, item) -> bool:
        """Bounded-wait put that keeps observing the stop flag: a
        worker parked on a full queue must exit promptly on close()
        instead of blocking in ``Queue.put`` forever."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def next(self, timeout: float = 60.0):
        """Next produced item, liveness-aware: a DEAD producer raises
        its stored exception (or a named death report) at the next
        short poll instead of burning the full ``timeout`` on an empty
        queue, and a timeout with the thread still ALIVE raises a
        :class:`TimeoutError` naming the wedged thread — the name to
        look for in the watchdog's stack dump."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                item = self._q.get(timeout=min(
                    0.2, max(deadline - time.monotonic(), 0.01)))
            except queue.Empty:
                # GIL-atomic single store: the worker writes _error
                # exactly once (then exits) and this side only reads —
                # a lock would add a queue-poll-rate hot path for a
                # once-per-lifetime publication
                if self._error is not None:  # lint: disable=FTH003 — worker's one write precedes its exit; reference-assignment is atomic
                    raise RuntimeError(
                        f"{self.name!r} producer thread died: "
                        f"{self._error!r}") from self._error
                if not self._thread.is_alive():
                    raise RuntimeError(
                        f"{self.name!r} producer thread exited without "
                        "delivering an item or an error")
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"{self.name!r} produced nothing for "
                        f"{timeout:.0f}s with its thread still alive — "
                        f"a WEDGED producer; look for thread "
                        f"{self.name!r} in the watchdog's stack dump")
                continue
            if isinstance(item, BaseException):
                raise item
            return item

    def alive(self) -> bool:
        """Producer-thread liveness (False once it exited — normally,
        after an error, or via close)."""
        return self._thread.is_alive()

    def depth(self) -> int:
        """Items currently buffered (approximate by nature — the worker
        appends concurrently); the stream plane's prefetch-depth gauge
        (fedtorch_tpu.telemetry): depth 0 at fetch time means the
        consumer is about to block on the producer."""
        return self._q.qsize()

    def close(self, join_timeout: float = 5.0) -> bool:
        """Stop the producer and drop queued items. Returns True when
        the worker thread actually exited within the bounded join —
        False means it is still finishing one in-flight produce call
        (it observes the stop flag at its next put and exits on its
        own; the thread is a daemon, so a drain with a deadline is
        never blocked on it). Idempotent."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=join_timeout)
        return not self._thread.is_alive()
