// Native host-side data pipeline.
//
// The reference's input pipeline rides torch's C++ DataLoader machinery
// (worker processes doing shuffle + collate). This is the TPU build's
// equivalent native layer: seeded permutation generation and
// multi-threaded row gather used to materialize the padded
// [clients, N, ...] device-feed arrays (fedtorch_tpu/data/batching.py)
// and per-epoch reshuffles without Python-loop overhead.
//
// Exposed via a plain C ABI consumed with ctypes
// (fedtorch_tpu/native/host_pipeline.py); no pybind11 dependency.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libfedtorch_host.so
//        pipeline.cpp -lpthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, high-quality seeded generator for shuffles.
static inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Fisher-Yates permutation of [0, n) into out, deterministic in seed.
void ft_seeded_perm(int64_t n, uint64_t seed, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = static_cast<int32_t>(i);
  uint64_t state = seed ^ 0xD1B54A32D192ED03ULL;
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = splitmix64(state) % static_cast<uint64_t>(i + 1);
    int32_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
}

// Gather rows: dst[k] = src[idx[k]] for row_bytes-sized rows, using
// num_threads workers (0 = hardware concurrency).
void ft_gather_rows(const void* src, int64_t row_bytes,
                    const int32_t* idx, int64_t n_idx, void* dst,
                    int32_t num_threads) {
  const char* s = static_cast<const char*>(src);
  char* d = static_cast<char*>(dst);
  int threads = num_threads > 0
                    ? num_threads
                    : static_cast<int32_t>(
                          std::thread::hardware_concurrency());
  if (threads < 1) threads = 1;
  if (threads == 1 || n_idx < 4 * threads) {
    for (int64_t k = 0; k < n_idx; ++k) {
      std::memcpy(d + k * row_bytes, s + int64_t(idx[k]) * row_bytes,
                  row_bytes);
    }
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n_idx + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n_idx ? lo + chunk : n_idx;
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t k = lo; k < hi; ++k) {
        std::memcpy(d + k * row_bytes, s + int64_t(idx[k]) * row_bytes,
                    row_bytes);
      }
    });
  }
  for (auto& th : pool) th.join();
}

// Cyclically pad an index list: out[k] = idx[k % n_idx] for k < n_out.
// (stack_partitions' padding rule, batching.py:41-65.)
void ft_cyclic_pad_indices(const int32_t* idx, int64_t n_idx,
                           int32_t* out, int64_t n_out) {
  for (int64_t k = 0; k < n_out; ++k) out[k] = idx[k % n_idx];
}

}  // extern "C"
