// Native host-side data pipeline.
//
// The reference's input pipeline rides torch's C++ DataLoader machinery
// (worker processes doing shuffle + collate). This is the TPU build's
// equivalent native layer: seeded permutation generation and
// multi-threaded row gather used to materialize the padded
// [clients, N, ...] device-feed arrays (fedtorch_tpu/data/batching.py)
// and per-epoch reshuffles without Python-loop overhead.
//
// Exposed via a plain C ABI consumed with ctypes
// (fedtorch_tpu/native/host_pipeline.py); no pybind11 dependency.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libfedtorch_host.so
//        pipeline.cpp -lpthread

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, high-quality seeded generator for shuffles.
static inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Fisher-Yates permutation of [0, n) into out, deterministic in seed.
void ft_seeded_perm(int64_t n, uint64_t seed, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = static_cast<int32_t>(i);
  uint64_t state = seed ^ 0xD1B54A32D192ED03ULL;
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = splitmix64(state) % static_cast<uint64_t>(i + 1);
    int32_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
}

// Gather rows: dst[k] = src[idx[k]] for row_bytes-sized rows, using
// num_threads workers (0 = hardware concurrency).
void ft_gather_rows(const void* src, int64_t row_bytes,
                    const int32_t* idx, int64_t n_idx, void* dst,
                    int32_t num_threads) {
  const char* s = static_cast<const char*>(src);
  char* d = static_cast<char*>(dst);
  int threads = num_threads > 0
                    ? num_threads
                    : static_cast<int32_t>(
                          std::thread::hardware_concurrency());
  if (threads < 1) threads = 1;
  if (threads == 1 || n_idx < 4 * threads) {
    for (int64_t k = 0; k < n_idx; ++k) {
      std::memcpy(d + k * row_bytes, s + int64_t(idx[k]) * row_bytes,
                  row_bytes);
    }
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n_idx + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n_idx ? lo + chunk : n_idx;
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t k = lo; k < hi; ++k) {
        std::memcpy(d + k * row_bytes, s + int64_t(idx[k]) * row_bytes,
                    row_bytes);
      }
    });
  }
  for (auto& th : pool) th.join();
}

// Cyclically pad an index list: out[k] = idx[k % n_idx] for k < n_out.
// (stack_partitions' padding rule, batching.py:41-65.)
void ft_cyclic_pad_indices(const int32_t* idx, int64_t n_idx,
                           int32_t* out, int64_t n_out) {
  for (int64_t k = 0; k < n_out; ++k) out[k] = idx[k % n_idx];
}

}  // extern "C"

// ---------------------------------------------------------------------------
// svmlight/libsvm text parser (the LibSVM datasets' on-disk format:
// "<label> <index>:<value> ...", 1-based ascending sparse indices,
// '#' comments — see tests/format_fixtures.py for the spec notes).
// Replaces sklearn's Python/Cython parser on the real-data path
// (epsilon is a ~12 GB text file; parse speed is the load bottleneck).
// The buffer must end with '\n' (the Python wrapper guarantees it).

namespace {

struct LineRange {
  const char* begin;
  const char* end;  // exclusive, at the '\n'
};

// Collect [begin, end) of every DATA line (non-empty after whitespace,
// not a '#' comment line).
static std::vector<LineRange> data_lines(const char* buf, int64_t len) {
  std::vector<LineRange> lines;
  const char* p = buf;
  const char* limit = buf + len;
  while (p < limit) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(limit - p)));
    if (nl == nullptr) nl = limit;
    const char* q = p;
    while (q < nl && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q < nl && *q != '#') lines.push_back({q, nl});
    p = nl + 1;
  }
  return lines;
}

// Parse one data line into labels[row] and dense[row * n_features].
// Returns false on malformed input (bad separator, index out of
// [1, n_features], non-ascending index).
static bool parse_line(const LineRange& ln, int64_t n_features,
                       float* label, float* dense_row) {
  char* cursor = nullptr;
  *label = std::strtof(ln.begin, &cursor);
  if (cursor == ln.begin) return false;
  const char* p = cursor;
  int64_t prev_idx = 0;
  while (p < ln.end) {
    while (p < ln.end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    if (p >= ln.end || *p == '#') break;  // trailing comment
    long long idx = std::strtoll(p, &cursor, 10);
    if (cursor == p || cursor >= ln.end || *cursor != ':') return false;
    if (idx < 1 || idx > n_features || idx <= prev_idx) return false;
    prev_idx = idx;
    p = cursor + 1;
    // the value must start HERE: strtof skips leading whitespace
    // (including '\n'), so a missing value would otherwise silently
    // consume the next line's label
    if (p >= ln.end || *p == ' ' || *p == '\t' || *p == '\r') {
      return false;
    }
    float v = std::strtof(p, &cursor);
    if (cursor == p || cursor > ln.end) return false;
    dense_row[idx - 1] = v;
    p = cursor;
  }
  return true;
}

}  // namespace

extern "C" {

// Number of data rows only — O(bytes) memchr walk, no tokenization.
// Callers that already know n_features (the test-split path) use this
// instead of the scan, so the serial pass stays cheap (Amdahl).
int64_t ft_svmlight_count(const char* buf, int64_t len) {
  return static_cast<int64_t>(data_lines(buf, len).size());
}

// Pass 1: number of data rows and the maximum feature index seen.
// Indices ascend within a line, so only the LAST "idx:val" token needs
// parsing — a backward walk per line, not a full tokenization (the
// full-tokenization fallback handles lines with '#' comments).
void ft_svmlight_scan(const char* buf, int64_t len, int64_t* n_rows,
                      int64_t* max_index) {
  auto lines = data_lines(buf, len);
  *n_rows = static_cast<int64_t>(lines.size());
  int64_t mx = 0;
  char* cursor = nullptr;
  for (const auto& ln : lines) {
    int64_t row_max = 0;
    const char* hash = static_cast<const char*>(std::memchr(
        ln.begin, '#', static_cast<size_t>(ln.end - ln.begin)));
    if (hash == nullptr) {
      // fast path: trim trailing whitespace, take the last token
      const char* e = ln.end;
      while (e > ln.begin &&
             (*(e - 1) == ' ' || *(e - 1) == '\t' || *(e - 1) == '\r'))
        --e;
      const char* sp = e;
      while (sp > ln.begin && *(sp - 1) != ' ' && *(sp - 1) != '\t')
        --sp;
      if (sp > ln.begin) {  // a pair exists (not just the label)
        long long idx = std::strtoll(sp, &cursor, 10);
        if (cursor != sp && cursor < e && *cursor == ':') row_max = idx;
      }
    } else {
      // comment on the line: tokenize forward up to the '#'
      const char* q = ln.begin;
      std::strtof(q, &cursor);  // skip label
      q = cursor;
      while (q < ln.end) {
        while (q < ln.end && (*q == ' ' || *q == '\t' || *q == '\r'))
          ++q;
        if (q >= ln.end || *q == '#') break;
        long long idx = std::strtoll(q, &cursor, 10);
        if (cursor == q || cursor >= ln.end || *cursor != ':') break;
        if (idx > row_max) row_max = idx;
        q = cursor + 1;
        std::strtof(q, &cursor);
        if (cursor == q) break;
        q = cursor;
      }
    }
    if (row_max > mx) mx = row_max;
  }
  *max_index = mx;
}

// Pass 2: fill labels[n_rows] and zero-initialized
// dense[n_rows * n_features], multithreaded over line ranges.
// Returns 0 on success, -1 if any line is malformed.
int32_t ft_svmlight_parse(const char* buf, int64_t len,
                          int64_t n_features, float* labels,
                          float* dense, int32_t num_threads) {
  auto lines = data_lines(buf, len);
  const int64_t n = static_cast<int64_t>(lines.size());
  std::memset(dense, 0,
              static_cast<size_t>(n * n_features) * sizeof(float));
  int threads = num_threads > 0
                    ? num_threads
                    : static_cast<int32_t>(
                          std::thread::hardware_concurrency());
  if (threads < 1) threads = 1;
  std::atomic<int32_t> bad{0};
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      if (!parse_line(lines[static_cast<size_t>(r)], n_features,
                      labels + r, dense + r * n_features)) {
        bad.store(1, std::memory_order_relaxed);
        return;
      }
    }
  };
  if (threads == 1 || n < 4 * threads) {
    work(0, n);
  } else {
    std::vector<std::thread> pool;
    int64_t chunk = (n + threads - 1) / threads;
    for (int t = 0; t < threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      pool.emplace_back(work, lo, hi);
    }
    for (auto& th : pool) th.join();
  }
  return bad.load() ? -1 : 0;
}

}  // extern "C"
