from fedtorch_tpu.native.host_pipeline import (  # noqa: F401
    HostPrefetcher, cyclic_pad_indices, gather_rows, load_library,
    native_available, seeded_permutation,
)
