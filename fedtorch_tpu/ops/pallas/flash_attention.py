"""Pallas TPU kernel: fused flash attention (forward) + memory-efficient
custom VJP.

The transformer's dense attention (models/transformer.py _SelfAttention)
materializes the full [B, H, T, T] score matrix in HBM — O(T^2) memory
and three HBM sweeps (scores, softmax, combine). This kernel computes
exact attention with the online-softmax recurrence (Rabe & Staats
arXiv:2112.05682; FlashAttention arXiv:2205.14135): each (batch·head,
q-block) grid cell streams K/V blocks through VMEM, keeping running
(max, sum, accumulator) statistics, so score memory is one
[block_q, block_k] tile and the output gets ONE HBM write. Causal mode
skips fully-masked K blocks outright (the loop bound, not a mask, so the
causal forward does ~half the FLOPs).

The backward pass recomputes probabilities blockwise from the saved
logsumexp — the standard flash VJP — as a `lax.scan` over q-blocks in
plain XLA: O(T·block) live memory, no T^2 tensor, and exact gradients
(tests pin both against the dense oracle).

Off-TPU (CPU tests, relay-wedged hosts) `flash_attention` transparently
uses the same math via the interpreter or the dense oracle — safe to
call anywhere, like the quantization kernel (quant_kernel.py).

Layout note: q/k/v arrive [B, T, H, D] (the repo's sequence-parallel
layout, parallel/sequence.py) and are re-laid-out to [B·H, T, D] so the
grid's leading axis enumerates independent attention problems.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Mosaic requires the last two dims of every block to be divisible by
# (8, 128) or equal to the array dims (jax/_src/pallas/mosaic/
# lowering.py:_check_block_mappings — validated against the real
# lowering in round 5: a [1, block_q] lse block is REJECTED on-chip
# even though the interpreter accepts it). Per-q-row statistics in
# VMEM SCRATCH therefore carry a broadcast 128-lane trailing dim, the
# same layout production TPU flash kernels use; lane 0 is the value.
_LANES = 128

# jax renamed TPUCompilerParams -> CompilerParams; accept either so the
# kernel (and its interpret-mode tests) run across the jaxlib span the
# relay and the CI container actually ship
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")
# The lse HBM OUTPUT does not need the full broadcast: a [BH, T, 8]
# array with a (1, block_q, 8) block also satisfies the rule (last
# block dim EQUALS the array dim; block_q is a divisor block, >= 16 or
# == T, so the sublane constraint holds) and Mosaic accepts the
# lowering (pinned by the AOT-lowering tests in
# tests/test_flash_attention.py). At 8 lanes the lse write is T*8*4
# bytes per head — 16x less HBM traffic than the 128-lane broadcast
# the advisor flagged (ADVICE r5: at D=64/bf16 the broadcast lse write
# was ~4x the size of the o output itself).
_LSE_LANES = 8

# dispatch policy ('auto' backend selection) lives in the pallas-free
# ops/attention_dispatch.py so the dense path never imports this
# module; re-exported here for kernel-side callers
from fedtorch_tpu.ops.attention_dispatch import (  # noqa: E402,F401
    FLASH_MIN_SEQ_LEN, resolve_attention,
)


def _kernel_finite(x):
    """``jnp.isfinite`` spelled as a comparison: NaN and +/-inf both
    compare False under ``abs(x) < inf``. The ``is_finite`` HLO has no
    Pallas TPU lowering on the older jaxlibs this repo still runs
    (the AOT-lowering tests pin this), and the comparison form lowers
    everywhere with identical semantics."""
    return jnp.abs(x) < jnp.inf


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale: float, causal: bool):
    """One (batch·head, q-block, k-block) grid cell. The k axis is the
    innermost ('arbitrary') grid dimension: running (max, sum, acc)
    stats live in VMEM scratch across its iterations, so only ONE
    [block_k, D] K/V tile is resident at a time — true streaming, no
    full-sequence VMEM residency. m/l scratch and the lse output are
    [blk_q, 128] lane-broadcast (every lane equal; see _LANES)."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    blk_q = q_ref.shape[1]
    blk_k = k_ref.shape[1]

    @pl.when(kb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def update():
        q = q_ref[0].astype(jnp.float32)                 # [blk_q, D]
        k_blk = k_ref[0].astype(jnp.float32)             # [blk_k, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [blk_q, blk_k]
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m = m_scr[:]                                     # [blk_q, 128]
        m_blk = jnp.max(s, axis=-1, keepdims=True)       # [blk_q, 1]
        m_new = jnp.maximum(m, m_blk)                    # [blk_q, 128]
        m_safe = jnp.where(_kernel_finite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, :1])
        p = jnp.where(_kernel_finite(s), p, 0.0)           # [blk_q, blk_k]
        corr = jnp.where(_kernel_finite(m), jnp.exp(m - m_safe), 0.0)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr[:, :1] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # K blocks entirely past this q-block's last position contribute
        # nothing — skip their FLOPs outright (~half the grid)
        pl.when(kb * blk_k <= (qi + 1) * blk_q - 1)(update)
    else:
        update()

    @pl.when(kb == nk - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe[:, :1]).astype(o_ref.dtype)
        m_fin = jnp.where(_kernel_finite(m_scr[:]), m_scr[:], 0.0)
        # scratch stays 128-lane; only the first _LSE_LANES lanes hit
        # HBM (every lane equal — lane 0 is the value)
        lse_ref[0] = (m_fin + jnp.log(l_safe))[:, :lse_ref.shape[-1]]


def _fwd_pallas(q3, k3, v3, scale: float, causal: bool, block_q: int,
                block_k: int, interpret: bool):
    """[BH, T, D] forward -> (o [BH, T, D], lse [BH, T] f32)."""
    BH, T, D = q3.shape
    grid = (BH, T // block_q, T // block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal)
    # Under shard_map (ring/ulysses call this per shard), jax's vma
    # check requires pallas_call outputs to declare which mesh axes
    # they vary over — propagate the inputs' vma (round-5 on-chip
    # finding: the CPU path never hit this because off-TPU flash falls
    # back to the XLA oracle, so the real kernel inside shard_map was
    # first exercised on the chip).
    _typeof = getattr(jax, "typeof", None)
    vmas = [getattr(_typeof(t), "vma", None) if _typeof is not None
            else None for t in (q3, k3, v3)]
    # lint: disable=FTL005 — vma presence is static sharding metadata
    if any(v is not None for v in vmas):
        # pass vma even when EMPTY: inside shard_map with replicated
        # q/k/v the check still requires an explicit (empty) vma
        vkw = {"vma": frozenset().union(*(v or frozenset()
                                          for v in vmas))}
    else:  # very old jax: aval has no vma concept
        vkw = {}
    o, lse_lanes = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q3.dtype, **vkw),
            jax.ShapeDtypeStruct((BH, T, _LSE_LANES), jnp.float32,
                                 **vkw),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, D), jnp.float32),       # accumulator
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse_lanes[:, :, 0]


def _fwd_xla(q3, k3, v3, scale: float, causal: bool):
    """Dense [BH, T, D] oracle forward returning (o, lse) — identical
    semantics to the kernel, for off-TPU fallback."""
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(jnp.float32),
                   k3.astype(jnp.float32)) * scale
    # lint: disable=FTL005 — causal is a static config flag
    if causal:
        T = q3.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.maximum(l, 1e-30)
    o = jnp.einsum("bqk,bkd->bqd", p / l_safe, v3.astype(jnp.float32))
    lse = (m_safe + jnp.log(l_safe))[..., 0]
    return o.astype(q3.dtype), lse


def _bwd_chunked(res, g, g_lse=None, *, scale: float, causal: bool,
                 block_q: int):
    """Flash VJP: recompute p blockwise from the saved logsumexp and
    accumulate dk/dv over a q-block scan — O(T·block_q) live memory.
    Pure XLA on purpose: it runs identically on TPU and in CPU tests,
    and XLA fuses the per-block einsums well.

    ``g_lse`` is the logsumexp cotangent (when the caller consumed the
    lse output — the ring-attention merge does): ∂lse/∂s = p, so it
    adds a ``g_lse·p`` term to the score cotangent; lse is independent
    of v."""
    q3, k3, v3, o3, lse = res
    BH, T, D = q3.shape
    f32 = jnp.float32
    q3f, k3f, v3f, o3f, gf = (t.astype(f32) for t in
                              (q3, k3, v3, o3, g))
    glf = jnp.zeros_like(lse) if g_lse is None else g_lse.astype(f32)
    # D_i = rowsum(do * o) — the softmax-jacobian diagonal term
    delta = jnp.sum(gf * o3f, axis=-1)                   # [BH, T]
    nq = T // block_q

    def step(carry, i):
        dk, dv = carry
        sl = jax.lax.dynamic_slice_in_dim
        q_i = sl(q3f, i * block_q, block_q, 1)           # [BH, bq, D]
        g_i = sl(gf, i * block_q, block_q, 1)
        lse_i = sl(lse, i * block_q, block_q, 1)
        d_i = sl(delta, i * block_q, block_q, 1)
        gl_i = sl(glf, i * block_q, block_q, 1)
        s = jnp.einsum("bqd,bkd->bqk", q_i, k3f) * scale
        if causal:
            q_pos = i * block_q + jnp.arange(block_q)
            mask = q_pos[:, None] >= jnp.arange(T)[None]
            s = jnp.where(mask[None], s, -jnp.inf)
        p = jnp.exp(s - lse_i[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)           # [BH, bq, T]
        dv = dv + jnp.einsum("bqk,bqd->bkd", p, g_i)
        dp = jnp.einsum("bqd,bkd->bqk", g_i, v3f)
        ds = p * (dp - d_i[..., None] + gl_i[..., None]) * scale
        dq_i = jnp.einsum("bqk,bkd->bqd", ds, k3f)
        dk = dk + jnp.einsum("bqk,bqd->bkd", ds, q_i)
        return (dk, dv), dq_i

    (dk, dv), dq_blocks = jax.lax.scan(
        step, (jnp.zeros_like(k3f), jnp.zeros_like(v3f)),
        jnp.arange(nq))
    # [nq, BH, bq, D] -> [BH, T, D]
    dq = jnp.moveaxis(dq_blocks, 0, 1).reshape(BH, T, D)
    return (dq.astype(q3.dtype), dk.astype(k3.dtype),
            dv.astype(v3.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash3(q3, k3, v3, scale, causal, block_q, block_k, use_pallas):
    out, _ = _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                         use_pallas)
    return out


def _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k, use_pallas):
    # lint: disable=FTL005 — use_pallas is a static backend switch
    if use_pallas is None or use_pallas:
        o, lse = _fwd_pallas(q3, k3, v3, scale, causal, block_q,
                             block_k, interpret=use_pallas is None)
    else:
        o, lse = _fwd_xla(q3, k3, v3, scale, causal)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(scale, causal, block_q, block_k, use_pallas, res, g):
    return _bwd_chunked(res, g, scale=scale, causal=causal,
                        block_q=block_q)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash3_lse(q3, k3, v3, scale, causal, block_q, block_k,
                use_pallas):
    """Like _flash3 but also returns the logsumexp [BH, T] — the
    statistic that makes attention outputs MERGEABLE (ring attention
    combines per-block results by lse weighting). Differentiable in
    both outputs (joint VJP in _bwd_chunked)."""
    out, res = _flash3_lse_fwd(q3, k3, v3, scale, causal, block_q,
                               block_k, use_pallas)
    return out


def _flash3_lse_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                    use_pallas):
    # one backend-dispatch implementation: _flash3_fwd's residuals
    # already carry the lse, so the lse-returning variant just
    # surfaces it — the two public kernels cannot diverge
    out, res = _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                           use_pallas)
    return (out, res[4]), res


def _flash3_lse_bwd(scale, causal, block_q, block_k, use_pallas, res,
                    g):
    g_o, g_lse = g
    return _bwd_chunked(res, g_o, g_lse, scale=scale, causal=causal,
                        block_q=block_q)


_flash3_lse.defvjp(_flash3_lse_fwd, _flash3_lse_bwd)


# Largest block_q*block_k score tile the kernel may hold in VMEM (f32;
# 512x512 = 1 MB — comfortable under the ~16 MB budget with q/k/v tiles
# and scratch). Only the degenerate-divisor path can exceed it.
_MAX_BLOCK_ELEMS = 512 * 512


def on_tpu() -> bool:
    """Same detection as the quantization kernel (quant_kernel._on_tpu):
    the axon-relay backend reports 'axon', not 'tpu' — a platform-name
    check would silently route every flash call to the dense fallback on
    the real chip."""
    from fedtorch_tpu.ops.pallas.quant_kernel import _on_tpu
    return _on_tpu()


def _divisor_block(T: int, block: int) -> int:
    """Largest usable block size that DIVIDES T (<= the request).

    Every code path — kernel grid, backward scan — assumes
    ``T % block == 0``; deriving the block here makes that a structural
    invariant instead of a fallback condition. Degenerate divisors
    (< 16 rows) would make the scan/grid long and thin, so those round
    up to T (one block — still exact, standard memory)."""
    if T <= block:
        return T
    if T % block == 0:
        return block
    d = math.gcd(T, block)
    return d if d >= 16 else T


def _default_blocks(T: int):
    """Data-driven default block shape, settled per ADVICE r5 + ROADMAP
    item 3:

    * T >= 4096 — (512, 512): well supported by the forward sweep
      (FLASH_BLOCK_SWEEP.json, v5e, fetch-synced: 1.48x vs dense
      forward at T=8192).
    * T <= 2048 — (128, 128), the previously-validated shape. The
      (256, 512) pick came from a SINGLE forward-only sweep point at
      T=2048 (1.08x — inside the documented +/-30% relay noise), and
      the re-run TRAINING A/B at those defaults regressed to 0.68x at
      T=2048 vs 1.04x at the original 128x128 (FLASH_TRAIN.json). Per
      the repo's measured-not-predicted rule the training measurement
      wins; more fetch-synced sub-2048 samples can revisit this.

    Both fit VMEM comfortably (<=1 MB score tile; _MAX_BLOCK_ELEMS).
    Note 'auto' attention dispatch routes T < 4096 to dense anyway
    (ops/attention_dispatch.py), so the sub-2048 default only governs
    explicit ``attention='flash'`` requests."""
    return (128, 128) if T <= 2048 else (512, 512)


def _prep(q, k, v, scale, block_q, block_k, force):
    """Shared wrapper plumbing: [B,T,H,D] -> [BH,T,D] layout, divisor
    block sizes, backend selection."""
    B, T, H, D = q.shape
    if block_q is None or block_k is None:
        dq, dk = _default_blocks(T)
        block_q = dq if block_q is None else block_q
        block_k = dk if block_k is None else block_k
    if k.shape != q.shape or v.shape != q.shape:
        # The kernel grid and chunked VJP tile Q and K/V with one shared
        # T; unequal q/kv lengths (e.g. cross-attention or uneven K/V
        # partitions) are not supported — fail with the shapes rather
        # than an opaque reshape error downstream. Ring/Ulysses always
        # pass equal-size blocks.
        raise ValueError(
            "flash attention requires q, k, v of identical shape "
            f"[B, T, H, D]; got q={q.shape}, k={k.shape}, v={v.shape}. "
            "For disjoint K/V partitions, run the kernel per equal-size "
            "block and merge with the returned logsumexp.")
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    block_q = _divisor_block(T, block_q)
    block_k = _divisor_block(T, block_k)
    q3, k3, v3 = (t.transpose(0, 2, 1, 3).reshape(B * H, T, D)
                  for t in (q, k, v))
    if force not in (None, "interpret", "xla"):
        raise ValueError(
            f"unknown force={force!r} (expected None, 'interpret', or "
            "'xla')")
    if force == "interpret":
        use_pallas = None           # pallas_call(interpret=True)
    elif force == "xla" or not on_tpu():
        use_pallas = False
    else:
        use_pallas = True
    if use_pallas and block_q * block_k > _MAX_BLOCK_ELEMS:
        # degenerate divisor (prime-ish T) collapsed to near-T blocks:
        # a [block_q, block_k] f32 score tile would blow VMEM on the
        # real lowering — the XLA oracle is the correct backend there
        use_pallas = False
    return (q3, k3, v3), (B, T, H, D), scale, block_q, block_k, \
        use_pallas


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    force: Optional[str] = None) -> jnp.ndarray:
    """Exact attention, [B, T, H, D] in/out, differentiable.

    Backend selection: the Pallas kernel on TPU; its interpreter when
    ``force='interpret'`` (CPU kernel tests); the dense-oracle math
    otherwise (CPU training/eval — same semantics, standard memory).
    Block sizes default to the measured per-T winners
    (``_default_blocks``) and are adjusted to divisors of T (static
    shapes: decided once at trace time), so both the kernel grid and
    the chunked VJP always tile the sequence exactly."""
    (q3, k3, v3), (B, T, H, D), scale, bq, bk, use_pallas = _prep(
        q, k, v, scale, block_q, block_k, force)
    out3 = _flash3(q3, k3, v3, scale, causal, bq, bk, use_pallas)
    return out3.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             force: Optional[str] = None):
    """:func:`flash_attention` that also returns the logsumexp
    ([B, T, H] f32) — the merge statistic for combining attention over
    disjoint K/V blocks: pieces (o_i, lse_i) over K-partitions combine
    exactly via lse-weighted averaging (ring attention's per-step
    blocks, parallel/sequence.py). Differentiable in both outputs."""
    (q3, k3, v3), (B, T, H, D), scale, bq, bk, use_pallas = _prep(
        q, k, v, scale, block_q, block_k, force)
    o3, lse3 = _flash3_lse(q3, k3, v3, scale, causal, bq, bk,
                           use_pallas)
    o = o3.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return o, lse3.reshape(B, H, T).transpose(0, 2, 1)
