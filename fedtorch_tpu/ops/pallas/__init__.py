from fedtorch_tpu.ops.pallas.quant_kernel import (  # noqa: F401
    fused_quantize_dequantize, fused_quantize_dequantize_batch,
    fused_quantize_dequantize_tree,
)
