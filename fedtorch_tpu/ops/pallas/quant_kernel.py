"""Pallas TPU kernel: fused adaptive affine quantize->dequantize.

The XLA path (ops/quantize.py) lowers the compression transform as
separate min/max/mean reductions plus the elementwise round-trip — several
HBM passes over each payload tensor. This kernel fuses the whole transform
into ONE VMEM-resident pass: statistics and the round-trip happen while
the block is on-chip, which matters because the aggregation path is
HBM-bandwidth bound (one payload tensor per model parameter per round).

Semantics are identical to ops.quantize.quantize_dequantize (the
reference's flow_utils.py:169-212 affine scheme). Falls back to the XLA
implementation off-TPU, for tensors too large for VMEM, and when the
input is a vmap batch tracer (pallas_call has no batching rule) — so it
is always safe to call.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fedtorch_tpu.ops.quantize import quantize_dequantize as _xla_qdq

_LANE = 128
# per-tensor VMEM budget for the single-block kernel (bytes of f32)
_MAX_VMEM_ELEMS = 2 * 1024 * 1024  # 8 MB of f32


def _qdq_kernel(n_ref, x_ref, out_ref, *, num_bits: int):
    import jax.numpy as jnp  # kernel-local alias

    qmin = -(2.0 ** (num_bits - 1))
    qmax = 2.0 ** (num_bits - 1) - 1.0
    x = x_ref[:]
    n = n_ref[0]
    rows, cols = x.shape
    flat_idx = (jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
                + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1))
    valid = flat_idx < n

    big = jnp.asarray(jnp.finfo(jnp.float32).max)
    mn = jnp.min(jnp.where(valid, x, big))
    mx = jnp.max(jnp.where(valid, x, -big))
    mean = jnp.sum(jnp.where(valid, x, 0.0)) / n.astype(jnp.float32)

    scale = (mx - mn) / (qmax - qmin)
    scale = jnp.where(scale == 0.0, 0.001, scale)
    zp = jnp.trunc(jnp.clip(qmin - (mn - mean) / scale, qmin, qmax))
    q = jnp.clip(jnp.round(zp + (x - mean) / scale), qmin, qmax)
    out_ref[:] = scale * (q - zp) + mean


@functools.partial(jax.jit, static_argnames=("num_bits",))
def _pallas_qdq_padded(x2d: jnp.ndarray, n: jnp.ndarray,
                       num_bits: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        functools.partial(_qdq_kernel, num_bits=num_bits),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(n, x2d)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _is_batch_traced(x) -> bool:
    from jax.interpreters import batching
    return isinstance(x, batching.BatchTracer)


def fused_quantize_dequantize(x: jnp.ndarray, num_bits: int = 8,
                              force_pallas: bool = False) -> jnp.ndarray:
    """Drop-in replacement for ops.quantize.quantize_dequantize."""
    n = x.size
    use_pallas = (force_pallas
                  or (_on_tpu() and n <= _MAX_VMEM_ELEMS)) \
        and not _is_batch_traced(x)
    if not use_pallas:
        return _xla_qdq(x, num_bits)
    rows = -(-n // _LANE)
    # pad rows to the f32 sublane multiple (8)
    rows = -(-rows // 8) * 8
    padded = jnp.zeros((rows * _LANE,), jnp.float32)
    padded = padded.at[:n].set(x.reshape(-1).astype(jnp.float32))
    out = _pallas_qdq_padded(padded.reshape(rows, _LANE),
                             jnp.asarray([n], jnp.int32), num_bits)
    return out.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)
