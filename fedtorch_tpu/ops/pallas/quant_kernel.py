"""Pallas TPU kernel: fused adaptive affine quantize->dequantize.

The XLA path (ops/quantize.py) lowers the compression transform as
separate min/max/mean reductions plus the elementwise round-trip — several
HBM passes over each payload tensor. This kernel fuses the whole transform
into ONE VMEM-resident pass: statistics and the round-trip happen while
the block is on-chip, which matters because the aggregation path is
HBM-bandwidth bound (one payload tensor per model parameter per round).

Semantics are identical to ops.quantize.quantize_dequantize (the
reference's flow_utils.py:169-212 affine scheme). Falls back to the XLA
implementation off-TPU, for tensors too large for VMEM, and when the
input is a vmap batch tracer (pallas_call has no batching rule) — so it
is always safe to call.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fedtorch_tpu.ops.quantize import quantize_dequantize as _xla_qdq

_LANE = 128
# Per-tensor ceiling for the SINGLE-BLOCK kernel. The scoped-VMEM limit on
# real TPUs is 16 MB and the kernel's working set (input + output + mask /
# where temps) is ~5x the input, so the empirical ceiling on v5e is
# ~786k f32 elements (1M OOMs the compiler). 512k leaves headroom for the
# int16 path's wider temps. Larger tensors take the grid-tiled two-pass
# kernel below.
_MAX_VMEM_ELEMS = 512 * 1024
# Row-block height for the tiled kernel: (512, 128) f32 blocks = 256 KB.
_TILE_ROWS = 512
# Ceiling for the tiled path: beyond this just use XLA (tensors this large
# only appear in imagenet/transformer configs where the payload is sharded
# anyway, and the stats/apply sweeps stop paying for the extra launch).
_MAX_TILED_ELEMS = 64 * 1024 * 1024


def _affine_roundtrip(x, mn, mx, mean, num_bits: int):
    """The affine quantize->dequantize given precomputed stats — the ONE
    place the scheme (zero-scale epsilon, zp trunc/clip, round/clip)
    lives; shared by the single-block, batch, and tiled kernels so the
    paths cannot desynchronize."""
    qmin = -(2.0 ** (num_bits - 1))
    qmax = 2.0 ** (num_bits - 1) - 1.0
    scale = (mx - mn) / (qmax - qmin)
    scale = jnp.where(scale == 0.0, 0.001, scale)
    zp = jnp.trunc(jnp.clip(qmin - (mn - mean) / scale, qmin, qmax))
    q = jnp.clip(jnp.round(zp + (x - mean) / scale), qmin, qmax)
    return scale * (q - zp) + mean


def _qdq_math(x, n, num_bits: int):
    """The fused statistics + affine round-trip on one [rows, cols]
    VMEM-resident block with ``n`` valid leading elements."""
    rows, cols = x.shape
    flat_idx = (jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
                + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1))
    valid = flat_idx < n

    big = jnp.asarray(jnp.finfo(jnp.float32).max)
    mn = jnp.min(jnp.where(valid, x, big))
    mx = jnp.max(jnp.where(valid, x, -big))
    mean = jnp.sum(jnp.where(valid, x, 0.0)) / n.astype(jnp.float32)
    return _affine_roundtrip(x, mn, mx, mean, num_bits)


def _qdq_kernel(n_ref, x_ref, out_ref, *, num_bits: int):
    out_ref[:] = _qdq_math(x_ref[:], n_ref[0], num_bits)


def _qdq_batch_kernel(n_ref, x_ref, out_ref, *, num_bits: int):
    """Grid-over-clients cell: one client's [1, rows, cols] block per
    program instance — statistics are PER CLIENT, exactly the vmapped
    per-client semantics of the uplink (fedavg.py:34-38)."""
    out_ref[0] = _qdq_math(x_ref[0], n_ref[0], num_bits)


def _tiled_stats_kernel(n_ref, x_ref, stats_ref):
    """Grid sweep 1: running [min, max, sum] over row-blocks.

    TPU grid steps run sequentially on the core, and ``stats_ref`` has a
    constant index map, so it stays resident and acts as an accumulator."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        stats_ref[0] = jnp.finfo(jnp.float32).max
        stats_ref[1] = -jnp.finfo(jnp.float32).max
        stats_ref[2] = 0.0

    x = x_ref[:]
    rows, cols = x.shape
    base = i * rows * cols
    flat_idx = base + (
        jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
        + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1))
    valid = flat_idx < n_ref[0]
    big = jnp.asarray(jnp.finfo(jnp.float32).max)
    stats_ref[0] = jnp.minimum(stats_ref[0],
                               jnp.min(jnp.where(valid, x, big)))
    stats_ref[1] = jnp.maximum(stats_ref[1],
                               jnp.max(jnp.where(valid, x, -big)))
    stats_ref[2] = stats_ref[2] + jnp.sum(jnp.where(valid, x, 0.0))


def _tiled_apply_kernel(stats_ref, n_ref, x_ref, out_ref, *, num_bits: int):
    """Grid sweep 2: the affine round-trip with the global stats in SMEM."""
    mean = stats_ref[2] / n_ref[0].astype(jnp.float32)
    out_ref[:] = _affine_roundtrip(x_ref[:], stats_ref[0], stats_ref[1],
                                   mean, num_bits)


@functools.partial(jax.jit, static_argnames=("num_bits", "interpret"))
def _pallas_qdq_tiled(x2d: jnp.ndarray, n: jnp.ndarray,
                      num_bits: int,
                      interpret: bool = False) -> jnp.ndarray:
    """Two grid sweeps over (TILE_ROWS, LANE) blocks: stats, then apply.

    HBM traffic is 2 reads + 1 write of the payload — the same order as
    XLA's fused reduce+elementwise lowering, but with the stats guaranteed
    single-pass; exists so payloads past the single-block VMEM ceiling
    keep identical fused semantics instead of silently changing path."""
    rows = x2d.shape[0]
    nb = rows // _TILE_ROWS
    stats = pl.pallas_call(
        _tiled_stats_kernel,
        grid=(nb,),
        out_shape=jax.ShapeDtypeStruct((3,), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_TILE_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=interpret,
    )(n, x2d)
    return pl.pallas_call(
        functools.partial(_tiled_apply_kernel, num_bits=num_bits),
        grid=(nb,),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_TILE_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_ROWS, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(stats, n, x2d)


@functools.partial(jax.jit, static_argnames=("num_bits", "interpret"))
def _pallas_qdq_padded(x2d: jnp.ndarray, n: jnp.ndarray,
                       num_bits: int,
                       interpret: bool = False) -> jnp.ndarray:
    return pl.pallas_call(
        functools.partial(_qdq_kernel, num_bits=num_bits),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(n, x2d)


@functools.partial(jax.jit, static_argnames=("num_bits", "interpret"))
def _pallas_qdq_batch_padded(x3d: jnp.ndarray, n: jnp.ndarray,
                             num_bits: int,
                             interpret: bool = False) -> jnp.ndarray:
    C, rows, lane = x3d.shape
    return pl.pallas_call(
        functools.partial(_qdq_batch_kernel, num_bits=num_bits),
        grid=(C,),
        out_shape=jax.ShapeDtypeStruct(x3d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, rows, lane), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, lane), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(n, x3d)


def fused_quantize_dequantize_batch(x: jnp.ndarray, num_bits: int = 8,
                                    force_pallas: bool = False,
                                    interpret: bool = False,
                                    sharded: bool = False) -> jnp.ndarray:
    """Per-slice quantize->dequantize over the LEADING axis: slice i gets
    its own statistics, identical to ``vmap(quantize_dequantize)``.

    This is the uplink kernel: the engine stacks the online clients'
    payloads as [k, ...] after the vmapped local loop, and the grid runs
    one program instance per client — covering the path the single-block
    kernel cannot (``pallas_call`` has no batching rule, so calling it
    under vmap falls back to XLA).

    ``sharded=True`` declares the leading axis sharded over multiple
    devices: the pallas custom call has no GSPMD partitioning rule, so
    the XLA path (which partitions cleanly) is used instead."""
    C = x.shape[0]
    n = 1
    for d in x.shape[1:]:
        n *= int(d)
    use_pallas = (force_pallas
                  or (_on_tpu() and n <= _MAX_VMEM_ELEMS
                      and not sharded)) \
        and not _is_batch_traced(x) and n > 0
    if not use_pallas:
        return jax.vmap(lambda v: _xla_qdq(v, num_bits))(x)
    rows = -(-n // _LANE)
    rows = -(-rows // 8) * 8
    padded = jnp.zeros((C, rows * _LANE), jnp.float32)
    padded = padded.at[:, :n].set(
        x.reshape(C, -1).astype(jnp.float32))
    out = _pallas_qdq_batch_padded(padded.reshape(C, rows, _LANE),
                                   jnp.asarray([n], jnp.int32), num_bits,
                                   interpret)
    return out.reshape(C, -1)[:, :n].reshape(x.shape).astype(x.dtype)


def fused_quantize_dequantize_tree(tree, num_bits: int = 8,
                                   leading_batch: bool = False,
                                   sharded: bool = False,
                                   force_pallas: bool = False,
                                   interpret: bool = False):
    """Per-tensor quantize->dequantize over a whole pytree, bucketed by
    flattened size: leaves of equal size are stacked and served by ONE
    client-grid kernel launch (per-slice stats keep exact per-tensor
    semantics).

    A resnet20 payload is ~117 leaves of only ~8 distinct sizes; the
    per-leaf path costs one kernel launch per leaf while bucketing costs
    one per distinct size. Measured on the relay-attached v5e the
    end-to-end difference vs per-leaf XLA is within run-to-run noise
    (+/-30%; the round-5 fetch-synced samples read 0.83-0.90x,
    PALLAS_TPU.json) — the transform is kept because it is at-worst
    noise-equivalent, structurally bounds launch count, and keeps
    per-tensor stats exact at every payload size; the clear pallas
    wins are the large flat payloads (uplink 1.2x, 1M+ single tensors
    1.2-1.8x fetch-synced).

    ``leading_batch=True`` marks uplink layout: each leaf carries a
    leading [k_online] axis and the bucket stacks to [b*k, n] so stats
    stay per (tensor, client). ``sharded=True`` (client axis split over
    devices) keeps the per-leaf XLA path — the pallas call has no GSPMD
    rule, and cross-device restacking would materialize transfers."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    if (sharded or not (_on_tpu() or force_pallas)
            or any(_is_batch_traced(x) for x in leaves)):
        if leading_batch:
            out = [fused_quantize_dequantize_batch(x, num_bits,
                                                   sharded=sharded)
                   for x in leaves]
        else:
            out = [fused_quantize_dequantize(x, num_bits) for x in leaves]
        return jax.tree.unflatten(treedef, out)

    buckets = {}
    for i, x in enumerate(leaves):
        if leading_batch:
            # key on (leading dim, per-slice size): equal-sized leaves
            # with different batch dims must not share a reshape
            buckets.setdefault((x.shape[0], x.size // x.shape[0]),
                               []).append(i)
        else:
            buckets.setdefault((1, x.size), []).append(i)
    out = [None] * len(leaves)
    for (k, n), idxs in buckets.items():
        if n > _MAX_VMEM_ELEMS:
            # past the batch kernel's per-slice VMEM ceiling: the grid
            # kernel can't hold a slice, so serve each slice with the
            # per-leaf fused path (single-block or TILED kernel) instead
            # of letting the batch call silently fall back to XLA
            for i in idxs:
                leaf = leaves[i]
                if leading_batch:
                    qs = jnp.stack([
                        fused_quantize_dequantize(leaf[c], num_bits,
                                                  force_pallas, interpret)
                        for c in range(k)])
                    out[i] = qs.reshape(leaf.shape).astype(leaf.dtype)
                else:
                    out[i] = fused_quantize_dequantize(leaf, num_bits,
                                                       force_pallas,
                                                       interpret)
            continue
        if leading_batch:
            stacked = jnp.stack(
                [leaves[i].reshape(k, n) for i in idxs]).reshape(-1, n)
        else:
            stacked = jnp.stack([leaves[i].reshape(n) for i in idxs])
        q = fused_quantize_dequantize_batch(stacked, num_bits,
                                            force_pallas=force_pallas,
                                            interpret=interpret)
        if leading_batch:
            q = q.reshape(len(idxs), k, n)
        for j, i in enumerate(idxs):
            out[i] = q[j].reshape(leaves[i].shape).astype(leaves[i].dtype)
    return jax.tree.unflatten(treedef, out)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _is_batch_traced(x) -> bool:
    try:
        from jax._src.interpreters.batching import BatchTracer
        return isinstance(x, BatchTracer)
    except ImportError:  # future jax relayout: fall back on the name
        import jax.core
        return isinstance(x, jax.core.Tracer) \
            and type(x).__name__ == "BatchTracer"


def fused_quantize_dequantize(x: jnp.ndarray, num_bits: int = 8,
                              force_pallas: bool = False,
                              interpret: bool = False) -> jnp.ndarray:
    """Drop-in replacement for ops.quantize.quantize_dequantize."""
    n = x.size
    use_pallas = (force_pallas
                  or (_on_tpu() and n <= _MAX_TILED_ELEMS)) \
        and not _is_batch_traced(x)
    if not use_pallas:
        return _xla_qdq(x, num_bits)
    if n <= _MAX_VMEM_ELEMS:
        rows = -(-n // _LANE)
        # pad rows to the f32 sublane multiple (8)
        rows = -(-rows // 8) * 8
        padded = jnp.zeros((rows * _LANE,), jnp.float32)
        padded = padded.at[:n].set(x.reshape(-1).astype(jnp.float32))
        out = _pallas_qdq_padded(padded.reshape(rows, _LANE),
                                 jnp.asarray([n], jnp.int32), num_bits,
                                 interpret)
    else:
        rows = -(-n // _LANE)
        rows = -(-rows // _TILE_ROWS) * _TILE_ROWS
        padded = jnp.zeros((rows * _LANE,), jnp.float32)
        padded = padded.at[:n].set(x.reshape(-1).astype(jnp.float32))
        out = _pallas_qdq_tiled(padded.reshape(rows, _LANE),
                                jnp.asarray([n], jnp.int32), num_bits,
                                interpret)
    return out.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)
