"""Pallas TPU kernel: fused adaptive affine quantize->dequantize.

The XLA path (ops/quantize.py) lowers the compression transform as
separate min/max/mean reductions plus the elementwise round-trip — several
HBM passes over each payload tensor. This kernel fuses the whole transform
into ONE VMEM-resident pass: statistics and the round-trip happen while
the block is on-chip, which matters because the aggregation path is
HBM-bandwidth bound (one payload tensor per model parameter per round).

Semantics are identical to ops.quantize.quantize_dequantize (the
reference's flow_utils.py:169-212 affine scheme). Falls back to the XLA
implementation off-TPU, for tensors too large for VMEM, and when the
input is a vmap batch tracer (pallas_call has no batching rule) — so it
is always safe to call.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fedtorch_tpu.ops.quantize import quantize_dequantize as _xla_qdq

_LANE = 128
# per-tensor VMEM budget for the single-block kernel (bytes of f32)
_MAX_VMEM_ELEMS = 2 * 1024 * 1024  # 8 MB of f32


def _qdq_math(x, n, num_bits: int):
    """The fused statistics + affine round-trip on one [rows, cols]
    VMEM-resident block with ``n`` valid leading elements."""
    qmin = -(2.0 ** (num_bits - 1))
    qmax = 2.0 ** (num_bits - 1) - 1.0
    rows, cols = x.shape
    flat_idx = (jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
                + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1))
    valid = flat_idx < n

    big = jnp.asarray(jnp.finfo(jnp.float32).max)
    mn = jnp.min(jnp.where(valid, x, big))
    mx = jnp.max(jnp.where(valid, x, -big))
    mean = jnp.sum(jnp.where(valid, x, 0.0)) / n.astype(jnp.float32)

    scale = (mx - mn) / (qmax - qmin)
    scale = jnp.where(scale == 0.0, 0.001, scale)
    zp = jnp.trunc(jnp.clip(qmin - (mn - mean) / scale, qmin, qmax))
    q = jnp.clip(jnp.round(zp + (x - mean) / scale), qmin, qmax)
    return scale * (q - zp) + mean


def _qdq_kernel(n_ref, x_ref, out_ref, *, num_bits: int):
    out_ref[:] = _qdq_math(x_ref[:], n_ref[0], num_bits)


def _qdq_batch_kernel(n_ref, x_ref, out_ref, *, num_bits: int):
    """Grid-over-clients cell: one client's [1, rows, cols] block per
    program instance — statistics are PER CLIENT, exactly the vmapped
    per-client semantics of the uplink (fedavg.py:34-38)."""
    out_ref[0] = _qdq_math(x_ref[0], n_ref[0], num_bits)


@functools.partial(jax.jit, static_argnames=("num_bits",))
def _pallas_qdq_padded(x2d: jnp.ndarray, n: jnp.ndarray,
                       num_bits: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        functools.partial(_qdq_kernel, num_bits=num_bits),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(n, x2d)


@functools.partial(jax.jit, static_argnames=("num_bits", "interpret"))
def _pallas_qdq_batch_padded(x3d: jnp.ndarray, n: jnp.ndarray,
                             num_bits: int,
                             interpret: bool = False) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, rows, lane = x3d.shape
    return pl.pallas_call(
        functools.partial(_qdq_batch_kernel, num_bits=num_bits),
        grid=(C,),
        out_shape=jax.ShapeDtypeStruct(x3d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, rows, lane), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, lane), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(n, x3d)


def fused_quantize_dequantize_batch(x: jnp.ndarray, num_bits: int = 8,
                                    force_pallas: bool = False,
                                    interpret: bool = False,
                                    sharded: bool = False) -> jnp.ndarray:
    """Per-slice quantize->dequantize over the LEADING axis: slice i gets
    its own statistics, identical to ``vmap(quantize_dequantize)``.

    This is the uplink kernel: the engine stacks the online clients'
    payloads as [k, ...] after the vmapped local loop, and the grid runs
    one program instance per client — covering the path the single-block
    kernel cannot (``pallas_call`` has no batching rule, so calling it
    under vmap falls back to XLA).

    ``sharded=True`` declares the leading axis sharded over multiple
    devices: the pallas custom call has no GSPMD partitioning rule, so
    the XLA path (which partitions cleanly) is used instead."""
    C = x.shape[0]
    n = 1
    for d in x.shape[1:]:
        n *= int(d)
    use_pallas = (force_pallas
                  or (_on_tpu() and n <= _MAX_VMEM_ELEMS
                      and not sharded)) \
        and not _is_batch_traced(x) and n > 0
    if not use_pallas:
        return jax.vmap(lambda v: _xla_qdq(v, num_bits))(x)
    rows = -(-n // _LANE)
    rows = -(-rows // 8) * 8
    padded = jnp.zeros((C, rows * _LANE), jnp.float32)
    padded = padded.at[:, :n].set(
        x.reshape(C, -1).astype(jnp.float32))
    out = _pallas_qdq_batch_padded(padded.reshape(C, rows, _LANE),
                                   jnp.asarray([n], jnp.int32), num_bits,
                                   interpret)
    return out.reshape(C, -1)[:, :n].reshape(x.shape).astype(x.dtype)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _is_batch_traced(x) -> bool:
    try:
        from jax._src.interpreters.batching import BatchTracer
        return isinstance(x, BatchTracer)
    except ImportError:  # future jax relayout: fall back on the name
        import jax.core
        return isinstance(x, jax.core.Tracer) \
            and type(x).__name__ == "BatchTracer"


def fused_quantize_dequantize(x: jnp.ndarray, num_bits: int = 8,
                              force_pallas: bool = False) -> jnp.ndarray:
    """Drop-in replacement for ops.quantize.quantize_dequantize."""
    n = x.size
    use_pallas = (force_pallas
                  or (_on_tpu() and n <= _MAX_VMEM_ELEMS)) \
        and not _is_batch_traced(x)
    if not use_pallas:
        return _xla_qdq(x, num_bits)
    rows = -(-n // _LANE)
    # pad rows to the f32 sublane multiple (8)
    rows = -(-rows // 8) * 8
    padded = jnp.zeros((rows * _LANE,), jnp.float32)
    padded = padded.at[:n].set(x.reshape(-1).astype(jnp.float32))
    out = _pallas_qdq_padded(padded.reshape(rows, _LANE),
                             jnp.asarray([n], jnp.int32), num_bits)
    return out.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)
