"""Fixed-k top-k sparsification, in-graph.

Rebuild of ``compress_tensor``/``decompress_tensor`` (``/root/reference/
fedtorch/comms/utils/flow_utils.py:218-237``) with a TPU-critical change:
``k`` is fixed at **trace time** from the compression ratio, because XLA
requires static shapes (SURVEY.md §7 'hard parts'). The reference's
``k = int(len(x)*r/2)`` rule is kept verbatim — the /2 accounts for
sending (value, index) pairs, i.e. ratio ``r`` measures *bytes*, not
elements.

Error-feedback memory (`memory += delta - decompressed`, qsparse.py:57,
fedgate.py:74-79) is implemented by the callers in
``fedtorch_tpu.algorithms``.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class Sparse(NamedTuple):
    """Static-shape sparse payload: k values + int32 flat indices."""
    values: jnp.ndarray   # [k]
    indices: jnp.ndarray  # [k] int32
    shape: tuple          # static original shape (aux data, not traced)


def num_kept(n: int, ratio: float) -> int:
    """k = n*r/2 (flow_utils.py:221); at least 1 so shapes stay valid."""
    k = int(n * ratio / 2)
    if k == 0:
        raise ValueError(
            "Compression ratio is too low!")  # matches reference behavior
    return k


def compress(x: jnp.ndarray, ratio: float = 0.5, comp_type: str = "topk",
             rng: jax.Array | None = None) -> Sparse:
    """Top-k (by |x|) or random-k selection of a flattened tensor."""
    shape = tuple(x.shape)
    x_f = x.reshape(-1)
    k = num_kept(x_f.shape[0], ratio)
    if comp_type == "topk":
        _, idx = jax.lax.top_k(jnp.abs(x_f), k)
    elif comp_type == "random":
        if rng is None:
            raise ValueError("random compression requires an rng key")
        idx = jax.random.permutation(rng, x_f.shape[0])[:k]
    else:
        raise NotImplementedError(comp_type)
    return Sparse(values=x_f[idx], indices=idx.astype(jnp.int32), shape=shape)


def decompress(sp: Sparse) -> jnp.ndarray:
    """Scatter values back into a dense zero tensor (flow_utils.py:232-237)."""
    n = 1
    for d in sp.shape:
        n *= d
    dense = jnp.zeros((n,), sp.values.dtype)
    dense = dense.at[sp.indices].set(sp.values)
    return dense.reshape(sp.shape)


def topk_roundtrip(x: jnp.ndarray, ratio: float = 0.5) -> jnp.ndarray:
    """compress->decompress in one go: the dense tensor the receiver sees.

    This is the form used inside jitted aggregation (the 'wire' is an ICI
    collective, so we keep the dense layout and rely on the mask being
    mostly zeros only for *semantic* parity; when an actual 4x payload
    reduction is wanted, use `compress` and all_gather the Sparse parts).
    """
    sp = compress(x, ratio=ratio, comp_type="topk")
    return decompress(sp)


def compress_pytree(tree, ratio: float = 0.5):
    """Per-leaf top-k round-trip; returns (dense reconstruction, residual).

    residual = x - reconstruction is the error-feedback increment."""
    recon = jax.tree.map(lambda x: topk_roundtrip(x, ratio), tree)
    residual = jax.tree.map(lambda x, r: x - r, tree, recon)
    return recon, residual
