"""Euclidean projection onto the probability simplex, jittable.

Rebuild of ``euclidean_proj_simplex`` / ``projection_simplex_sort``
(``/root/reference/fedtorch/comms/utils/flow_utils.py:52-157``), used by
the AFL and DRFA dual-variable updates. The reference runs these on CPU
between rounds; here the projection is an O(n log n) sort expressed in
``jnp`` so the whole dual update stays inside the jitted round program.
"""
from __future__ import annotations

import jax.numpy as jnp


def project_simplex(v: jnp.ndarray, s: float = 1.0) -> jnp.ndarray:
    """min_w ||w - v||^2 s.t. sum(w) = s, w >= 0 (Duchi et al., ICML'08).

    Matches flow_utils.py:52-97 including the degenerate rho=0 fallback
    when no component satisfies the support condition."""
    v = jnp.asarray(v, jnp.float32)
    n = v.shape[0]
    u = jnp.sort(v)[::-1]                       # decreasing
    cssv = jnp.cumsum(u)
    ind = jnp.arange(1, n + 1, dtype=v.dtype)
    cond = u * ind > (cssv - s)
    # rho = last index satisfying cond; 0 if none (reference :88-91).
    rho = jnp.max(jnp.where(cond, jnp.arange(n), 0))
    theta = (cssv[rho] - s) / (rho + 1.0)
    return jnp.clip(v - theta, 0.0, None)


def project_simplex_floor(v: jnp.ndarray, s: float = 1.0,
                          floor: float = 1e-3) -> jnp.ndarray:
    """Projection followed by the DRFA lambda floor
    (federated/drfa.py:246-250): entries <= floor are raised to the floor so
    every client keeps nonzero sampling probability, then the vector is
    renormalized once (the reference does not re-floor after normalizing)."""
    w = project_simplex(v, s)
    w = jnp.where(w <= floor, floor, w)
    return w / jnp.sum(w) * s
