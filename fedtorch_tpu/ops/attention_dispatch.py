"""Attention backend dispatch policy — pallas-free on purpose.

The policy is a pure string/int decision, but it used to live next to
the flash kernel, whose module imports ``jax.experimental.pallas``
at top level — so the DENSE path (which never runs the kernel) would
still crash at import time on jax builds without pallas/Mosaic.
Keeping the dispatch here lets ``models/transformer.py`` resolve the
backend without touching the kernel stack; the kernel module
re-exports these names for callers that already import them from
there.
"""
from __future__ import annotations

# Shortest sequence length at which 'auto' attention dispatch picks the
# flash kernel. From the on-chip training A/B at the tuned block
# defaults (FLASH_TRAIN.json, TPU v5e, ±30% relay run-to-run variance):
# T=1024 1.12x, T=2048 0.68x (a REGRESSION — the dense path's [T, T]
# scores still fit comfortably and the kernel's launch/tiling overhead
# dominates), T=4096 1.77x (outside the noise band), T=8192 1.05x with
# the dense score tensor already at 2.1 GB/layer. Flash is therefore
# the default only where it measurably wins or where dense memory
# becomes the binding constraint — T >= 4096.
FLASH_MIN_SEQ_LEN = 4096


def resolve_attention(mode: str, seq_len: int) -> str:
    """Resolve an attention mode ('auto'|'dense'|'flash') for a static
    sequence length. 'auto' guards users from the measured T=2048
    regression window (constant above); explicit modes pass through so
    A/Bs can pin either backend at any T."""
    if mode == "auto":
        return "flash" if seq_len >= FLASH_MIN_SEQ_LEN else "dense"
    if mode not in ("dense", "flash"):
        raise ValueError(
            f"attention must be 'auto', 'dense' or 'flash', got {mode!r}")
    return mode
