from fedtorch_tpu.ops.quantize import (  # noqa: F401
    QuantInfo, dequantize, dequantize_pytree, quantize, quantize_dequantize,
    quantize_pytree,
)
from fedtorch_tpu.ops.simplex import (  # noqa: F401
    project_simplex, project_simplex_floor,
)
from fedtorch_tpu.ops.topk import (  # noqa: F401
    Sparse, compress, compress_pytree, decompress, topk_roundtrip,
)
