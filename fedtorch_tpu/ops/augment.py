"""In-graph image augmentation.

The reference's CIFAR training pipeline applies RandomHorizontalFlip +
RandomCrop(32, padding=4) on the host per batch (prepare_data.py:29-35);
here the same augmentation is a jittable per-sample transform applied
inside the training scan — no host round-trips, fresh randomness per
local step from the threaded PRNG.

One deliberate difference: the reference crops in raw pixel space before
normalization (zero-padding = black border), while this operates on
normalized tensors (zero-padding = per-channel mean border). The crop
statistics are otherwise identical.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def augment_image_batch(rng: jax.Array, x: jnp.ndarray,
                        pad: int = 4) -> jnp.ndarray:
    """Random horizontal flip + pad-and-crop, per sample. x: [B,H,W,C]."""
    b, h, w, c = x.shape
    r_flip, r_top, r_left = jax.random.split(rng, 3)
    flip = jax.random.bernoulli(r_flip, 0.5, (b,))
    x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    tops = jax.random.randint(r_top, (b,), 0, 2 * pad + 1)
    lefts = jax.random.randint(r_left, (b,), 0, 2 * pad + 1)

    def crop(img, top, left):
        return jax.lax.dynamic_slice(img, (top, left, 0), (h, w, c))

    return jax.vmap(crop)(xp, tops, lefts)
