"""In-graph image augmentation.

The reference's CIFAR training pipeline applies RandomHorizontalFlip +
RandomCrop(32, padding=4) on the host per batch (prepare_data.py:29-35);
here the same augmentation is a jittable per-sample transform applied
inside the training scan — no host round-trips, fresh randomness per
local step from the threaded PRNG.

One deliberate difference: the reference crops in raw pixel space before
normalization (zero-padding = black border), while this operates on
normalized tensors (zero-padding = per-channel mean border). The crop
statistics are otherwise identical.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def augment_image_batch(rng: jax.Array, x: jnp.ndarray,
                        pad: int = 4) -> jnp.ndarray:
    """Random horizontal flip + pad-and-crop, per sample. x: [B,H,W,C]."""
    b, h, w, c = x.shape
    r_flip, r_top, r_left = jax.random.split(rng, 3)
    flip = jax.random.bernoulli(r_flip, 0.5, (b,))
    x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    tops = jax.random.randint(r_top, (b,), 0, 2 * pad + 1)
    lefts = jax.random.randint(r_left, (b,), 0, 2 * pad + 1)

    def crop(img, top, left):
        return jax.lax.dynamic_slice(img, (top, left, 0), (h, w, c))

    return jax.vmap(crop)(xp, tops, lefts)


# -- color toolkit (preprocess_toolkit.py:124-214) -----------------------
# The reference's AlexNet-style PCA lighting and brightness/contrast/
# saturation jitter (used by its inception_color_preproccess preset,
# preprocess_toolkit.py:66-80; its main CIFAR/MNIST path uses only
# flip+crop above). All transforms are jittable, batched [B, H, W, 3],
# with per-sample randomness from the given key.

# ImageNet PCA statistics (preprocess_toolkit.py:10-17)
IMAGENET_PCA_EIGVAL = (0.2175, 0.0188, 0.0045)
IMAGENET_PCA_EIGVEC = ((-0.5675, 0.7192, 0.4009),
                       (-0.5808, -0.0045, -0.8140),
                       (-0.5836, -0.6948, 0.4203))


def pca_lighting(rng: jax.Array, x: jnp.ndarray,
                 alphastd: float = 0.1) -> jnp.ndarray:
    """AlexNet PCA lighting noise (Lighting, preprocess_toolkit.py:124-142):
    adds ``eigvec @ (alpha * eigval)`` per sample to every pixel, with
    ``alpha ~ N(0, alphastd)`` drawn per sample per channel."""
    if alphastd == 0:
        return x
    b = x.shape[0]
    eigval = jnp.asarray(IMAGENET_PCA_EIGVAL)
    eigvec = jnp.asarray(IMAGENET_PCA_EIGVEC)
    alpha = alphastd * jax.random.normal(rng, (b, 3))
    rgb = (eigvec[None] * (alpha * eigval)[:, None, :]).sum(-1)  # [B, 3]
    return x + rgb[:, None, None, :]


def _grayscale(x: jnp.ndarray) -> jnp.ndarray:
    """ITU-R 601-2 luma replicated over RGB (Grayscale,
    preprocess_toolkit.py:145-152)."""
    gs = (0.299 * x[..., 0] + 0.587 * x[..., 1] + 0.114 * x[..., 2])
    return jnp.repeat(gs[..., None], 3, axis=-1)


def _lerp(x, target, alpha):
    return x + alpha[:, None, None, None] * (target - x)


def saturation_jitter(rng, x, var: float):
    """lerp toward grayscale by alpha ~ U(0, var)
    (Saturation, preprocess_toolkit.py:155-163)."""
    alpha = jax.random.uniform(rng, (x.shape[0],), maxval=var)
    return _lerp(x, _grayscale(x), alpha)


def brightness_jitter(rng, x, var: float):
    """lerp toward black by alpha ~ U(0, var)
    (Brightness, preprocess_toolkit.py:166-174)."""
    alpha = jax.random.uniform(rng, (x.shape[0],), maxval=var)
    return _lerp(x, jnp.zeros_like(x), alpha)


def contrast_jitter(rng, x, var: float):
    """lerp toward the per-sample mean gray level by alpha ~ U(0, var)
    (Contrast, preprocess_toolkit.py:177-185)."""
    alpha = jax.random.uniform(rng, (x.shape[0],), maxval=var)
    gs_mean = _grayscale(x).mean(axis=(1, 2, 3), keepdims=True)
    return _lerp(x, jnp.broadcast_to(gs_mean, x.shape), alpha)


def color_jitter(rng: jax.Array, x: jnp.ndarray, brightness: float = 0.4,
                 contrast: float = 0.4, saturation: float = 0.4):
    """Brightness/contrast/saturation jitter applied in a RANDOM ORDER
    per batch (ColorJitter(RandomOrder), preprocess_toolkit.py:188-214),
    via a branch over the 6 permutations so it stays jittable."""
    import itertools
    r_order, r_b, r_c, r_s = jax.random.split(rng, 4)
    ops = [lambda v: brightness_jitter(r_b, v, brightness),
           lambda v: contrast_jitter(r_c, v, contrast),
           lambda v: saturation_jitter(r_s, v, saturation)]
    perms = list(itertools.permutations(range(3)))

    def make_branch(perm):
        def branch(v):
            for i in perm:
                v = ops[i](v)
            return v
        return branch

    which = jax.random.randint(r_order, (), 0, len(perms))
    return jax.lax.switch(which, [make_branch(p) for p in perms], x)


def inception_color_batch(rng: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
    """The reference's color-augmentation preset: ColorJitter(0.4,0.4,0.4)
    then PCA Lighting(0.1) (inception_color_preproccess,
    preprocess_toolkit.py:66-80), minus the resize/crop stages our data
    layout already fixes."""
    r_j, r_l = jax.random.split(rng)
    return pca_lighting(r_l, color_jitter(r_j, x), alphastd=0.1)
