"""Adaptive affine quantization, in-graph.

Rebuild of ``/root/reference/fedtorch/comms/utils/flow_utils.py:169-212``
(``quantize_tensor`` / ``dequantize_tensor``) as jittable functions: on TPU
the quantized payload is not a wire format but an in-graph transform applied
to model deltas before the aggregation collective (SURVEY.md §2.10), which
shrinks the ICI/DCN all-gather payload 4x (int8) while keeping shapes
static.

Semantics preserved from the reference:
* symmetric integer range ``[-2^(b-1), 2^(b-1)-1]``;
* adaptive mode computes ``scale=(max-min)/(qmax-qmin)`` with a 0.001
  floor when the tensor is constant, a zero point clipped into the integer
  range and truncated toward zero (``int(...)``), and centers on the mean;
* dequantize: ``scale*(q - zero_point) + mean``.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

# Non-adaptive defaults (flow_utils.py:8).
SCALE_QUANTIZE = 0.001
ZERO_POINT_QUANTIZE = 0.0


class QuantInfo(NamedTuple):
    """The [scale, zero_point, mean] triple the reference sends alongside
    the payload (flow_utils.py:205)."""
    scale: jnp.ndarray
    zero_point: jnp.ndarray
    mean: jnp.ndarray


def _int_dtype(num_bits: int):
    if num_bits == 8:
        return jnp.int8
    if num_bits == 16:
        return jnp.int16
    raise ValueError(f"Unsupported quantization bits: {num_bits}")


def quantize(x: jnp.ndarray, num_bits: int = 8, adaptive: bool = True,
             info: QuantInfo | None = None) -> Tuple[jnp.ndarray, QuantInfo]:
    """Affine-quantize ``x``; returns (int payload, QuantInfo)."""
    qmin = -(2.0 ** (num_bits - 1))
    qmax = 2.0 ** (num_bits - 1) - 1.0
    x = jnp.asarray(x)
    if adaptive:
        min_val, max_val, mean_val = x.min(), x.max(), x.mean()
        scale = (max_val - min_val) / (qmax - qmin)
        scale = jnp.where(scale == 0.0, 0.001, scale)
        init_zp = qmin - (min_val - mean_val) / scale
        # int() in the reference truncates toward zero after clipping.
        zero_point = jnp.trunc(jnp.clip(init_zp, qmin, qmax))
    elif info is not None:
        scale, zero_point, mean_val = info.scale, info.zero_point, info.mean
    else:
        scale = jnp.asarray(SCALE_QUANTIZE, x.dtype)
        zero_point = jnp.asarray(ZERO_POINT_QUANTIZE, x.dtype)
        mean_val = jnp.asarray(0.0, x.dtype)

    q = zero_point + (x - mean_val) / scale
    q = jnp.clip(jnp.round(q), qmin, qmax).astype(_int_dtype(num_bits))
    return q, QuantInfo(scale=scale.astype(jnp.float32),
                        zero_point=zero_point.astype(jnp.float32),
                        mean=mean_val.astype(jnp.float32))


def dequantize(q: jnp.ndarray, info: QuantInfo | None = None) -> jnp.ndarray:
    """Inverse transform (flow_utils.py:208-212)."""
    qf = q.astype(jnp.float32)
    if info is None:
        return SCALE_QUANTIZE * (qf - ZERO_POINT_QUANTIZE)
    return info.scale * (qf - info.zero_point) + info.mean


def quantize_pytree(tree, num_bits: int = 8):
    """Quantize every leaf of a pytree; returns (payload tree, info tree)."""
    leaves, treedef = jax.tree.flatten(tree)
    qs, infos = [], []
    for leaf in leaves:
        q, info = quantize(leaf, num_bits=num_bits, adaptive=True)
        qs.append(q)
        infos.append(info)
    return jax.tree.unflatten(treedef, qs), jax.tree.unflatten(treedef, infos)


def dequantize_pytree(payload, infos):
    leaves_q, treedef = jax.tree.flatten(payload)
    leaves_i = treedef.flatten_up_to(infos)
    return jax.tree.unflatten(
        treedef, [dequantize(q, i) for q, i in zip(leaves_q, leaves_i)])


def quantize_dequantize(x: jnp.ndarray, num_bits: int = 8) -> jnp.ndarray:
    """Round-trip, i.e. the value the receiver reconstructs."""
    q, info = quantize(x, num_bits=num_bits, adaptive=True)
    return dequantize(q, info)
