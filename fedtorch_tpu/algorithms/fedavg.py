"""FedAvg, FedProx, and FedAdam.

Parity targets:
* FedAvg — weighted model-delta sum + server step
  (comms/algorithms/federated/fedavg.py:11-99), with optional adaptive
  int8/int16 quantization of the uplink payload and of the aggregated
  downlink (fedavg.py:40-64). On TPU the "wire" is an ICI collective; the
  quantize->sum->quantize->dequantize chain is kept in-graph so numerics
  match the reference's lossy path.
* FedProx — adds the proximal term mu/2 ||x - x_s||^2 to the local loss.
  The reference implements it as a gradient correction mu*(x - x_s) added
  before the step (federated/main.py:123-129); both forms are identical
  for SGD, we use the gradient form.
* FedAdam (arXiv:2003.00295) — per-layer adaptive server denominator
  v = beta*v + (1-beta)*||d||; d /= sqrt(v)+tau (fedavg.py:81-84).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.core import optim
from fedtorch_tpu.core.state import tree_scale


class FedAvg(FedAlgorithm):
    name = "fedavg"

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        # uplink quantization happens in payload_batch_transform (on the
        # stacked client axis, outside the vmap) — not here
        return tree_scale(delta, weight), client_aux

    def payload_batch_transform(self, payloads):
        if self.cfg.federated.quantized:
            # per-client uplink quantization (fedavg.py:34-38), bucketed
            # by leaf size so equal-sized tensors share one client-grid
            # pallas launch (per-slice stats = exact per-tensor,
            # per-client semantics). XLA vmap fallback off-TPU AND when
            # the client axis is sharded over >1 device: the pallas
            # custom call has no GSPMD partitioning rule, while XLA's
            # quantizer partitions cleanly with the axis.
            from fedtorch_tpu.ops.pallas import (
                fused_quantize_dequantize_tree,
            )
            bits = self.cfg.federated.quantized_bits
            payloads = fused_quantize_dequantize_tree(
                payloads, bits, leading_batch=True,
                sharded=self.mesh_devices > 1)
        return payloads

    def aggregate_transform(self, payload_sum):
        if self.cfg.federated.quantized:
            # downlink re-quantization of the summed delta (fedavg.py:54-64)
            # — same bucketed kernel path (the sum is replicated, never
            # sharded, so bucketing is always safe here)
            from fedtorch_tpu.ops.pallas import (
                fused_quantize_dequantize_tree,
            )
            bits = self.cfg.federated.quantized_bits
            payload_sum = fused_quantize_dequantize_tree(
                payload_sum, bits)
        return payload_sum


class FedProx(FedAvg):
    """FedProx = FedAvg + proximal gradient mu*(x - x_server)."""

    name = "fedprox"

    def transform_grads(self, grads, *, params, server_params, client_aux,
                        server_aux, lr):
        mu = self.cfg.federated.fedprox_mu
        return jax.tree.map(lambda g, p, s: g + mu * (p - s),
                            grads, params, server_params)


class FedAdam(FedAvg):
    """Server-side adaptivity: the aggregated delta is normalized per
    layer by a running norm estimate before the server step."""

    name = "fedadam"

    def init_server_aux(self, params, num_clients: int):
        # one scalar v per parameter leaf (args.fedadam_v, comps/init)
        return jax.tree.map(lambda p: jnp.zeros(()), params)

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        beta = self.cfg.federated.fedadam_beta
        tau = self.cfg.federated.fedadam_tau
        new_v = jax.tree.map(
            lambda v, d: beta * v + (1 - beta) * jnp.linalg.norm(d.ravel()),
            server_aux, payload_sum)
        payload_sum = jax.tree.map(
            lambda d, v: d / (jnp.sqrt(v) + tau), payload_sum, new_v)
        new_params, new_opt = optim.server_step(
            server_params, payload_sum, server_opt,
            self.cfg.optim.lr_scale_at_sync, self.cfg.optim)
        return new_params, new_opt, new_v
