"""Qsparse-Local-SGD — top-k sparsified deltas with error feedback.

Parity target: ``qsparse_aggregation``
(comms/algorithms/federated/qsparse.py:11-71):

* sample-size rank weights ``w_i = n_i / N_total`` (qsparse.py:23 —
  unlike fedavg's uniform 1/num_online);
* wire: top-k of ``w*(delta + memory)``; aggregated ``d = sum_i``;
* error feedback: ``memory_i += delta_i - d`` (qsparse.py:57);
* server step on ``d`` with ``lr_scale_at_sync``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.core.state import tree_zeros_like
from fedtorch_tpu.ops.topk import topk_roundtrip


class Qsparse(FedAlgorithm):
    name = "qsparse"

    def setup(self, data) -> None:
        # setup-time host math: sizes live on the host at build time,
        # so summing with numpy avoids a device round-trip entirely
        # (a jnp.sum here would upload, reduce, and sync back — the
        # legal-but-wasteful pattern lint FTL001 exists to catch)
        self._total_samples = float(np.sum(np.asarray(data.sizes)))

    def init_client_aux(self, params):
        return {"memory": tree_zeros_like(params)}

    def client_weights(self, server_aux, online_idx, num_online_eff,
                       sizes):
        # rank_weight = num_samples_per_epoch / train_dataset_size
        return sizes.astype(jnp.float32) / self._total_samples

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        ratio = self.cfg.federated.compressed_ratio
        payload = jax.tree.map(
            lambda d, m: topk_roundtrip((d + m) * weight, ratio),
            delta, client_aux["memory"])
        return payload, client_aux

    def client_post(self, *, delta, client_aux, payload_sum, lr,
                    local_steps, server_params, params, weight):
        return {"memory": jax.tree.map(
            lambda m, dr, d: m + dr - d, client_aux["memory"], delta,
            payload_sum)}
