"""DRFA — Distributionally Robust Federated Averaging (NeurIPS 2020).

Parity target: the DRFA round (comms/trainings/federated/drfa.py:38-258,
SURVEY.md §3.5), a minimax wrapper around an inner aggregation algorithm
(fedavg / fedgate / scaffold — drfa.py:178-193):

* lambda [C] initialized proportional to client sample sizes
  (drfa.py:51-57);
* the dual step size decays 0.9x every round (drfa.py:77);
* client sampling is UNIFORM in both phases (drfa.py:71,216 use
  set_online_clients; the lambda-weighted sampler misc.py:30-37 exists in
  the reference but is never called by its DRFA loop). Set
  ``FederatedConfig.drfa_lambda_sampling=True`` for the paper-faithful
  lambda-distributed sampling via Gumbel top-k (the same
  sequential-renormalization scheme numpy's choice(p=..,replace=False)
  uses);
* aggregation weights: ``lambda_i * C / num_online`` (fedavg.py:27's
  lambda_weight branch), applied through the inner algorithm's payload;
* a shared random step index k ~ U[1, K) is broadcast each round
  (drfa.py:93-99); every client snapshots its model after k local steps
  (drfa.py:109-111) and the snapshots are averaged with 1/|online|
  (aggregate_models_virtual, misc.py:39-52);
* second phase (drfa.py:215-249): a SECOND uniformly-sampled client set
  computes the kth-average model's loss on one random local batch; the
  dual ascends ``lambda += gamma * K * loss_vector * (C/num_online2)``,
  projects onto the simplex and floors at 1e-3.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.base import (FedAlgorithm, num_online_effective)
from fedtorch_tpu.core.losses import per_sample_loss
from fedtorch_tpu.core.state import tree_scale, tree_zeros_like
from fedtorch_tpu.data.batching import sample_batch
from fedtorch_tpu.ops.simplex import project_simplex_floor


class DRFA(FedAlgorithm):
    name = "drfa"
    # the dual phase streams: host_probe_fn plans the second-phase
    # batches, post_round_global_feed consumes them from the feed
    needs_post_probe = True

    def __init__(self, cfg, inner: FedAlgorithm):
        super().__init__(cfg)
        self.inner = inner

    @property
    def participation_replayable(self):
        # the default-uniform draw replays on the host bit-exactly;
        # lambda-distributed sampling reads DEVICE state (the dual
        # variable) the host schedule cannot see — the cell validator
        # keeps that variant off the feed source
        return not self.cfg.federated.drfa_lambda_sampling

    # -- delegation helpers ------------------------------------------------
    def setup(self, data):
        self.inner.setup(data)
        self._sizes = jnp.asarray(data.sizes, jnp.float32)

    def bind(self, model, criterion):
        super().bind(model, criterion)
        self.inner.bind(model, criterion)

    # -- state -------------------------------------------------------------
    def init_client_aux(self, params):
        return {"inner": self.inner.init_client_aux(params),
                "kth": tree_zeros_like(params),
                "k_rand": jnp.zeros((), jnp.int32)}

    def init_server_aux(self, params, num_clients: int):
        lam = self._sizes / jnp.sum(self._sizes)  # drfa.py:51-57
        return {"inner": self.inner.init_server_aux(params, num_clients),
                "lambda": lam,
                "gamma": jnp.asarray(self.cfg.federated.drfa_gamma),
                "kth_avg": tree_zeros_like(params)}

    # -- sampling & weighting ---------------------------------------------
    def participation(self, rng, num_clients, k, round_idx, server_aux):
        if not self.cfg.federated.drfa_lambda_sampling:
            # reference behavior: engine's default uniform sampling with
            # round-0 client-0 forcing (drfa.py:71-75)
            return None
        # paper-faithful option: Gumbel top-k == sampling w/o replacement
        # from lambda (the reference's unused misc.py:30-37 sampler)
        lam = jnp.clip(server_aux["lambda"], 1e-12, None)
        g = jax.random.gumbel(rng, (num_clients,))
        return jax.lax.top_k(jnp.log(lam) + g, k)[1]

    def client_weights(self, server_aux, online_idx, num_online_eff,
                       sizes):
        lam = jnp.take(server_aux["lambda"], online_idx)
        n = self.cfg.federated.num_clients
        return lam * n / num_online_eff  # fedavg.py:27

    # -- local loop --------------------------------------------------------
    def pre_round(self, on_aux, *, server, x, y, sizes, lr, rng):
        K = max(self.local_steps_per_round, 2)
        k_rand = jax.random.randint(jax.random.fold_in(rng, 11), (), 1, K)
        k_full = jnp.full(on_aux["k_rand"].shape, k_rand, jnp.int32)
        inner_aux = self.inner.pre_round(
            on_aux["inner"], server=server._replace(
                aux=server.aux["inner"]),
            x=x, y=y, sizes=sizes, lr=lr, rng=rng)
        return dict(on_aux, inner=inner_aux, k_rand=k_full)

    def local_step(self, *, params, opt, client_aux, rnn_carry,
                   server_params, server_aux, bx, by, bval_x, bval_y, lr,
                   rng, step_idx, local_index, step_budget=None):
        params, opt, inner_aux, rnn_carry, loss, acc = \
            self.inner.local_step(
                params=params, opt=opt, client_aux=client_aux["inner"],
                rnn_carry=rnn_carry, server_params=server_params,
                server_aux=server_aux["inner"], bx=bx, by=by,
                bval_x=bval_x, bval_y=bval_y, lr=lr, rng=rng,
                step_idx=step_idx, local_index=local_index,
                step_budget=step_budget)
        # snapshot after k local steps (drfa.py:109-111); under
        # epoch-sync size skew the shared k is clamped into the client's
        # own active range so an early-exited client still snapshots a
        # REAL model (the reference's DRFA "does not fully support the
        # epoch mode", drfa.py:96 — this is the faithful generalization)
        k_snap = client_aux["k_rand"] if step_budget is None \
            else jnp.minimum(client_aux["k_rand"],
                             jnp.asarray(step_budget, jnp.int32))
        hit = (step_idx + 1) == k_snap
        kth = jax.tree.map(lambda s, p: jnp.where(hit, p, s),
                           client_aux["kth"], params)
        new_aux = dict(client_aux, inner=inner_aux, kth=kth)
        return params, opt, new_aux, rnn_carry, loss, acc

    # -- aggregation -------------------------------------------------------
    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        inner_payload, inner_aux = self.inner.client_payload(
            delta=delta, client_aux=client_aux["inner"], params=params,
            server_params=server_params, server_aux=server_aux["inner"],
            lr=lr, local_steps=local_steps, weight=weight,
            full_loss=full_loss)
        payload = {"inner": inner_payload,
                   # aggregate_models_virtual: 1/|online| model average
                   "kth": tree_scale(client_aux["kth"],
                                     1.0 / self.k_online)}
        return payload, dict(client_aux, inner=inner_aux)

    def payload_batch_transform(self, payloads):
        return dict(payloads,
                    inner=self.inner.payload_batch_transform(
                        payloads["inner"]))

    def aggregate_transform(self, payload_sum):
        return dict(payload_sum,
                    inner=self.inner.aggregate_transform(
                        payload_sum["inner"]))

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        new_params, new_opt, inner_saux = self.inner.server_update(
            server_params, server_opt, server_aux["inner"],
            payload_sum["inner"], online_idx=online_idx,
            num_online_eff=num_online_eff, client_losses=client_losses)
        new_aux = dict(server_aux, inner=inner_saux,
                       kth_avg=payload_sum["kth"])
        return new_params, new_opt, new_aux

    def client_post(self, *, delta, client_aux, payload_sum, lr,
                    local_steps, server_params, params, weight):
        inner_aux = self.inner.client_post(
            delta=delta, client_aux=client_aux["inner"],
            payload_sum=payload_sum["inner"], lr=lr,
            local_steps=local_steps, server_params=server_params,
            params=params, weight=weight)
        return dict(client_aux, inner=inner_aux)

    # -- dual update (second phase, drfa.py:215-249) -----------------------
    def post_round_global(self, server, data, rng):
        C = self.cfg.federated.num_clients
        k = self.k_online
        B = self.cfg.data.batch_size
        rng_idx, rng_batch = jax.random.split(rng)
        idx2 = jax.random.permutation(rng_idx, C)[:k]  # uniform sampling
        kth_avg = server.aux["kth_avg"]
        model = self.model

        def one_loss(ci, rng_c):
            x, y = data.x[ci], data.y[ci]
            bx, by = sample_batch(rng_c, x, y, data.sizes[ci], B)
            # fresh hidden for the kth-model probe (centered/drfa.py:242)
            logits = self.forward_reset(kth_avg, bx)
            return jnp.mean(per_sample_loss(logits, by,
                                            model.is_regression))

        losses = jax.vmap(one_loss)(idx2, jax.random.split(rng_batch, k))
        return self._dual_update(server, idx2, losses)

    def _dual_update(self, server, idx2, losses):
        """The dual ascent shared by both data planes: scatter the
        probe losses into [C], step lambda, project (drfa.py:239-249)."""
        C = self.cfg.federated.num_clients
        num_online2 = num_online_effective(idx2)
        lam = server.aux["lambda"]
        # per-round decayed dual step size (drfa.py:77 gamma *= 0.9)
        gamma = server.aux["gamma"] * 0.9
        # loss_tensor scaled by n/num_online (drfa.py:239-241)
        loss_vec = jnp.zeros_like(lam).at[idx2].set(
            losses * C / num_online2)
        lam = lam + gamma * self.local_steps_per_round * loss_vec
        lam = project_simplex_floor(lam, floor=1e-3)
        return server._replace(
            aux=dict(server.aux, **{"lambda": lam, "gamma": gamma}))

    def host_probe_fn(self, sizes):
        """Host replay of the second phase's data plan: the SAME
        ``fold_in(rng_round, 99)`` → split → uniform permutation →
        per-client ``sample_batch`` index draw the device phase
        consumes (threefry is backend-deterministic, so the cohort and
        rows are bit-exact). Runs inside the jitted RoundSchedule on
        the CPU backend."""
        C = self.cfg.federated.num_clients
        k = self.k_online
        B = self.cfg.data.batch_size
        sizes32 = jnp.asarray(sizes, jnp.int32)

        def probe(rng_round):
            rng = jax.random.fold_in(rng_round, 99)
            rng_idx, rng_batch = jax.random.split(rng)
            idx2 = jax.random.permutation(rng_idx, C)[:k]
            rngs = jax.random.split(rng_batch, k)
            on_sizes = jnp.take(sizes32, idx2)
            # sample_batch's exact index draw (data/batching.py)
            rows = jax.vmap(lambda r, s: jax.random.randint(
                r, (B,), 0, jnp.maximum(s, 1)))(rngs, on_sizes)
            return idx2, rows
        return probe

    def post_round_global_feed(self, server, probe, rng):
        """The dual phase on the stream plane: the probe cohort's
        batches arrive pre-gathered in the feed (``probe_idx`` IS the
        ``permutation(rng_idx, C)[:k]`` draw — the host replayed it
        from the same key), so the device does O(k) probe work with no
        [C, n_max, ...] input. Bitwise-identical lambda trajectory to
        :meth:`post_round_global` (tests/test_streaming.py)."""
        kth_avg = server.aux["kth_avg"]
        model = self.model

        def one_loss(bx, by):
            # fresh hidden for the kth-model probe (centered/drfa.py:242)
            logits = self.forward_reset(kth_avg, bx)
            return jnp.mean(per_sample_loss(logits, by,
                                            model.is_regression))

        losses = jax.vmap(one_loss)(probe.probe_x, probe.probe_y)
        return self._dual_update(server, probe.probe_idx, losses)
