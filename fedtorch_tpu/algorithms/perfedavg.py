"""PerFedAvg — Personalized FedAvg via first-order MAML (arXiv:2002.07948).

Parity target: the perfedavg branch of the centered loop
(comms/trainings/federated/centered/main.py:156-170): after each standard
local step (the MAML inner step at the scheduled LR), one more SGD step is
taken on a batch from the client's validation split at the fixed outer
rate ``perfedavg_beta`` (scheduler.py lr_external override). Aggregation
is plain FedAvg; personalization is the adapted model itself.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.fedavg import FedAvg
from fedtorch_tpu.core import optim


class PerFedAvg(FedAvg):
    name = "perfedavg"
    needs_val_batch = True

    def init_client_aux(self, params):
        # pre-aggregation adapted model — the personalized artifact
        return {"local_snapshot": jax.tree.map(jnp.array, params)}

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        payload, aux = super().client_payload(
            delta=delta, client_aux=client_aux, params=params,
            server_params=server_params, server_aux=server_aux, lr=lr,
            local_steps=local_steps, weight=weight, full_loss=full_loss)
        return payload, dict(aux, local_snapshot=params)

    def local_step(self, *, params, opt, client_aux, rnn_carry,
                   server_params, server_aux, bx, by, bval_x, bval_y, lr,
                   rng, step_idx, local_index, step_budget=None):
        # inner step (centered/main.py:127-141 standard step)
        params, opt, client_aux, rnn_carry, loss, acc = super().local_step(
            params=params, opt=opt, client_aux=client_aux,
            rnn_carry=rnn_carry, server_params=server_params,
            server_aux=server_aux, bx=bx, by=by, bval_x=bval_x,
            bval_y=bval_y, lr=lr, rng=rng, step_idx=step_idx,
            local_index=local_index, step_budget=step_budget)

        # outer step at beta on the val batch (centered/main.py:156-170)
        beta = self.cfg.federated.perfedavg_beta
        rng_v = jax.random.fold_in(rng, 2)

        def vloss(p):
            # the reference's outer inference threads no hidden state
            # (centered/main.py:166); fresh zero carry for rnn archs
            logits = self.forward_reset(p, bval_x, train=True, rng=rng_v)
            return self.criterion(logits, bval_y)

        g = jax.grad(vloss)(params)
        params, opt = optim.local_step(params, g, opt, beta,
                                       self.cfg.optim)
        return params, opt, client_aux, rnn_carry, loss, acc
