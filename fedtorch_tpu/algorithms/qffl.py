"""qFFL / q-FedAvg (arXiv:1905.10497) — fairness-weighted aggregation.

Parity target: ``qffl_aggregation_centered``
(comms/algorithms/federated/centered/qffl.py:4-33) — the reference wires
qFFL only in centered mode (SURVEY.md §2.3):

* each client's full-data loss F_k on the incoming server model scales its
  delta: ``Delta_k = delta_k * F_k^q / lr``;
* normalizer ``h = sum_k [ q*F_k^(q-1)*||Delta_k||^2 + F_k^q / lr ]``
  (accumulated per-parameter in the reference; the norm is per-layer);
* server applies ``(sum_k Delta_k) / (h + 1e-10)``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.core import optim


class QFFL(FedAlgorithm):
    name = "qffl"
    needs_full_loss = True

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        q = self.cfg.federated.qffl_q
        fq = jnp.float_power(full_loss + 1e-10, q)
        scaled = jax.tree.map(lambda d: d * fq / lr, delta)
        # h contribution (qffl.py:20-23): per-layer squared norms of the
        # scaled delta, plus the loss term once per client
        sq_norms = sum(jnp.sum(jnp.square(x))
                       for x in jax.tree.leaves(scaled))
        h = q * jnp.float_power(full_loss + 1e-10, q - 1.0) * sq_norms \
            + fq / lr
        return {"delta": scaled, "h": h}, client_aux

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        d = jax.tree.map(lambda x: x / (payload_sum["h"] + 1e-10),
                         payload_sum["delta"])
        new_params, new_opt = optim.server_step(
            server_params, d, server_opt,
            self.cfg.optim.lr_scale_at_sync, self.cfg.optim)
        return new_params, new_opt, server_aux
