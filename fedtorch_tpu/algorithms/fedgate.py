"""FedGATE / FedCOMGATE (arXiv:2007.01154) — gradient tracking with
optional compressed or quantized aggregation.

Parity target: ``fedgate_aggregation``
(comms/algorithms/federated/fedgate.py:13-118) and the local correction
(federated/main.py:116-119):

* local step: ``g <- g - delta_i`` (the gradient-tracking variate);
* wire formats (fedgate.py:33-100): adaptive-quantized weighted delta;
  top-k compressed ``w*(delta_i + memory_i)`` with error-feedback memory
  ``memory_i += delta_i - d`` where ``d`` is the aggregated sum
  (fedgate.py:74-79, applied post-aggregation); or the dense weighted
  delta;
* tracking update after aggregation (fedgate.py:102-104):
  ``delta_i += (delta_round_i - d) / (lr * K)`` computed before the client
  re-syncs to the server model — here in :meth:`client_post` with the
  aggregated payload.
* FedCOMGATE = FedGATE + quantization (BASELINE.md config #2's ``-q``).
"""
from __future__ import annotations

import jax

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.core.state import tree_scale, tree_zeros_like
from fedtorch_tpu.ops.topk import topk_roundtrip


class FedGate(FedAlgorithm):
    name = "fedgate"

    def init_client_aux(self, params):
        aux = {"delta": tree_zeros_like(params)}
        if self.cfg.federated.compressed:
            aux["memory"] = tree_zeros_like(params)
        return aux

    def transform_grads(self, grads, *, params, server_params, client_aux,
                        server_aux, lr):
        # gradient tracking (main.py:116-119)
        return jax.tree.map(lambda g, d: g - d, grads, client_aux["delta"])

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        fed = self.cfg.federated
        weighted = tree_scale(delta, weight)
        if fed.quantized:
            # quantized uplink applied in payload_batch_transform
            payload = weighted
        elif fed.compressed:
            # g = w*delta + w*memory, top-k sparsified (fedgate.py:59-66)
            payload = jax.tree.map(
                lambda d, m: topk_roundtrip(d + m * weight,
                                            fed.compressed_ratio),
                weighted, client_aux["memory"])
        else:
            payload = weighted
        return payload, client_aux

    def payload_batch_transform(self, payloads):
        if self.cfg.federated.quantized:
            # FedCOMGATE quantized uplink (fedgate.py:33-44), per-client
            # stats on the stacked axis, bucketed by leaf size (one
            # client-grid launch per distinct size); XLA fallback when
            # the client axis spans multiple devices (no pallas
            # partitioning rule)
            from fedtorch_tpu.ops.pallas import (
                fused_quantize_dequantize_tree,
            )
            payloads = fused_quantize_dequantize_tree(
                payloads, self.cfg.federated.quantized_bits,
                leading_batch=True, sharded=self.mesh_devices > 1)
        return payloads

    def aggregate_transform(self, payload_sum):
        # FedCOMGATE downlink: the re-quantized aggregate feeds BOTH the
        # server step and the clients' tracking/memory updates
        # (fedgate.py:74-79 broadcasts the re-quantized tensor)
        if self.cfg.federated.quantized:
            from fedtorch_tpu.ops.pallas import (
                fused_quantize_dequantize_tree,
            )
            payload_sum = fused_quantize_dequantize_tree(
                payload_sum, self.cfg.federated.quantized_bits)
        return payload_sum

    def client_post(self, *, delta, client_aux, payload_sum, lr,
                    local_steps, server_params, params, weight):
        # tracking variate: delta_i += (delta_round_i - d)/(lr*K)
        # (fedgate.py:102-104; delta arg here is x_s - x_i of this round)
        new_track = jax.tree.map(
            lambda t, dr, d: t + (dr - d) / (lr * local_steps),
            client_aux["delta"], delta, payload_sum)
        new_aux = dict(client_aux, delta=new_track)
        if self.cfg.federated.compressed:
            # error feedback (fedgate.py:78): memory_i += delta_i - d
            new_aux["memory"] = jax.tree.map(
                lambda m, dr, d: m + dr - d, client_aux["memory"], delta,
                payload_sum)
        return new_aux
