"""Algorithm registry (--federated_type dispatch, main.py:29-42)."""
from __future__ import annotations

from fedtorch_tpu.algorithms.apfl import APFL
from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.algorithms.fedavg import FedAdam, FedAvg, FedProx
from fedtorch_tpu.algorithms.fedgate import FedGate
from fedtorch_tpu.algorithms.perfedavg import PerFedAvg
from fedtorch_tpu.algorithms.perfedme import PerFedMe
from fedtorch_tpu.algorithms.qffl import QFFL
from fedtorch_tpu.algorithms.qsparse import Qsparse
from fedtorch_tpu.algorithms.scaffold import Scaffold

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.name] = cls
    return cls


for _cls in (FedAvg, FedProx, FedAdam, Scaffold, FedGate, Qsparse, QFFL,
             APFL, PerFedMe, PerFedAvg):
    register(_cls)


def make_algorithm(cfg) -> FedAlgorithm:
    name = cfg.federated.algorithm
    if name not in _REGISTRY:
        raise ValueError(
            f"Algorithm {name!r} is not implemented yet; available: "
            f"{sorted(_REGISTRY)}")
    return _REGISTRY[name](cfg)
