"""Algorithm registry (--federated_type dispatch, main.py:29-42)."""
from __future__ import annotations

from fedtorch_tpu.algorithms.afl import AFL
from fedtorch_tpu.algorithms.apfl import APFL
from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.algorithms.drfa import DRFA
from fedtorch_tpu.algorithms.fedavg import FedAdam, FedAvg, FedProx
from fedtorch_tpu.algorithms.fedgate import FedGate
from fedtorch_tpu.algorithms.perfedavg import PerFedAvg
from fedtorch_tpu.algorithms.perfedme import PerFedMe
from fedtorch_tpu.algorithms.qffl import QFFL
from fedtorch_tpu.algorithms.qsparse import Qsparse
from fedtorch_tpu.algorithms.scaffold import Scaffold

_REGISTRY = {}

# inner aggregations DRFA can wrap (drfa.py:178-193)
DRFA_INNER = ("fedavg", "fedgate", "scaffold")


def register(cls):
    _REGISTRY[cls.name] = cls
    return cls


for _cls in (FedAvg, FedProx, FedAdam, Scaffold, FedGate, Qsparse, QFFL,
             APFL, PerFedMe, PerFedAvg, AFL):
    register(_cls)


def make_algorithm(cfg) -> FedAlgorithm:
    name = cfg.federated.algorithm
    if name not in _REGISTRY:
        raise ValueError(
            f"Algorithm {name!r} is not implemented yet; available: "
            f"{sorted(_REGISTRY)} (+ drfa wrapper)")
    if cfg.federated.drfa:
        if name not in DRFA_INNER:
            raise ValueError(
                f"DRFA wraps one of {DRFA_INNER}, got {name!r} "
                "(ref: drfa.py:178-193)")
        return DRFA(cfg, inner=_REGISTRY[name](cfg))
    return _REGISTRY[name](cfg)
