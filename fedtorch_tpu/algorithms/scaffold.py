"""SCAFFOLD (arXiv:1910.06378) — control-variate variance reduction.

Parity target: the reference's *centered* implementation
(comms/algorithms/federated/centered/scaffold.py:3-49), which is the
faithful one — the MPI version double-applies the gathered tensor
(scaffold.py:58-64 assigns ``cp.grad.data = d[0]`` then immediately
overwrites it with the client's own ``t[0]`` and decrements the server
control twice), a bug we do not reproduce.

Semantics:
* local step: ``g <- g + c - c_i`` (server minus client control,
  federated/main.py:120-122);
* at sync: ``c_i+ = c_i - c + (x_s - x_i)/(K*lr)`` (scaffold.py:26-27);
* aggregation payload: weighted model delta plus the control delta
  ``(c_i+ - c_i)/N`` (centered/scaffold.py:31-38: server control
  accumulates the sum of control deltas over online clients divided by the
  TOTAL client count N);
* server: ``x_s -= scale * sum(w_i * delta_i)``; ``c += sum_i (c_i+ -
  c_i)/N``.

The control-variate pair rides the same aggregation collective as the
model delta (the reference stacks them into one tensor per param,
scaffold.py:38-56 — here they are just two pytree branches of the
payload).

Momentum caveat (measured, not hypothetical): the control update
``(x_s - x_i)/(K*lr)`` equals the mean local gradient ONLY under plain
SGD. With ``in_momentum`` the realized per-step displacement is up to
``1/(1-m)`` times larger, the controls over-estimate, and training
diverges exponentially — in the reference exactly as here (verified
side-by-side on the reference's centered scaffold with
``--in_momentum True``: both trajectories blow up within ~15 rounds,
2026-07-29). Run SCAFFOLD with plain local SGD, as in the paper.
"""
from __future__ import annotations

import jax

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.core import optim
from fedtorch_tpu.core.state import tree_scale, tree_zeros_like


class Scaffold(FedAlgorithm):
    name = "scaffold"

    def init_client_aux(self, params):
        return {"control": tree_zeros_like(params)}

    def init_server_aux(self, params, num_clients: int):
        return {"control": tree_zeros_like(params)}

    def transform_grads(self, grads, *, params, server_params, client_aux,
                        server_aux, lr):
        return jax.tree.map(lambda g, c, ci: g + c - ci, grads,
                            server_aux["control"], client_aux["control"])

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        c_i = client_aux["control"]
        # c_i+ = c_i - c + (x_s - x_i)/(K*lr); delta = x_s - x_i
        c_new = jax.tree.map(
            lambda ci, c, d: ci - c + d / (local_steps * lr),
            c_i, server_aux["control"], delta)
        control_delta = jax.tree.map(lambda cn, ci: cn - ci, c_new, c_i)
        n_total = self.cfg.federated.num_clients
        payload = {
            "delta": tree_scale(delta, weight),
            "control_delta": tree_scale(control_delta, 1.0 / n_total),
        }
        return payload, {"control": c_new}

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        new_params, new_opt = optim.server_step(
            server_params, payload_sum["delta"], server_opt,
            self.cfg.optim.lr_scale_at_sync, self.cfg.optim)
        new_control = jax.tree.map(
            lambda c, d: c + d, server_aux["control"],
            payload_sum["control_delta"])
        return new_params, new_opt, {"control": new_control}

    def payload_scale(self) -> float:
        return 2.0  # delta + control variate per param (scaffold.py:38)
