"""APFL — Adaptive Personalized Federated Learning (arXiv:2003.13461).

Parity target: the APFL training loop
(comms/trainings/federated/apfl.py:33-180):

* per batch, TWO steps (apfl.py:95-116): a standard local-model step, then
  a personalized-model step on the mixed output
  ``alpha*personal(x) + (1-alpha)*local(x)`` (inference_personal,
  eval.py:31-39) using the *updated* local model, with gradients taken
  w.r.t. the personal parameters only;
* optional adaptive alpha on the first batch of each round
  (apfl.py:119-123 -> flow_utils.py:240-250):
  ``grad_alpha = sum_l <p_personal - p_local, alpha*g_personal +
  (1-alpha)*g_local> + 0.02*alpha``; ``alpha <- clip(alpha - eta*
  grad_alpha, 0, 1)``, then averaged across the online clients. (The
  reference's global_average passes count=n_nodes per client, shrinking
  alpha by ~n — an apparent bug; we use the plain mean over online
  clients.)
* aggregation: plain FedAvg on the local model (apfl.py:151-152); the
  personal model and its optimizer state persist per client.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.fedavg import FedAvg
from fedtorch_tpu.core import optim


class APFL(FedAvg):
    name = "apfl"

    def init_client_aux(self, params):
        return {
            "personal": jax.tree.map(jnp.array, params),
            "personal_opt": optim.init_opt_state(params, self.cfg.optim),
            "alpha": jnp.asarray(self.cfg.federated.personal_alpha),
            # pre-aggregation local model for personalized evaluation
            # (the reference validates personal models BEFORE the sync,
            # apfl.py:138-144)
            "local_snapshot": jax.tree.map(jnp.array, params),
        }

    def _mixed_loss(self, personal_params, local_params, alpha, bx, by,
                    rng):
        # recurrent models run with a fresh zero carry per batch
        # (forward_reset policy; base.py)
        train = rng is not None
        out_p = self.forward_reset(personal_params, bx, train=train,
                                   rng=rng)
        out_l = self.forward_reset(local_params, bx, train=train, rng=rng)
        return self.criterion(alpha * out_p + (1 - alpha) * out_l, by)

    def pre_round(self, on_aux, *, server, x, y, sizes, lr, rng):
        """Adaptive alpha (apfl.py:119-123): per-client update on the
        round's first batch at the scheduled LR, then averaged across the
        online clients. The alpha gradient is evaluated deterministically
        (no dropout noise)."""
        if not self.cfg.federated.adaptive_alpha:
            return on_aux
        B = self.cfg.data.batch_size

        def one(aux, xc, yc, eta):
            bx, by = xc[:B], yc[:B]
            alpha = aux["alpha"]
            g_p = jax.grad(self._mixed_loss, argnums=0)(
                aux["personal"], server.params, alpha, bx, by, None)
            g_l = jax.grad(self._mixed_loss, argnums=1)(
                aux["personal"], server.params, alpha, bx, by, None)
            # grad_alpha = sum <p_pers - p_local, alpha*g_p + (1-a)*g_l>
            grad_alpha = sum(
                jnp.vdot(pp - pl, alpha * gp + (1 - alpha) * gl)
                for pp, pl, gp, gl in zip(
                    jax.tree.leaves(aux["personal"]),
                    jax.tree.leaves(server.params),
                    jax.tree.leaves(g_p), jax.tree.leaves(g_l)))
            grad_alpha = grad_alpha + 0.02 * alpha
            new_alpha = jnp.clip(alpha - eta * grad_alpha, 0.0, 1.0)
            return dict(aux, alpha=new_alpha)

        new_aux = jax.vmap(one)(on_aux, x, y, lr)
        mean_alpha = jnp.mean(new_aux["alpha"])
        return dict(new_aux,
                    alpha=jnp.full_like(new_aux["alpha"], mean_alpha))

    def local_step(self, *, params, opt, client_aux, rnn_carry,
                   server_params, server_aux, bx, by, bval_x, bval_y, lr,
                   rng, step_idx, local_index, step_budget=None):
        # 1) standard local step (apfl.py:95-103)
        params, opt, client_aux, rnn_carry, loss, acc = super().local_step(
            params=params, opt=opt, client_aux=client_aux,
            rnn_carry=rnn_carry, server_params=server_params,
            server_aux=server_aux, bx=bx, by=by, bval_x=bval_x,
            bval_y=bval_y, lr=lr, rng=rng, step_idx=step_idx,
            local_index=local_index, step_budget=step_budget)
        # 2) personal step on the mixed output with the UPDATED local
        #    model (apfl.py:105-116)
        alpha = client_aux["alpha"]
        rng_p = jax.random.fold_in(rng, 1)
        g_p = jax.grad(self._mixed_loss, argnums=0)(
            client_aux["personal"], params, alpha, bx, by, rng_p)
        personal, p_opt = optim.local_step(
            client_aux["personal"], g_p, client_aux["personal_opt"], lr,
            self.cfg.optim)
        new_aux = dict(client_aux, personal=personal, personal_opt=p_opt)
        return params, opt, new_aux, rnn_carry, loss, acc

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight, full_loss=None):
        payload, aux = super().client_payload(
            delta=delta, client_aux=client_aux, params=params,
            server_params=server_params, server_aux=server_aux, lr=lr,
            local_steps=local_steps, weight=weight, full_loss=full_loss)
        # keep the trained pre-sync local model for personalized eval
        return payload, dict(aux, local_snapshot=params)
