"""Federated algorithm interface.

The reference couples each algorithm's logic across three places: aux-state
construction (nodes/nodes.py:87-112 ``gen_aux_models``), in-loop gradient
corrections (comms/trainings/federated/main.py:116-129), and an aggregation
function (comms/algorithms/federated/*). Here an algorithm is one object
with pure-function hooks; the engine (parallel/federated.py) calls them

* under ``vmap`` over the client axis (aux init, grad transform, payload),
* replicated for the server update.

All hooks must be jit-traceable: static shapes, no Python control flow on
traced values.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from fedtorch_tpu.config import ExperimentConfig
from fedtorch_tpu.core import optim
from fedtorch_tpu.core.losses import accuracy  # noqa: F401 (hook use)
from fedtorch_tpu.core.state import tree_scale


def num_online_effective(online_idx: jnp.ndarray) -> jnp.ndarray:
    """The reference's weighting denominator (fedavg.py:18-27): |online|
    when client 0 is online, |online|+1 otherwise (the MPI server shares
    rank 0 with a client). Shared by the engine and DRFA's second
    sampling phase."""
    k = online_idx.shape[0]
    has0 = jnp.any(online_idx == 0).astype(jnp.float32)
    return k + (1.0 - has0)


class FedAlgorithm:
    """Base = FedAvg behavior; subclasses override hooks."""

    name = "fedavg"
    # engine computes each online client's full-data loss on the incoming
    # server model when set (qFFL, centered/main.py:62-72)
    needs_full_loss = False

    # set when the algorithm consumes a per-step validation batch
    # (PerFedAvg's MAML outer step; requires cfg.federated.personal)
    needs_val_batch = False

    # True when the host RoundSchedule can replay this algorithm's
    # participation draw bit-exactly (the stream plane's precondition:
    # the feed packer must know the cohort before the round runs). The
    # base default samples uniformly from the round key alone, which
    # the schedule replays; an override that reads DEVICE state the
    # host cannot see (DRFA's lambda-distributed sampling) must leave
    # this False — the cell validator refuses the feed source then.
    # Subclasses overriding ``participation`` with a replayable draw
    # flip this True (or make it a property over their config).
    participation_replayable = True

    # True when the algorithm's ``post_round_global`` phase can run on
    # the stream plane from a host-packed probe (``host_probe_fn`` +
    # ``post_round_global_feed`` below — DRFA's dual update). False
    # with an overridden ``post_round_global`` means the feed source
    # is refused (the phase needs full data access).
    needs_post_probe = False

    def __init__(self, cfg: ExperimentConfig):
        self.cfg = cfg
        self.model = None
        self.criterion = None
        # set by the engine before tracing (static round length / static
        # online-client count / mesh size)
        self.local_steps_per_round = max(cfg.train.local_step, 1)
        # devices the client axis is sharded over; wire-format kernels
        # without a partitioning rule (pallas) must stay off when > 1
        self.mesh_devices = 1
        self.k_online = max(
            int(cfg.federated.online_client_rate
                * cfg.federated.num_clients), 1)

    def setup(self, data) -> None:
        """One-time hook with the ClientData (sample-size weighting)."""

    def bind(self, model, criterion) -> None:
        """Engine provides the model/criterion so algorithm hooks can run
        forwards/backwards of their own (personal models)."""
        self.model = model
        self.criterion = criterion

    # -- state ---------------------------------------------------------
    def init_client_aux(self, params) -> Any:
        """Per-client aux pytree (called under vmap). () = none."""
        return ()

    def init_server_aux(self, params, num_clients: int) -> Any:
        return ()

    # -- local loop hooks (per client, inside the scan) ------------------
    def forward_reset(self, params, bx, *, train: bool = False, rng=None):
        """Forward pass with a FRESH zero hidden carry for recurrent
        models — the policy for every AUXILIARY forward (personal models,
        MAML outer steps, DRFA's kth-model loss probe). The reference
        re-inits hidden per round for its main loop
        (centered/main.py:96-97) and starts auxiliary inferences fresh
        (centered/drfa.py:242); only the engine's main local loop threads
        a carry across steps."""
        model = self.model
        if model.is_recurrent:
            logits, _ = model.apply(
                params, bx, train=train, rng=rng,
                carry=model.init_carry(bx.shape[0]))
            return logits
        return model.apply(params, bx, train=train, rng=rng)

    def extra_loss(self, params, server_params, client_aux) -> jnp.ndarray:
        """Added to the batch loss (FedProx's proximal term)."""
        return jnp.asarray(0.0)

    def transform_grads(self, grads, *, params, server_params, client_aux,
                        server_aux, lr):
        """Gradient correction before the optimizer step
        (fedgate main.py:116-119, scaffold main.py:120-122)."""
        return grads

    def participation(self, rng, num_clients: int, k: int, round_idx,
                      server_aux):
        """Override to control online-client sampling; return a [k] index
        array or None for the engine's default uniform sampling
        (misc.py:10-19). DRFA samples from the lambda distribution
        (misc.py:30-37)."""
        return None

    def post_round_global(self, server, data, rng):
        """Optional second phase after aggregation with full data access
        (DRFA's kth-model loss collection + dual update,
        drfa.py:215-249). Returns the updated ServerState."""
        return server

    def host_probe_fn(self, sizes):
        """Host replica of the ``post_round_global`` phase's DATA
        plan, for the stream plane (``needs_post_probe``): return a
        closure ``probe(rng_round) -> (probe_idx, probe_rows)`` that
        replays — on the CPU backend, bit-exactly — which clients' and
        which storage rows the post phase will consume, from the same
        round key chain the device phase folds. The feed packer
        gathers those rows into ``RoundFeed.probe_*``. None (default)
        = no probe."""
        return None

    def post_round_global_feed(self, server, probe, rng):
        """The ``post_round_global`` twin for the stream plane: same
        math, but over the pre-gathered probe batches (a ``RoundFeed``
        with ``probe_idx``/``probe_x``/``probe_y``) instead of the
        full data pytree — O(k) device work, no [C, n_max, ...]
        input. Must be bitwise-identical to ``post_round_global``
        given the probe ``host_probe_fn`` planned. Returns the updated
        ServerState."""
        return server

    def pre_round(self, on_aux, *, server, x, y, sizes, lr, rng):
        """Once per round, on the gathered [k] online-client aux, OUTSIDE
        the vmapped local loop — the place for cross-client work like
        APFL's globally-averaged adaptive alpha (apfl.py:119-123).
        ``x``/``y``: each online client's first batch (first B
        storage-order rows, identical in every gather mode);
        ``lr``: [k] scheduled LR at each online client's current epoch."""
        return on_aux

    def local_step(self, *, params, opt, client_aux, rnn_carry,
                   server_params, server_aux, bx, by, bval_x, bval_y, lr,
                   rng, step_idx, local_index, step_budget=None):
        """One local training step (the hot loop body,
        federated/main.py:83-155). The base implements the standard
        inference -> backward -> per-algorithm grad correction ->
        dual-mode SGD step; personalized algorithms override or extend.

        ``step_budget`` is the client's EFFECTIVE step count this round
        (its epoch-sync budget; == the scan length in local-step mode):
        steps at index >= step_budget run but are masked out by the
        engine, so step-indexed logic (sync pulls, snapshots) must
        anchor on the budget, not the scan length.

        Returns (params, opt, client_aux, rnn_carry, loss, acc)."""
        model, criterion, cfg = self.model, self.criterion, self.cfg

        moe_w = cfg.model.moe_aux_weight

        def loss_fn(p):
            aux_reg = jnp.asarray(0.0)
            if model.is_recurrent:
                logits, new_rnn = model.apply(p, bx, train=True, rng=rng,
                                              carry=rnn_carry)
            else:
                new_rnn = rnn_carry
                if model.has_aux_loss and moe_w > 0:
                    logits, aux = model.apply_with_aux(
                        p, bx, train=True, rng=rng)
                    aux_reg = moe_w * aux
                else:
                    logits = model.apply(p, bx, train=True, rng=rng)
            loss = criterion(logits, by) + aux_reg
            loss = loss + self.extra_loss(p, server_params, client_aux)
            return loss, (logits, new_rnn)

        (loss, (logits, new_rnn)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = self.transform_grads(
            grads, params=params, server_params=server_params,
            client_aux=client_aux, server_aux=server_aux, lr=lr)
        if model.has_noise_param:
            # robust archs: gradient ASCENT on the adversarial input
            # noise (federated/main.py:131-141)
            grads = dict(grads)
            grads["noise"] = -grads["noise"]
        params, opt = optim.local_step(params, grads, opt, lr, cfg.optim)
        acc = jnp.asarray(0.0) if model.is_regression \
            else accuracy(logits, by)
        return params, opt, client_aux, new_rnn, loss, acc

    # -- aggregation -----------------------------------------------------
    def client_weights(self, server_aux, online_idx, num_online_eff,
                       sizes) -> jnp.ndarray:
        """Aggregation weights [k] for the gathered online clients.

        ``num_online_eff`` is the reference denominator (fedavg.py:18-27):
        |online| when client 0 is online, |online|+1 otherwise (the MPI
        server shares rank 0 with a client). Default: uniform
        1/num_online_eff; AFL/DRFA override with lambda weights."""
        k = online_idx.shape[0]
        return jnp.full((k,), 1.0) / num_online_eff

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight,
                       full_loss=None) -> Tuple[Any, Any]:
        """Per-client (already-weighted) payload for the aggregation
        collective, plus updated aux. delta = server - client.
        ``full_loss`` is provided when ``needs_full_loss`` is set."""
        return tree_scale(delta, weight), client_aux

    def payload_batch_transform(self, payloads):
        """Uplink wire-format transform on the STACKED [k, ...] online
        payloads, applied by the engine AFTER the vmapped client loop
        and BEFORE the aggregation sum. Semantics are per-client
        (leading-axis slices get independent statistics); living outside
        the vmap lets grid-based kernels (the pallas client-grid
        quantizer) serve the uplink, which ``pallas_call`` under vmap
        cannot. Identity by default."""
        return payloads

    def aggregate_transform(self, payload_sum):
        """Downlink wire-format transform of the aggregated payload.

        The engine applies this ONCE after the aggregation collective, so
        ``server_update`` and ``client_post`` consume the SAME transformed
        sum — matching the reference, which re-quantizes the aggregated
        tensor server-side and broadcasts THAT to clients
        (fedavg.py:54-64, fedgate.py:74-79). Identity by default."""
        return payload_sum

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        """Consume the summed payload; apply the dual-mode server step
        (p -= lr_scale_at_sync * d, fedavg.py:89-94).

        ``online_idx``: [k] int client ids of this round's participants;
        ``num_online_eff``: the weighting denominator (client_weights);
        ``client_losses``: [k] mean local train loss per online client
        (AFL's dual ascent consumes these, afl.py:39-47)."""
        new_params, new_opt = optim.server_step(
            server_params, payload_sum, server_opt,
            self.cfg.optim.lr_scale_at_sync, self.cfg.optim)
        return new_params, new_opt, server_aux

    def client_post(self, *, delta, client_aux, payload_sum, lr,
                    local_steps, server_params, params, weight) -> Any:
        """Per-client aux update that needs the aggregated payload
        (FedGATE's gradient-tracking delta, fedgate.py:102-104). Called
        under vmap over the online clients; ``params`` are the client's
        local params at round end, ``lr`` its final local LR."""
        return client_aux

    # -- payload accounting ----------------------------------------------
    def payload_scale(self) -> float:
        """Fraction of dense float32 bytes the wire format costs
        (1.0 dense, 0.25 int8, ...). Used for comm_bytes metrics."""
        fed = self.cfg.federated
        if fed.quantized:
            return fed.quantized_bits / 32.0
        if fed.compressed:
            return fed.compressed_ratio
        return 1.0
