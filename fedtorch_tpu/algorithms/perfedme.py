"""PerFedMe / pFedMe — Moreau-envelope personalization (arXiv:2006.08848).

Parity target: ``train_and_validate_perfedme_centered``
(comms/trainings/federated/centered/perfedme.py:25-167):

* every batch updates the PERSONAL model theta with the prox gradient
  ``grad f(theta) + lambda*(theta - w)`` (perfedme.py:99-101);
* every 5 local steps (and at sync) the local copy of the global model w
  takes a step along ``lambda*(w - theta)`` through the main optimizer
  (perfedme.py:115-124);
* aggregation: plain FedAvg on w; theta persists per client.

Reported train loss/accuracy come from the personal model's inference
(perfedme.py:93), matching the reference tracker.

Stability note: the prox step multiplies by ``lr * lambda``; with the
reference default lambda=15 the personal model oscillates unless
``lr < 1/lambda`` (e.g. lr 0.05 works, 0.3 diverges). Same bound applies
to the reference implementation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.fedavg import FedAvg
from fedtorch_tpu.core import optim
from fedtorch_tpu.core.losses import accuracy


class PerFedMe(FedAvg):
    name = "perfedme"

    def init_client_aux(self, params):
        return {
            "personal": jax.tree.map(jnp.array, params),
            "personal_opt": optim.init_opt_state(params, self.cfg.optim),
        }

    def local_step(self, *, params, opt, client_aux, rnn_carry,
                   server_params, server_aux, bx, by, bval_x, bval_y, lr,
                   rng, step_idx, local_index, step_budget=None):
        lam = self.cfg.federated.perfedme_lambda
        model, criterion = self.model, self.criterion

        def ploss(pp):
            # personal model: fresh zero carry per batch for rnn archs
            logits = self.forward_reset(pp, bx, train=True, rng=rng)
            return criterion(logits, by), logits

        (loss, logits), g_p = jax.value_and_grad(ploss, has_aux=True)(
            client_aux["personal"])
        # prox-to-global gradient (perfedme.py:99-101)
        g_p = jax.tree.map(lambda g, p, w: g + lam * (p - w), g_p,
                           client_aux["personal"], params)
        personal, p_opt = optim.local_step(
            client_aux["personal"], g_p, client_aux["personal_opt"], lr,
            self.cfg.optim)

        # every 5 steps or at sync (= the client's OWN last active step,
        # perfedme.py:115-124 fires where the reference's local loop
        # exits — under epoch-sync size skew that is the client's budget,
        # not the scan length): pull w toward theta
        last_step = step_budget if step_budget is not None \
            else self.local_steps_per_round
        is_last = (step_idx + 1) == last_step
        update_w = ((local_index + 1) % 5 == 0) | is_last
        g_w = jax.tree.map(lambda w, p: lam * (w - p), params, personal)
        new_params, new_opt = optim.local_step(params, g_w, opt, lr,
                                               self.cfg.optim)
        sel = lambda a, b: jnp.where(update_w, a, b)
        params = jax.tree.map(sel, new_params, params)
        opt = jax.tree.map(sel, new_opt, opt)

        acc = jnp.asarray(0.0) if model.is_regression \
            else accuracy(logits, by)
        new_aux = dict(client_aux, personal=personal, personal_opt=p_opt)
        return params, opt, new_aux, rnn_carry, loss, acc
