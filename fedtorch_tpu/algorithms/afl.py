"""AFL — Agnostic Federated Learning (arXiv:1902.00146).

Parity targets: ``afl_aggregation``
(comms/algorithms/federated/afl.py:9-61) and the AFL loop's dual update
(trainings/federated/afl.py:157-170):

* aggregation weights are the dual variable itself: ``w_i = lambda_i``
  (afl.py:11-14 — NOT normalized by the online count);
* each client reports its (single-step: AFL forces local_step=1,
  parameters.py:249-251) batch loss; the server ascends
  ``lambda += gamma * loss_vector`` over the online clients, projects onto
  the simplex, floors at 1e-3 and renormalizes once (afl loop:160-170 —
  same rule as DRFA's, via ops.simplex.project_simplex_floor);
* clients are sampled uniformly; lambda only drives weighting + duals.

lambda lives in the server aux [C]; the reference initializes it uniform
(nodes.py gen_aux_models).
"""
from __future__ import annotations

import jax.numpy as jnp

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.core import optim
from fedtorch_tpu.ops.simplex import project_simplex_floor


class AFL(FedAlgorithm):
    name = "afl"

    def init_server_aux(self, params, num_clients: int):
        return {"lambda": jnp.full((num_clients,), 1.0 / num_clients)}

    def client_weights(self, server_aux, online_idx, num_online_eff,
                       sizes):
        return jnp.take(server_aux["lambda"], online_idx)

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        new_params, new_opt = optim.server_step(
            server_params, payload_sum, server_opt,
            self.cfg.optim.lr_scale_at_sync, self.cfg.optim)
        # dual ascent on the online clients' losses (afl loop:160-170)
        lam = server_aux["lambda"]
        loss_vec = jnp.zeros_like(lam).at[online_idx].set(client_losses)
        lam = lam + self.cfg.federated.drfa_gamma * loss_vec
        lam = project_simplex_floor(lam, floor=1e-3)
        return new_params, new_opt, {"lambda": lam}
