"""Command-line entry point.

Parity with the reference's flag system + entry points (parameters.py
get_args ~90 flags; main.py / main_centered.py): one argparse surface
mapping onto the typed :class:`ExperimentConfig`, a ``--backend`` switch
replacing ``mpirun`` process launch (tpu = all visible TPU devices over
one mesh; cpu = virtual host mesh for debugging, the centered-mode
analog), and the train/validate/checkpoint driver loop
(federated/main.py:56-211).

Usage:
    python -m fedtorch_tpu.cli --federated true --data synthetic \
        --federated_type fedavg --num_comms 20 --num_clients 10
"""
from __future__ import annotations

import argparse
import os
import time

from fedtorch_tpu.config import (
    CLIENT_STORES, PARTICIPATION_MODES,
    CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
    FederatedConfig, LRConfig, MeshConfig, ModelConfig, OptimConfig,
    TelemetryConfig, TrainConfig,
)


def str2bool(v) -> bool:
    """parameters.py:263-280."""
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"Boolean value expected, got {v!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="fedtorch_tpu: TPU-native federated learning")
    # dataset (parameters.py:23-37)
    p.add_argument("-d", "--data", default="cifar10")
    p.add_argument("-p", "--data_dir", default="./data/")
    p.add_argument("--download", type=str2bool, default=False)
    p.add_argument("--partition_data", type=str2bool, default=True)
    p.add_argument("--augment", type=str2bool, default=None,
                   help="train-time flip+crop for image data "
                        "(default: on for the cifar family)")
    p.add_argument("--synthetic_alpha", type=float, default=0.0)
    p.add_argument("--synthetic_beta", type=float, default=0.0)
    p.add_argument("--sensitive_feature", type=int, default=9)
    # federated (parameters.py:40-110)
    p.add_argument("-f", "--federated", type=str2bool, default=False)
    p.add_argument("--num_class_per_client", type=int, default=1)
    p.add_argument("--num_comms", type=int, default=100)
    p.add_argument("--online_client_rate", type=float, default=0.1)
    p.add_argument("--federated_sync_type", default="epoch",
                   choices=["epoch", "local_step"])
    p.add_argument("--num_epochs_per_comm", type=int, default=1)
    p.add_argument("--iid_data", type=str2bool, default=True)
    p.add_argument("--federated_type", default="fedavg")
    p.add_argument("--unbalanced", type=str2bool, default=False)
    p.add_argument("--dirichlet", type=str2bool, default=False)
    p.add_argument("--fed_personal", type=str2bool, default=False)
    p.add_argument("--fed_personal_alpha", type=float, default=0.5)
    p.add_argument("--fed_adaptive_alpha", type=str2bool, default=False)
    p.add_argument("--fed_personal_test", type=str2bool, default=False)
    p.add_argument("--fedadam_beta", type=float, default=0.9)
    p.add_argument("--fedadam_tau", type=float, default=0.1)
    p.add_argument("--quantized", type=str2bool, default=False)
    p.add_argument("--quantized_bits", type=int, default=8)
    p.add_argument("--compressed", type=str2bool, default=False)
    p.add_argument("--compressed_ratio", type=float, default=1.0)
    p.add_argument("--sync_mode", default="sync",
                   choices=("sync", "async"),
                   help="server execution plane: 'sync' (default) "
                        "blocks each round on all k online clients; "
                        "'async' is the FedBuff-style buffered server "
                        "— clients train on possibly-stale snapshots, "
                        "the server commits every --async_buffer_size "
                        "staleness-weighted arrivals, and num_comms "
                        "counts COMMITS (docs/robustness.md "
                        "'Asynchronous federation')")
    p.add_argument("--async_buffer_size", type=int, default=0,
                   help="updates buffered per async commit (FedBuff's "
                        "m); 0 = auto: max(1, k_online // 2)")
    p.add_argument("--async_concurrency", type=int, default=0,
                   help="concurrently-training clients in async mode "
                        "(FedBuff's M_c); 0 = auto: k_online")
    p.add_argument("--staleness_weight", default="poly",
                   choices=("const", "poly", "inv"),
                   help="async staleness damping s(tau) for an update "
                        "tau commits stale: poly=(1+tau)^-exponent "
                        "(FedBuff default), inv=1/(1+tau), const=1; "
                        "normalized to mean 1 per commit")
    p.add_argument("--staleness_exponent", type=float, default=0.5,
                   help="exponent of the 'poly' staleness weight")
    p.add_argument("--snapshot_ring", type=int, default=8,
                   help="async snapshot ring depth: past commit "
                        "versions kept resident for in-flight clients "
                        "(memory: ring x (params + server aux))")
    p.add_argument("--federated_drfa", type=str2bool, default=False)
    p.add_argument("--drfa_gamma", type=float, default=0.1)
    p.add_argument("--perfedavg_beta", type=float, default=0.001)
    p.add_argument("--fedprox_mu", type=float, default=0.002)
    p.add_argument("--perfedme_lambda", type=float, default=15.0)
    p.add_argument("--qffl_q", type=float, default=0.0)
    # model (parameters.py:113-115, 180-194)
    p.add_argument("-a", "--arch", default="mlp")
    p.add_argument("--norm", default="bn", choices=["bn", "gn"])
    p.add_argument("--drop_rate", type=float, default=0.0)
    p.add_argument("--densenet_growth_rate", type=int, default=12)
    p.add_argument("--densenet_bc_mode", type=str2bool, default=False)
    p.add_argument("--densenet_compression", type=float, default=0.5)
    p.add_argument("--wideresnet_widen_factor", type=int, default=4)
    p.add_argument("--mlp_num_layers", type=int, default=2)
    p.add_argument("--mlp_hidden_size", type=int, default=500)
    p.add_argument("--rnn_seq_len", type=int, default=50)
    p.add_argument("--rnn_hidden_size", type=int, default=50)
    p.add_argument("--vocab_size", type=int, default=86)
    p.add_argument("--moe_experts", type=int, default=0,
                   help="transformer arch: >0 swaps block MLPs for a "
                        "Switch-MoE with this many experts. With "
                        "--moe_capacity_factor 0 dispatch is exact but "
                        "costs E x the dense MLP FLOPs")
    p.add_argument("--moe_capacity_factor", type=float, default=0.0,
                   help="0 = exact dense MoE dispatch (E x FLOPs); >0 "
                        "= sparse Switch dispatch, per-expert capacity "
                        "ceil(cf*tokens/E), cf x FLOPs, over-capacity "
                        "tokens drop to the residual (try 1.25)")
    p.add_argument("--moe_aux_weight", type=float, default=0.0,
                   help="Switch load-balance aux-loss weight (0.01 in "
                        "the paper); 0 disables and the gate can "
                        "collapse onto one expert")
    p.add_argument("--attention", default="auto",
                   choices=("auto", "dense", "flash"),
                   help="transformer attention backend: 'flash' = fused "
                        "online-softmax pallas kernel on TPU (exact; "
                        "dense fallback off-TPU); 'auto' (default) "
                        "picks flash only at sequence lengths where the "
                        "on-chip A/B measured it winning (T >= 4096 — "
                        "FLASH_TRAIN.json's T=2048 window regressed "
                        "0.68x)")
    p.add_argument("--conv_impl", default="auto",
                   choices=("auto", "conv", "matmul"),
                   help="conv-family lowering (resnet/wideresnet/"
                        "densenet/cnn): 'matmul' = im2col + one batched "
                        "matmul per layer (identical math; fills the "
                        "MXU differently under per-client weights — "
                        "see docs/performance.md)")
    # training scheme (parameters.py:118-141)
    p.add_argument("--stop_criteria", default="epoch")
    p.add_argument("--num_epochs", type=int, default=None)
    p.add_argument("--num_iterations", type=int, default=None)
    p.add_argument("--local_step", type=int, default=1)
    p.add_argument("--local_step_warmup_type", default=None)
    p.add_argument("--local_step_warmup_period", type=int, default=None)
    p.add_argument("--local_step_warmup_per_interval", type=str2bool,
                   default=False)
    p.add_argument("--turn_on_local_step_from", type=int, default=None)
    p.add_argument("--turn_off_local_step_from", type=int, default=None)
    p.add_argument("--avg_model", type=str2bool, default=True)
    p.add_argument("--reshuffle_per_epoch", type=str2bool, default=False)
    p.add_argument("-b", "--batch_size", type=int, default=50)
    p.add_argument("--data_plane", default="device",
                   choices=("device", "stream"),
                   help="federated data plane: 'device' keeps every "
                        "client's rows resident in device memory "
                        "(population capped by HBM); 'stream' keeps "
                        "the client store on the host and prefetches "
                        "each round's packed online-client rows one "
                        "round ahead, overlapping the transfer with "
                        "the previous round's compute "
                        "(docs/performance.md 'Streaming data plane')")
    p.add_argument("--data_store", default="ram",
                   choices=CLIENT_STORES,
                   help="client-store backend on the stream plane: "
                        "'ram' (default) holds the [C, n_max, ...] "
                        "population in host memory; 'mmap' memory-maps "
                        "a sharded on-disk store built by "
                        "save_client_store — host residency is "
                        "O(touched rows), enabling million-client "
                        "populations (docs/performance.md 'The "
                        "million-client store')")
    p.add_argument("--data_store_dir", default="",
                   help="directory holding the mmap store's "
                        "manifest.json + shard files (required with "
                        "--data_store mmap)")
    p.add_argument("--participation_mode", default="perm",
                   choices=PARTICIPATION_MODES,
                   help="per-round client sampling: 'perm' (default, "
                        "legacy-bitwise) draws a [C] random score "
                        "vector per selection; 'sparse' draws O(k) "
                        "without-replacement ids and never "
                        "materializes a [C] array — required reading "
                        "at million-client populations "
                        "(docs/performance.md)")
    p.add_argument("--growing_batch_size", type=str2bool, default=False)
    p.add_argument("--base_batch_size", type=int, default=None)
    p.add_argument("--max_batch_size", type=int, default=0)
    # learning rate (parameters.py:144-166)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--lr_schedule_scheme", default=None)
    p.add_argument("--lr_change_epochs", default=None)
    p.add_argument("--lr_fields", default=None)
    p.add_argument("--lr_scale_indicators", default=None)
    p.add_argument("--lr_scaleup", type=str2bool, default=False)
    p.add_argument("--lr_scaleup_type", default="linear")
    p.add_argument("--lr_scale_at_sync", type=float, default=1.0)
    p.add_argument("--lr_warmup", type=str2bool, default=False)
    p.add_argument("--lr_warmup_epochs", type=int, default=5)
    p.add_argument("--lr_decay", type=float, default=10.0)
    p.add_argument("--lr_onecycle_low", type=float, default=0.15)
    p.add_argument("--lr_onecycle_high", type=float, default=3.0)
    p.add_argument("--lr_onecycle_extra_low", type=float, default=0.0015)
    p.add_argument("--lr_onecycle_num_epoch", type=int, default=46)
    p.add_argument("--lr_gamma", type=float, default=None)
    p.add_argument("--lr_mu", type=float, default=None)
    p.add_argument("--lr_alpha", type=float, default=None)
    # optimizer (parameters.py:168-183)
    p.add_argument("--optimizer", default="sgd")
    p.add_argument("--in_momentum", type=str2bool, default=False)
    p.add_argument("--in_momentum_factor", type=float, default=0.9)
    p.add_argument("--out_momentum", type=str2bool, default=False)
    p.add_argument("--out_momentum_factor", type=float, default=None)
    p.add_argument("--use_nesterov", type=str2bool, default=False)
    p.add_argument("--weight_decay", type=float, default=5e-4)
    p.add_argument("--correct_wd", type=str2bool, default=False)
    p.add_argument("--wd_skip_norm_bias", type=str2bool, default=False,
                   help="exclude norm scale/shift and bias params from "
                        "weight decay (standard practice); default "
                        "False = the reference's uniform decay, which "
                        "parity runs must keep")
    # misc / checkpoint (parameters.py:196-222)
    p.add_argument("--manual_seed", type=int, default=6)
    p.add_argument("--per_class_acc", type=str2bool, default=False)
    p.add_argument("--evaluate", "-e", type=str2bool, default=False)
    p.add_argument("--eval_freq", type=int, default=1)
    p.add_argument("--summary_freq", type=int, default=10)
    p.add_argument("--debug", type=str2bool, default=True)
    p.add_argument("--resume", default=None)
    p.add_argument("--checkpoint_index", default=None)
    p.add_argument("-c", "--checkpoint", default="./checkpoint/")
    p.add_argument("--run_dir", default=None,
                   help="use this exact directory for checkpoints/logs "
                        "instead of a hyperparam+timestamp subfolder of "
                        "--checkpoint; required for elastic restarts "
                        "(run_elastic/supervise relaunch with "
                        "--resume <this dir>)")
    p.add_argument("--save_all_models", type=str2bool, default=False)
    p.add_argument("--save_some_models", default="1,29,59")
    p.add_argument("--checkpoint_keep_last_n", type=int, default=0,
                   help="garbage-collect all but the newest N per-round "
                        "checkpoint_r{N}.ckpt keeps (0 = keep all; "
                        "model_best/checkpoint.ckpt never collected)")
    p.add_argument("--async_checkpoint", action="store_true",
                   help="write checkpoints from a background thread "
                        "(atomic) so rounds never block on disk")
    p.add_argument("--check_model_at_sync", type=str2bool, default=False)
    p.add_argument("--track_model_aggregation", type=str2bool,
                   default=False)
    p.add_argument("--log_dir", default="./logdir/")
    p.add_argument("--experiment", default=None)
    # robustness: chaos injection / update guards / round supervisor
    # (docs/robustness.md; no reference analog — it is fail-stop)
    p.add_argument("--fault_client_drop_rate", type=float, default=0.0,
                   help="per-round probability an online client crashes "
                        "mid-round (masked out of aggregation, weights "
                        "renormalized over survivors)")
    p.add_argument("--fault_straggler_rate", type=float, default=0.0,
                   help="per-round probability an online client is a "
                        "straggler (completes only a fraction of its "
                        "local steps)")
    p.add_argument("--fault_straggler_step_frac", type=float, default=0.5,
                   help="fraction of the step budget a straggler "
                        "completes before missing the deadline")
    p.add_argument("--fault_nan_inject_rate", type=float, default=0.0,
                   help="per-round probability an online client uploads "
                        "a NaN-poisoned delta (exercises the guards)")
    p.add_argument("--fault_byzantine_rate", type=float, default=0.0,
                   help="per-round probability an online client is an "
                        "ADVERSARY: its upload is replaced by a crafted "
                        "finite vector that passes the benign-fault "
                        "guards (the robust_agg layer is the defense)")
    p.add_argument("--fault_byzantine_mode", default="sign_flip",
                   choices=("sign_flip", "scale", "zero", "gauss",
                            "collude"),
                   help="attack shape: sign_flip=-scale*delta, "
                        "scale=norm inflation, zero=free-rider, "
                        "gauss=pure noise, collude=all byzantine "
                        "clients submit the identical "
                        "-scale*(honest mean) update")
    p.add_argument("--fault_byzantine_scale", type=float, default=1.0,
                   help="attack magnitude multiplier")
    p.add_argument("--robust_agg", default="mean",
                   choices=("mean", "median", "trimmed_mean", "krum",
                            "multikrum", "norm_bound"),
                   help="aggregation rule at the round/commit seam "
                        "(robustness/aggregators.py): 'mean' (default) "
                        "is the pre-robust weighted sum, bitwise-"
                        "identical; median/trimmed_mean are "
                        "coordinate-wise (Yin et al. 2018), "
                        "krum/multikrum pairwise-distance selection "
                        "(Blanchard et al. 2017), norm_bound centered "
                        "clipping with a server momentum "
                        "(Karimireddy et al. 2021). Composes after "
                        "guards/chaos and async staleness weights on "
                        "BOTH federation planes")
    p.add_argument("--robust_trim_frac", type=float, default=0.1,
                   help="trimmed_mean's per-end trim fraction and "
                        "krum's assumed byzantine fraction")
    p.add_argument("--robust_norm_tau", type=float, default=1.5,
                   help="norm_bound clip radius as a multiple of the "
                        "median distance-to-momentum (1.5: adversaries "
                        "clamp hard, clustered honest updates barely)")
    p.add_argument("--guard_updates", type=str2bool, default=False,
                   help="screen client deltas before aggregation: "
                        "reject non-finite, reject/clip norm-exploded")
    p.add_argument("--guard_norm_multiplier", type=float, default=10.0,
                   help="norm threshold as a multiple of the round's "
                        "median surviving delta norm")
    p.add_argument("--guard_mode", default="reject",
                   choices=("reject", "clip"))
    p.add_argument("--supervisor", type=str2bool, default=False,
                   help="wrap the round loop with divergence detection, "
                        "snapshot rollback, retry with backoff, and "
                        "round skipping (docs/robustness.md)")
    p.add_argument("--supervisor_loss_blowup", type=float, default=0.0,
                   help=">0: mean online loss above this multiple of "
                        "the running loss EMA counts as divergence")
    p.add_argument("--supervisor_max_retries", type=int, default=2)
    p.add_argument("--supervisor_backoff_base", type=float, default=0.5)
    p.add_argument("--host_fault_seams", default="",
                   help="comma-separated host-plane fault seams to arm "
                        "(robustness/host_chaos.py): stream.gather, "
                        "stream.delay, stream.h2d, ckpt.write, "
                        "ckpt.torn, telemetry.write, native.load. "
                        "Faults fire deterministically from "
                        "--host_fault_seed, so every drill replays "
                        "(docs/robustness.md 'Host plane')")
    p.add_argument("--host_fault_rate", type=float, default=0.25,
                   help="per-check fire probability at each armed "
                        "host seam")
    p.add_argument("--host_fault_seed", type=int, default=0,
                   help="seed of the pure-hash fault schedule")
    p.add_argument("--host_fault_delay_s", type=float, default=0.02,
                   help="stall injected per fire at the stream.delay "
                        "seam (seconds)")
    p.add_argument("--host_fault_max", type=int, default=0,
                   help=">0 caps total fires per seam (e.g. "
                        "host_retry_max+1 at rate 1.0 kills the stream "
                        "producer exactly once for the rebuild drill); "
                        "0 = uncapped")
    p.add_argument("--host_retry_max", type=int, default=3,
                   help="bounded retry budget at each host seam "
                        "(stream gather/H2D, checkpoint writes) and "
                        "the producer-rebuild budget per feed pop "
                        "(robustness/host_recovery.py)")
    p.add_argument("--host_retry_backoff_s", type=float, default=0.05,
                   help="first host-seam retry delay; doubles per "
                        "attempt (capped at 2s)")
    p.add_argument("--watchdog_timeout_s", type=float, default=0.0,
                   help=">0 arms the stall watchdog: if no round "
                        "completes within this many seconds (a dead "
                        "peer blocking a DCN collective), dump thread "
                        "stacks to the run log and exit with the "
                        "restartable code 75 so the restart harness "
                        "cycles the job (docs/robustness.md)")
    # deployment-realism availability plane + round lifecycle
    # (robustness/availability.py; docs/robustness.md "Deployment
    # realism")
    p.add_argument("--avail_model", default="default",
                   choices=("default", "trace"),
                   help="client availability model driving async "
                        "arrival delays and the sync round lifecycle: "
                        "'default' reproduces the legacy straggler-"
                        "knob draws bitwise; 'trace' adds FedScale-"
                        "style device speed classes and diurnal on/off "
                        "curves from an in-tree synthetic trace")
    p.add_argument("--avail_dropout_rate", type=float, default=0.0,
                   help="per-dispatch probability a client drops "
                        "mid-round (async: arrival discarded and slot "
                        "re-dispatched; sync: local state rolled back "
                        "and update masked)")
    p.add_argument("--avail_diurnal_period", type=int, default=0,
                   help="trace model only: rounds per diurnal cycle "
                        "(0 = flat availability)")
    p.add_argument("--over_select_frac", type=float, default=1.0,
                   help=">1 over-selects ceil(frac*k) clients per sync "
                        "round and closes the round on the first k "
                        "arrivals; late survivors are deadline-masked "
                        "through the accept seam")
    p.add_argument("--avail_quorum_frac", type=float, default=0.0,
                   help=">0: a sync round whose accepted cohort falls "
                        "below ceil(frac*k) is sub-quorum — see "
                        "--avail_quorum_action")
    p.add_argument("--avail_quorum_action", default="degrade",
                   choices=("degrade", "abort"),
                   help="sub-quorum handling: 'degrade' commits the "
                        "renormalized partial cohort (counted + "
                        "evented); 'abort' escalates to the supervisor "
                        "retry/skip path (requires --supervisor)")
    p.add_argument("--dp_noise_multiplier", type=float, default=0.0,
                   help="> 0 arms DP-FedAvg server aggregation: "
                        "per-client L2 clip to --dp_clip_norm then "
                        "Gaussian noise z*clip/k on the weighted "
                        "estimate (0 = off, program byte-identical)")
    p.add_argument("--dp_clip_norm", type=float, default=1.0,
                   help="per-client L2 clip radius for the DP stage")
    p.add_argument("--dp_epsilon_budget", type=float, default=0.0,
                   help="> 0 caps the RDP-accounted epsilon spend at "
                        "--dp_delta; exhaustion handled per "
                        "--dp_budget_action (0 = unlimited; spend is "
                        "still accounted and logged)")
    p.add_argument("--dp_delta", type=float, default=1e-5,
                   help="target delta for the (eps, delta) accounting")
    p.add_argument("--dp_budget_action", default="stop",
                   choices=("stop", "degrade"),
                   help="epsilon-budget exhaustion: 'stop' ends the "
                        "run cleanly at the last affordable round; "
                        "'degrade' continues noise-free (counted + "
                        "evented, health intent 'degraded')")
    # device / mesh (replaces parameters.py:225-236 MPI block)
    p.add_argument("--backend", default=None,
                   help="jax platform: tpu|cpu|None(auto)")
    p.add_argument("--num_devices", type=int, default=None)
    p.add_argument("--num_workers", "-j", "--world_size", type=int,
                   default=10, dest="num_workers",
                   help="number of clients/workers (MPI world size)")
    p.add_argument("--coordinator_address", default=None,
                   help="multi-host DCN coordinator (host:port)")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--compute_dtype", default="float32",
                   choices=("float32", "bfloat16"),
                   help="matmul/conv compute dtype (params stay f32); "
                        "bfloat16 feeds the MXU at full rate")
    p.add_argument("--scan_unroll", type=int, default=1,
                   help=">1 unrolls the local-step scan so XLA can "
                        "software-pipeline consecutive steps")
    p.add_argument("--remat", action="store_true",
                   help="per-block rematerialization for resnet/"
                        "transformer: ~1.33x FLOPs for depth-independent "
                        "activation memory")
    p.add_argument("--client_fusion", default="auto",
                   choices=("auto", "vmap", "fused"),
                   help="client-axis execution strategy for the round "
                        "program's model compute: 'fused' packs the k "
                        "online clients into one feature_group_count=k "
                        "grouped conv per layer (k x the MXU lanes; "
                        "resnet-cifar/cnn + norm=bn, 1-device mesh); "
                        "'auto' currently keeps 'vmap' pending the "
                        "on-chip A/B (docs/performance.md)")
    p.add_argument("--client_shards", type=int, default=0,
                   help="pod-scale client-axis sharding: shard the k "
                        "online clients over this many device groups "
                        "(power of two <= 64 dividing both the device "
                        "count and k) with exactly one cross-shard "
                        "all-reduce at the aggregation seam; 0 = off "
                        "(legacy program), 1 = the unsharded bitwise "
                        "twin (docs/performance.md 'Pod-scale round "
                        "programs')")
    p.add_argument("--allow_train_as_test", type=str2bool, default=False,
                   help="permit dataset loaders with a missing test "
                        "split (EMNIST mirrors) to substitute a slice "
                        "of TRAIN data as the test set; off by default "
                        "because it silently reports train accuracy "
                        "as test accuracy")
    # observability (fedtorch_tpu.telemetry, docs/observability.md)
    p.add_argument("--telemetry", default="default",
                   choices=("off", "default", "debug"),
                   help="run telemetry: 'default' writes schema-"
                        "versioned metrics.jsonl/events.jsonl, a "
                        "Perfetto-loadable trace.json of host spans, "
                        "and the atomically-replaced health.json to "
                        "the run dir (measured <= 1% round overhead, "
                        "TELEMETRY_AB.json; zero added device syncs); "
                        "'debug' re-exports the trace every 25 rounds; "
                        "'off' disables everything "
                        "(docs/observability.md)")
    p.add_argument("--cost_capture_scan_rounds", type=int, default=0,
                   help="> 0 additionally AOT-lowers the scan-of-R "
                        "round-program twin for the active data "
                        "source into program_costs.json at the "
                        "one-shot cost capture (rounds_scan[R] on "
                        "the device plane, rounds_stream_scan[R] — "
                        "the scanned streamed program — on the "
                        "stream plane); 0 captures the per-round "
                        "programs only. Ignored (with a logged note) "
                        "under --sync_mode async, whose commit plane "
                        "refuses the scan dispatch")
    p.add_argument("--cohort_stats", type=str2bool, default=False,
                   help="federation-plane cohort statistics "
                        "(docs/observability.md 'Federation plane'): "
                        "the aggregation seam additionally emits "
                        "per-client accept/selection masks, the "
                        "robust rule's suspicion scores, per-job "
                        "staleness, update-norm quantiles and the "
                        "cosine-dispersion heterogeneity gauge — all "
                        "riding the round loop's one batched fetch "
                        "into per-round gauges and the per-client "
                        "client_ledger.json. Off (default) the round/"
                        "commit program is byte-identical to the "
                        "stats-free engine; on, it traces once and "
                        "trajectories stay bitwise-identical")
    p.add_argument("--ledger_sketch_budget", type=int, default=65536,
                   help="population threshold/budget of the per-"
                        "client ledger: dense numpy counters at "
                        "num_clients <= budget, count-min "
                        "participation sketch + suspicion top-K "
                        "above it — ledger memory stays "
                        "O(min(C, budget)) at C >= 1e6")
    p.add_argument("--anomaly_zscore", type=float, default=6.0,
                   help="EWMA z-score threshold of the observe-only "
                        "anomaly detector over the metrics rows "
                        "(loss, cohort dispersion, guard-reject "
                        "rate, staleness) — emits anomaly.detected "
                        "events, never drives control flow; 0 "
                        "disables")
    return p


def args_to_config(args) -> ExperimentConfig:
    cfg = ExperimentConfig(
        data=DataConfig(
            dataset=args.data, data_dir=args.data_dir,
            partition_data=args.partition_data, iid=args.iid_data,
            num_class_per_client=args.num_class_per_client,
            unbalanced=args.unbalanced, dirichlet=args.dirichlet,
            synthetic_alpha=args.synthetic_alpha,
            synthetic_beta=args.synthetic_beta,
            sensitive_feature=args.sensitive_feature,
            data_plane=args.data_plane,
            store=args.data_store,
            store_dir=args.data_store_dir,
            batch_size=args.batch_size,
            growing_batch_size=args.growing_batch_size,
            base_batch_size=args.base_batch_size,
            max_batch_size=args.max_batch_size,
            reshuffle_per_epoch=args.reshuffle_per_epoch,
            augment=args.augment,
            allow_train_as_test=args.allow_train_as_test),
        federated=FederatedConfig(
            federated=args.federated, num_clients=args.num_workers,
            num_comms=args.num_comms,
            online_client_rate=args.online_client_rate,
            sync_type=args.federated_sync_type,
            num_epochs_per_comm=args.num_epochs_per_comm,
            sync_mode=args.sync_mode,
            participation_mode=args.participation_mode,
            async_buffer_size=args.async_buffer_size,
            async_concurrency=args.async_concurrency,
            staleness_weight=args.staleness_weight,
            staleness_exponent=args.staleness_exponent,
            snapshot_ring=args.snapshot_ring,
            algorithm=args.federated_type, personal=args.fed_personal,
            personal_alpha=args.fed_personal_alpha,
            adaptive_alpha=args.fed_adaptive_alpha,
            personal_test=args.fed_personal_test,
            fedadam_beta=args.fedadam_beta, fedadam_tau=args.fedadam_tau,
            quantized=args.quantized, quantized_bits=args.quantized_bits,
            compressed=args.compressed,
            compressed_ratio=args.compressed_ratio,
            drfa=args.federated_drfa, drfa_gamma=args.drfa_gamma,
            perfedavg_beta=args.perfedavg_beta,
            fedprox_mu=args.fedprox_mu,
            perfedme_lambda=args.perfedme_lambda, qffl_q=args.qffl_q),
        model=ModelConfig(
            arch=args.arch, norm=args.norm, drop_rate=args.drop_rate,
            densenet_growth_rate=args.densenet_growth_rate,
            densenet_bc_mode=args.densenet_bc_mode,
            densenet_compression=args.densenet_compression,
            wideresnet_widen_factor=args.wideresnet_widen_factor,
            mlp_num_layers=args.mlp_num_layers,
            mlp_hidden_size=args.mlp_hidden_size,
            rnn_seq_len=args.rnn_seq_len,
            rnn_hidden_size=args.rnn_hidden_size,
            vocab_size=args.vocab_size,
            moe_experts=args.moe_experts,
            moe_capacity_factor=args.moe_capacity_factor,
            moe_aux_weight=args.moe_aux_weight,
            attention=args.attention,
            conv_impl=args.conv_impl),
        optim=OptimConfig(
            optimizer=args.optimizer, lr=args.lr,
            in_momentum=args.in_momentum,
            in_momentum_factor=args.in_momentum_factor,
            out_momentum=args.out_momentum,
            out_momentum_factor=args.out_momentum_factor,
            use_nesterov=args.use_nesterov,
            weight_decay=args.weight_decay, correct_wd=args.correct_wd,
            wd_skip_norm_bias=args.wd_skip_norm_bias,
            lr_scale_at_sync=args.lr_scale_at_sync),
        lr_schedule=LRConfig(
            schedule_scheme=args.lr_schedule_scheme,
            lr_change_epochs=args.lr_change_epochs,
            lr_fields=args.lr_fields,
            lr_scale_indicators=args.lr_scale_indicators,
            scaleup=args.lr_scaleup, scaleup_type=args.lr_scaleup_type,
            warmup=args.lr_warmup, warmup_epochs=args.lr_warmup_epochs,
            decay=args.lr_decay, onecycle_low=args.lr_onecycle_low,
            onecycle_high=args.lr_onecycle_high,
            onecycle_extra_low=args.lr_onecycle_extra_low,
            onecycle_num_epoch=args.lr_onecycle_num_epoch,
            gamma=args.lr_gamma, mu=args.lr_mu, alpha=args.lr_alpha),
        train=TrainConfig(
            stop_criteria=args.stop_criteria, num_epochs=args.num_epochs,
            num_iterations=args.num_iterations,
            local_step=args.local_step,
            local_step_warmup_type=args.local_step_warmup_type,
            local_step_warmup_period=args.local_step_warmup_period,
            local_step_warmup_per_interval=(
                args.local_step_warmup_per_interval),
            turn_on_local_step_from=args.turn_on_local_step_from,
            turn_off_local_step_from=args.turn_off_local_step_from,
            avg_model=args.avg_model, manual_seed=args.manual_seed,
            evaluate=args.evaluate, eval_freq=args.eval_freq,
            summary_freq=args.summary_freq,
            per_class_acc=args.per_class_acc),
        checkpoint=CheckpointConfig(
            checkpoint_dir=args.checkpoint, run_dir=args.run_dir,
            resume=args.resume,
            checkpoint_index=args.checkpoint_index,
            save_all_models=args.save_all_models,
            save_some_models=args.save_some_models,
            keep_last_n=args.checkpoint_keep_last_n,
            async_save=args.async_checkpoint,
            log_dir=args.log_dir, debug=args.debug,
            check_model_at_sync=args.check_model_at_sync,
            track_model_aggregation=args.track_model_aggregation),
        mesh=MeshConfig(
            backend=args.backend, num_devices=args.num_devices,
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes, process_id=args.process_id,
            compute_dtype=args.compute_dtype,
            scan_unroll=args.scan_unroll, remat=args.remat,
            client_fusion=args.client_fusion,
            client_shards=args.client_shards),
        telemetry=TelemetryConfig(
            level=args.telemetry,
            cost_capture_scan_rounds=args.cost_capture_scan_rounds,
            cohort_stats=args.cohort_stats,
            ledger_sketch_budget=args.ledger_sketch_budget,
            anomaly_zscore=args.anomaly_zscore),
        fault=FaultConfig(
            client_drop_rate=args.fault_client_drop_rate,
            straggler_rate=args.fault_straggler_rate,
            straggler_step_frac=args.fault_straggler_step_frac,
            nan_inject_rate=args.fault_nan_inject_rate,
            byzantine_rate=args.fault_byzantine_rate,
            byzantine_mode=args.fault_byzantine_mode,
            byzantine_scale=args.fault_byzantine_scale,
            robust_agg=args.robust_agg,
            robust_trim_frac=args.robust_trim_frac,
            robust_norm_tau=args.robust_norm_tau,
            guard_updates=args.guard_updates,
            guard_norm_multiplier=args.guard_norm_multiplier,
            guard_mode=args.guard_mode,
            supervisor=args.supervisor,
            loss_blowup_factor=args.supervisor_loss_blowup,
            max_retries=args.supervisor_max_retries,
            backoff_base_s=args.supervisor_backoff_base,
            host_fault_seams=args.host_fault_seams,
            host_fault_rate=args.host_fault_rate,
            host_fault_seed=args.host_fault_seed,
            host_fault_delay_s=args.host_fault_delay_s,
            host_fault_max=args.host_fault_max,
            host_retry_max=args.host_retry_max,
            host_retry_backoff_s=args.host_retry_backoff_s,
            watchdog_timeout_s=args.watchdog_timeout_s,
            avail_model=args.avail_model,
            avail_dropout_rate=args.avail_dropout_rate,
            avail_diurnal_period=args.avail_diurnal_period,
            over_select_frac=args.over_select_frac,
            avail_quorum_frac=args.avail_quorum_frac,
            avail_quorum_action=args.avail_quorum_action,
            dp_noise_multiplier=args.dp_noise_multiplier,
            dp_clip_norm=args.dp_clip_norm,
            dp_epsilon_budget=args.dp_epsilon_budget,
            dp_delta=args.dp_delta,
            dp_budget_action=args.dp_budget_action),
        experiment=args.experiment,
    )
    return cfg.finalize()


def run_experiment(cfg: ExperimentConfig,
                   download: bool = False,
                   round_callback=None) -> dict:
    """The driver loop (main.py dispatch + federated/main.py:56-211).

    ``round_callback(r, trainer, server, clients, metrics)`` (optional)
    fires after every completed federated round — the hook the
    preemption/kill-drill harness uses to fingerprint rounds.

    Process lifecycle (docs/robustness.md "Process lifecycle"):
    SIGTERM/SIGINT/SIGUSR1 request a drain — the loop finishes the
    round in flight, agrees on the stop across hosts, writes a final
    checkpoint, flushes the async writer, and the result carries
    ``preempted=True`` (:func:`main` converts that into the restartable
    exit code 75). ``fault.watchdog_timeout_s > 0`` additionally arms a
    stall watchdog that converts a wedged pod into the same exit code.
    """
    import jax
    import jax.numpy as jnp

    from fedtorch_tpu.utils import enable_compile_cache
    if cfg.checkpoint.resume is None:
        enable_compile_cache()
    # else: resumed runs bypass the persistent compilation cache. On
    # cpu jaxlib 0.4.36, executing the CACHE-DESERIALIZED round
    # executable on restored (post-``maybe_resume``) state corrupts
    # the donated output buffers — bitwise-correct losses but garbage
    # aggregated params on the first post-resume round, then a heap-
    # corruption abort at exit; ~50% reproducible in the kill drill
    # (tests/test_kill_drill.py), 0% with the cache bypassed. A
    # restarted run recompiles (seconds on CPU, ~40-50s on TPU) —
    # correctness over restart latency until the jaxlib bug is fixed.

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import (
        FederatedTrainer, build_local_sgd, evaluate, evaluate_personal,
        init_multihost,
    )
    from fedtorch_tpu.utils import (
        PhaseTimer, RunLogger, aggregation_tracking, init_checkpoint_dir,
        maybe_resume, model_norms, save_checkpoint,
    )

    if cfg.mesh.backend == "cpu" or os.environ.get(
            "JAX_PLATFORMS", "").strip().lower() == "cpu":
        # the env var alone is not enough: a site hook may have already
        # overridden jax_platforms to a TPU proxy at interpreter start
        jax.config.update("jax_platforms", "cpu")
    init_multihost(cfg.mesh)

    ckpt_dir = init_checkpoint_dir(cfg)
    logger = RunLogger(ckpt_dir, debug=cfg.checkpoint.debug)
    logger.log_args(cfg)
    logger.log(f"devices: {jax.devices()}")
    timer = PhaseTimer()

    # unified run telemetry (docs/observability.md): structured
    # metrics/events + host spans + machine-readable health, written to
    # the run dir. Host-only: every value it records is either a host
    # counter or comes from the loop's ONE batched scalar fetch below —
    # zero added device syncs, traced programs untouched.
    from fedtorch_tpu.telemetry import Telemetry
    tel = Telemetry(
        ckpt_dir, level=cfg.telemetry.level,
        process_index=jax.process_index(),
        run_meta={
            "algorithm": cfg.effective_algorithm,
            "dataset": cfg.data.dataset, "arch": cfg.model.arch,
            "sync_mode": cfg.federated.sync_mode,
            "data_plane": cfg.data.data_plane,
            "num_clients": cfg.federated.num_clients,
            "num_comms": cfg.federated.num_comms,
            "experiment": cfg.experiment,
        },
        max_span_events=cfg.telemetry.max_span_events)
    tel.install()
    tel.health_update("starting")

    # host-plane chaos + self-healing (docs/robustness.md "Host
    # plane"): the recovery ledger is ALWAYS installed — real host
    # faults (a full disk, a gather hiccup) retry and count whether or
    # not a drill is armed; the seeded injector only when
    # --host_fault_seams named seams. Both are host-only: no traced
    # program changes, no device syncs.
    from fedtorch_tpu.robustness import host_chaos, host_recovery
    recovery = host_recovery.HostRecovery(
        policy=host_recovery.RetryPolicy(
            max_retries=cfg.fault.host_retry_max,
            backoff_base_s=cfg.fault.host_retry_backoff_s)).install()
    injector = host_chaos.HostFaultInjector.from_config(cfg.fault)
    if injector is not None:
        injector.install()
        logger.log("host chaos armed: seams="
                   f"{','.join(sorted(injector.seams))} "
                   f"rate={injector.rate} seed={injector.seed}")

    def _uninstall_host_plane():
        # paired with every tel.close(): the active injector/ledger
        # must not leak past this run into a library caller's next one
        if injector is not None:
            injector.uninstall()
        recovery.uninstall()

    # everything from data build through trainer/handler
    # construction can raise (dataset IO, the async/stream
    # gate matrix, resume incompatibility): the active
    # telemetry must not leak past this run into a library
    # caller's next one
    try:
        timer.start("data")
        with tel.span("data.build"):
            fed_data = build_federated_data(cfg, download=download)
            model = define_model(cfg, batch_size=cfg.data.batch_size)
        timer.stop("data")

        rng = jax.random.key(cfg.train.manual_seed)

        if not cfg.federated.federated:
            # local-SGD mode: flatten the per-worker shards back into one
            # training set and IID-repartition across workers
            import numpy as np
            try:
                splits_x = np.asarray(fed_data.train.x).reshape(
                    (-1,) + fed_data.train.x.shape[2:])
                splits_y = np.asarray(fed_data.train.y).reshape(-1)
                trainer = build_local_sgd(cfg, model, splits_x, splits_y)
                server, clients, history = trainer.fit(rng)
                res = jax.device_get(evaluate(model, server.params,
                                              fed_data.test_x,
                                              fed_data.test_y))
                logger.log_val(len(history), "test", float(res.loss),
                               float(res.top1), float(res.top5))
                tel.health_update("complete", round_idx=len(history))
            finally:
                _uninstall_host_plane()
                tel.close()
            return {"test_top1": float(res.top1), "rounds": len(history)}

        algorithm = make_algorithm(cfg)
        if cfg.federated.sync_mode == "async":
            # the async commit plane (docs/robustness.md "Asynchronous
            # federation"): run_round executes one COMMIT and server.round
            # counts commit versions, so the loop below — checkpointing,
            # eval cadence, preemption drain, supervisor — runs unchanged
            from fedtorch_tpu.async_plane import AsyncFederatedTrainer
            trainer = AsyncFederatedTrainer(cfg, model, algorithm,
                                            fed_data.train,
                                            val_data=fed_data.val)
        else:
            trainer = FederatedTrainer(cfg, model, algorithm, fed_data.train,
                                       val_data=fed_data.val)
        server, clients = trainer.init_state(rng)
        server, clients, best_prec1, resumed = maybe_resume(
            cfg.checkpoint.resume, server, clients, cfg,
            cfg.checkpoint.checkpoint_index)
        if resumed:
            logger.log("resumed from round "
                       f"{int(jax.device_get(server.round))}")

        save_rounds = tuple(
            int(x) for x in cfg.checkpoint.save_some_models.split(","))
        async_ckpt = None
        if cfg.checkpoint.async_save:
            from fedtorch_tpu.utils import AsyncCheckpointer
            async_ckpt = AsyncCheckpointer()
        saver = async_ckpt.save if async_ckpt is not None else save_checkpoint
        last_saved_round = None
        lost_at_save = 0
        supervisor = None
        run_round = trainer.run_round
        if cfg.fault.supervisor:
            from fedtorch_tpu.robustness import RoundSupervisor
            supervisor = RoundSupervisor(trainer, checkpoint_dir=ckpt_dir,
                                         logger=logger)
            run_round = supervisor.run_round
        # process lifecycle: signal-driven drain + stall watchdog
        # (robustness/preemption.py, robustness/watchdog.py). The stop
        # decision is SPMD-agreed via the per-round scalar fetch; the
        # watchdog is host-only and off by default (watchdog_timeout_s=0).
        from fedtorch_tpu.robustness import PreemptionHandler, StallWatchdog
        from fedtorch_tpu.robustness.guards import (
            all_rejected_scalars as _all_rejected,
        )
        preempt = PreemptionHandler(logger=logger)
        preempt.install()
        trainer.attach_stop_signal(lambda: preempt.stop_requested)
        # NOTE for operators: the timeout must comfortably exceed the
        # worst-case compile + round + eval + checkpoint time — the first
        # round pays XLA compilation under the same clock.
        watchdog = StallWatchdog(cfg.fault.watchdog_timeout_s, logger=logger)
        watchdog.start()
        # device-side cost capture (telemetry.costs,
        # docs/observability.md "Device-side"): process 0 AOT-lowers
        # uninstrumented twins of the round/commit + eval programs ONCE
        # after the first round (persistent compile cache warm by then)
        # and writes program_costs.json; afterwards every metrics row
        # carries the measured-MFU and HBM-watermark gauges computed
        # from host state alone — the traced programs never change
        # (HLO byte-identical, sentinel holds; pinned in
        # tests/test_device_observability.py)
        cost_capture = None
        if cfg.telemetry.cost_capture_scan_rounds > 0 \
                and cfg.federated.sync_mode == "async":
            # the async trainer's lowered_cost_programs ignores
            # num_scan_rounds (its commit plane refuses the scan
            # dispatch) — say so instead of silently dropping the flag
            logger.log(
                "cost capture: --cost_capture_scan_rounds is ignored "
                "under sync_mode='async' (the commit plane refuses "
                "the scan dispatch; capturing the commit program only)")
        if tel.enabled and tel.is_writer:
            from fedtorch_tpu.telemetry.costs import ProgramCostCapture
            cost_capture = ProgramCostCapture(
                ckpt_dir, compute_dtype=cfg.mesh.compute_dtype,
                arch=cfg.model.arch, batch_size=cfg.data.batch_size,
                local_steps=trainer.local_steps,
                k_online=trainer.k_online,
                num_devices=int(trainer.mesh.devices.size),
                backend=jax.default_backend(),
                run_meta={"algorithm": cfg.effective_algorithm,
                          "sync_mode": cfg.federated.sync_mode,
                          "data_plane": cfg.data.data_plane},
                log=logger.log)
        # federation-plane observability (docs/observability.md
        # "Federation plane"): the per-client ledger accumulates the
        # cohort vectors the batched fetch now carries (cohort_stats
        # on, writer process only), and the observe-only anomaly
        # detector watches the finished metrics rows. Both host-only.
        ledger = None
        anomaly = None
        if tel.enabled and tel.is_writer and cfg.telemetry.cohort_stats:
            from fedtorch_tpu.telemetry.ledger import ClientLedger
            ledger = ClientLedger(
                ckpt_dir, num_clients=cfg.federated.num_clients,
                sketch_budget=cfg.telemetry.ledger_sketch_budget,
                seed=cfg.train.manual_seed,
                run_meta={"algorithm": cfg.effective_algorithm,
                          "robust_agg": cfg.fault.robust_agg,
                          "sync_mode": cfg.federated.sync_mode},
                log=logger.log)
            if ledger.load_existing():
                # elastic restarts ADOPT the run dir's ledger (the
                # program_costs.json convention) — counters resume
                # instead of overwriting the history with zeros
                logger.log("client ledger: adopted existing "
                           f"client_ledger.json ({ledger.rounds} "
                           "rounds)")
        if tel.enabled and cfg.telemetry.anomaly_zscore > 0.0:
            from fedtorch_tpu.telemetry.anomaly import (
                EwmaAnomalyDetector,
            )
            anomaly = EwmaAnomalyDetector(
                zscore=cfg.telemetry.anomaly_zscore)
        # privacy plane (robustness/privacy.py): the host-side RDP
        # accountant streams epsilon spend per committed round. EVERY
        # process accounts (the charge is deterministic, so budget
        # decisions stay SPMD-consistent without a collective); only
        # the writer persists. Participation probability is the run's
        # real cohort width over the population — the commit buffer m
        # on the async plane, k_online on the sync planes ('sparse'
        # k/C directly; 'perm' prefix selection charges equivalently).
        accountant = None
        dp_q = 0.0
        if cfg.fault.dp_armed:
            from fedtorch_tpu.robustness.privacy import (
                ACCOUNTANT_FILE, PrivacyAccountant,
            )
            accountant = PrivacyAccountant(
                cfg.fault.dp_noise_multiplier, cfg.fault.dp_delta)
            width = getattr(trainer, "buffer_size", None) \
                or trainer.k_online
            dp_q = min(1.0, width / float(cfg.federated.num_clients))
            if accountant.load_existing(ckpt_dir):
                # elastic restarts ADOPT the run dir's accountant (the
                # program_costs.json convention) — spend resumes, and
                # per-round-index dedup below makes re-run rounds
                # charge exactly once
                logger.log(
                    "privacy accountant: adopted existing "
                    f"{ACCOUNTANT_FILE} (eps_spent="
                    f"{accountant.epsilon():.4f} over "
                    f"{accountant.charged_rounds} rounds)")
        # still inside the guard: this fetch can raise too (device
        # fault, poisoned resume state) and must not leak the active
        # telemetry / a 'starting' intent for a dead run
        start_round = int(jax.device_get(server.round))
        tel.event("run.start", start_round=start_round, resumed=resumed,
                  num_comms=cfg.federated.num_comms)
    except BaseException:
        tel.health_update("error")
        _uninstall_host_plane()
        tel.close()
        raise
    results = {}
    loop_raised = False
    byz_attack_seen = False
    host_retries_seen = 0
    # consecutive sub-quorum rounds (availability lifecycle): a
    # persistent streak flips the health intent to 'degraded' below
    quorum_streak = 0
    # privacy budget lifecycle: True once 'degrade' flipped the run
    # noise-free — drives the 'degraded' health intent at exit
    dp_degraded = False
    # round-wall critical path (telemetry/critical_path.py): per-round
    # overlap efficiency from the DELTAS of the producer's cumulative
    # gather/H2D/wait gauges — pure host float math over values the
    # row already carries, zero extra device syncs
    from fedtorch_tpu.telemetry.critical_path import (
        StreamOverlapTracker,
    )
    overlap_tracker = StreamOverlapTracker()
    try:
        for r in range(start_round, cfg.federated.num_comms):
            # privacy budget lifecycle (docs/robustness.md "Privacy
            # plane"): pre-check affordability BEFORE dispatching
            # round r — 'stop' ends the run at the LAST affordable
            # round (never one past the budget), 'degrade' flips the
            # traced noise scale to 0.0 (data, not program: no
            # retrace) and keeps going noise-free. Deterministic on
            # every process, so the SPMD decision needs no collective.
            if accountant is not None and not dp_degraded \
                    and cfg.fault.dp_epsilon_budget > 0.0 \
                    and accountant.preview_epsilon(dp_q) \
                    > cfg.fault.dp_epsilon_budget:
                action = cfg.fault.dp_budget_action
                spent = accountant.epsilon()
                tel.event("privacy.budget_exhausted", round=r,
                          action=action, epsilon_spent=spent,
                          epsilon_budget=cfg.fault.dp_epsilon_budget,
                          delta=cfg.fault.dp_delta,
                          charged_rounds=accountant.charged_rounds)
                logger.log(
                    f"privacy budget exhausted before round {r}: "
                    f"eps_spent={spent:.4f} of "
                    f"{cfg.fault.dp_epsilon_budget} (action="
                    f"{action})")
                results["dp_exhausted"] = True
                results["dp_exhausted_at_round"] = r
                if action == "stop":
                    break
                server = trainer.dp_set_noise_scale(server, 0.0)
                dp_degraded = True
            timer.new_round()
            # copy, not alias: the round jit donates the server buffers
            prev_params = jax.tree.map(jnp.copy, server.params) \
                if cfg.checkpoint.track_model_aggregation else None
            timer.start("round")
            # the "round" span covers dispatch through completion of
            # the jitted round/commit program — what the 90%-non-MXU
            # attribution question is asked against
            with tel.span("round", round=r):
                server, clients, metrics = run_round(server, clients)
                if supervisor is None:
                    # the supervisor's health check already blocked
                    jax.block_until_ready(server.params)
            round_time = timer.stop("round")
            # ONE batched device->host fetch for everything this loop
            # logs (round_host_scalars) — per-scalar float() here would
            # serialize a transfer per metric per round (lint FTL001).
            # The ledger's per-client cohort vectors ride the SAME
            # device_get when cohort_stats is on. A supervised healthy
            # round already fetched the scalar dict for its health
            # check: reuse it (only the [k] cohort vectors transfer).
            led_dev = trainer.cohort_fetch_dev(metrics) \
                if ledger is not None else None
            led = None
            fetch_t0 = time.perf_counter()
            if supervisor is not None and \
                    supervisor.last_scalars is not None:
                sc = supervisor.last_scalars
                if led_dev is not None:
                    led = jax.device_get(led_dev)
            else:
                with tel.span("scalar_fetch", round=r):
                    if led_dev is None:
                        sc = trainer.round_host_scalars(clients,
                                                        metrics)
                    else:
                        sc_dev, led = jax.device_get(
                            (trainer.round_scalars_dev(clients,
                                                       metrics),
                             led_dev))
                        sc = {k: float(v) for k, v in sc_dev.items()}
            fetch_s = time.perf_counter() - fetch_t0
            timer.add_comm(num_bytes=sc["comm_bytes"])
            # the scalar fetch blocked on the round's results: the
            # round genuinely completed — feed the stall watchdog
            watchdog.heartbeat(r)
            if accountant is not None and not dp_degraded:
                # charge the COMMITTED round (after degrade the noise
                # is off, so spend freezes); charge_round dedups by
                # round index — supervisor retries and resume re-runs
                # charge exactly once
                accountant.charge_round(r, dp_q)

            if cost_capture is not None and not cost_capture.captured \
                    and not cost_capture.load_existing():
                # once, at the first completed round (elastic restarts
                # adopt the run dir's existing capture instead — a
                # resumed run bypasses the compile cache and would pay
                # a real recompile); a failure turns the device gauges
                # off, never the run
                with tel.span("cost_capture", round=r):
                    try:
                        programs, primary = \
                            trainer.lowered_cost_programs(
                                server, clients,
                                num_scan_rounds=cfg.telemetry
                                .cost_capture_scan_rounds)
                        try:
                            from fedtorch_tpu.parallel.evaluate import (
                                lowered_eval_program,
                            )
                            programs["eval"] = lowered_eval_program(
                                model, server.params, fed_data.test_x,
                                fed_data.test_y)
                        except Exception as e:
                            logger.log("cost capture: eval program "
                                       f"skipped ({e})")
                        cost_capture.capture(programs, primary=primary)
                    except Exception as e:
                        cost_capture.captured = True
                        logger.log(f"cost capture: lowering failed "
                                   f"({e}); device gauges off")

            if cfg.fault.chaos_enabled or cfg.fault.guard_updates:
                if sc["dropped"] or sc["rejected"] or sc["clipped"] \
                        or sc["stragglers"] or sc["byzantine"]:
                    logger.log(
                        f"Round {r}: faults — "
                        f"dropped={sc['dropped']:.0f} "
                        f"stragglers={sc['stragglers']:.0f} "
                        f"rejected={sc['rejected']:.0f} "
                        f"clipped={sc['clipped']:.0f} "
                        f"byzantine={sc['byzantine']:.0f}")
                if sc["byzantine"] and not byz_attack_seen:
                    # one attack event per run, at the first observed
                    # injection — monitors key on this, not on scanning
                    # every row's counter
                    byz_attack_seen = True
                    tel.event("chaos.byzantine_attack", round=r,
                              mode=cfg.fault.byzantine_mode,
                              rate=cfg.fault.byzantine_rate,
                              scale=cfg.fault.byzantine_scale,
                              robust_agg=cfg.fault.robust_agg)
                if supervisor is None and _all_rejected(sc):
                    # renorm scale hit 0: every surviving update was
                    # rejected (or every client crashed) — the server
                    # held this round. With a supervisor the same
                    # detection runs inside its health path.
                    logger.log(f"Round {r}: guards rejected EVERY "
                               "update — server held (renorm scale 0)")
                    tel.event("guards.all_rejected", round=r,
                              n_online=sc["n_online"],
                              rejected=sc["rejected"],
                              dropped=sc["dropped"])

            if cfg.checkpoint.check_model_at_sync:
                norms = jax.device_get(model_norms(server.params))
                logger.log(f"Round {r}: server model l2="
                           f"{float(norms['l2']):.4f} "
                           f"max|w|={float(norms['max_abs']):.4f}")
            if prev_params is not None:
                tr = jax.device_get(
                    aggregation_tracking(prev_params, server.params))
                logger.log(f"Round {r}: aggregation cosine="
                           f"{float(tr['cosine']):.6f} "
                           f"distance={float(tr['distance']):.6f}")

            n_online = max(sc["n_online"], 1.0)
            epoch = sc["mean_epoch"]
            logger.log_train(r, epoch, sc["loss_sum"] / n_online,
                             sc["acc_sum"] / n_online, sc["lr"],
                             comm_bytes=sc["comm_bytes"],
                             round_time=round_time)

            eval_s = checkpoint_s = None
            if (r + 1) % cfg.train.eval_freq == 0:
                timer.start("eval")
                with tel.span("eval", round=r):
                    # one transfer for the whole EvalResult pytree
                    res = jax.device_get(evaluate(
                        model, server.params, fed_data.test_x,
                        fed_data.test_y))
                eval_s = timer.stop("eval")
                top1 = float(res.top1)
                is_best = top1 > best_prec1
                best_prec1 = max(best_prec1, top1)
                logger.log_val(r, "test", float(res.loss), top1,
                               float(res.top5), best=best_prec1)
                if cfg.train.per_class_acc:
                    from fedtorch_tpu.models.common import num_classes_of
                    from fedtorch_tpu.parallel import evaluate_per_class
                    accs, counts = evaluate_per_class(
                        model, server.params, fed_data.test_x,
                        fed_data.test_y, num_classes_of(cfg.data.dataset))
                    logger.log("Round: {}. Per-class acc: {}".format(
                        r, [round(float(a), 4) for a in accs]))
                if accountant is not None and tel.is_writer:
                    # persist spend BEFORE the checkpoint that could
                    # become a resume point: any adopted restart then
                    # sees spend >= its round (never-forget-spend half
                    # of the resume contract)
                    accountant.save(ckpt_dir)
                timer.start("checkpoint")
                with tel.span("checkpoint", round=r):
                    saver(ckpt_dir, server, clients, cfg, best_prec1,
                          is_best,
                          save_all=cfg.checkpoint.save_all_models,
                          save_some_rounds=save_rounds)
                last_saved_round = r
                # lost-write watermark at enqueue time: the drain's
                # skip branch compares against it to detect THIS
                # round's async write failing behind our back
                lost_at_save = async_ckpt.lost_writes \
                    if async_ckpt is not None else 0
                checkpoint_s = timer.stop("checkpoint")
                if cfg.federated.personal and fed_data.val is not None \
                        and cfg.effective_algorithm in (
                            "apfl", "perfedme", "perfedavg"):
                    _, _, summary = evaluate_personal(
                        model, clients.aux, clients.params,
                        trainer.val_data, cfg.effective_algorithm)
                    logger.log_val(r, "validation_personal",
                                   summary["loss_mean"],
                                   summary["acc_mean"])
                results["test_top1"] = top1

            # one schema-versioned metrics row per round (async: per
            # commit), populated from the already-fetched scalar dict
            # plus host-only subsystem gauges — zero extra transfers
            n_onl = max(sc["n_online"], 1.0)
            row = {
                "round": r, "round_s": round_time,
                "loss": sc["loss_sum"] / n_onl,
                "acc": sc["acc_sum"] / n_onl, "lr": sc["lr"],
                "n_online": sc["n_online"],
                "comm_bytes": sc["comm_bytes"],
                "mean_epoch": sc["mean_epoch"], "fetch_s": fetch_s,
                "dropped": sc["dropped"],
                "stragglers": sc["stragglers"],
                "rejected": sc["rejected"], "clipped": sc["clipped"],
                "staleness": sc["staleness"],
                "byzantine": sc["byzantine"],
                "robust_selected": sc["robust_selected"],
                "robust_trimmed": sc["robust_trimmed"],
                # deployment-realism lifecycle counters — same fetch
                "avail_dropped": sc["avail_dropped"],
                "deadline_missed": sc["deadline_missed"],
                "quorum_degraded": sc["quorum_degraded"],
            }
            if eval_s is not None:
                row["eval_s"] = eval_s
                # already host floats (the eval device_get above) —
                # riding the row costs nothing extra
                row["test_top1"] = top1
                row["best_top1"] = best_prec1
            if checkpoint_s is not None:
                row["checkpoint_s"] = checkpoint_s
            if "cohort_dispersion" in sc:
                # the heterogeneity gauge (cohort_stats on) — already
                # part of the batched scalar fetch
                row["cohort_dispersion"] = sc["cohort_dispersion"]
            if "dp_clipped_frac" in sc:
                # privacy-plane gauges (DP armed) — same batched fetch
                row["dp_clipped_frac"] = sc["dp_clipped_frac"]
                row["dp_noise_sigma"] = sc["dp_noise_sigma"]
            if accountant is not None:
                # host-side accountant read: pure f64 math, no sync
                row["dp_epsilon_spent"] = accountant.epsilon()
            if led is not None:
                # cohort norm quantiles + the per-client ledger fold
                # (host numpy from the same fetch; O(k) update)
                nq = led["norm_q"]
                row.update({
                    "cohort_norm_min": float(nq[0]),
                    "cohort_norm_q25": float(nq[1]),
                    "cohort_norm_med": float(nq[2]),
                    "cohort_norm_q75": float(nq[3]),
                    "cohort_norm_max": float(nq[4]),
                })
                ledger.update(r, led)
                row.update(ledger.stats())
            row.update(trainer.telemetry_gauges())
            overlap_eff = overlap_tracker.observe(row)
            if overlap_eff is not None:
                # stream plane: the fraction of this round's producer
                # gather+H2D wall hidden under device compute — the
                # number ROADMAP item 1's STREAM_AB 1.15x gap needs
                row["overlap_efficiency"] = overlap_eff
            if cost_capture is not None:
                # measured MFU + HBM watermark pair — empty until the
                # capture above succeeded, host-side either way
                row.update(cost_capture.round_gauges(round_time))
            if async_ckpt is not None:
                row.update(async_ckpt.stats())
            if supervisor is not None:
                row.update(sup_rollbacks=float(supervisor.stats.rollbacks),
                           sup_retries=float(supervisor.stats.retries),
                           sup_skipped=float(
                               supervisor.stats.skipped_rounds),
                           # skip-cause split (fault vs sub-quorum
                           # abort) — docs/robustness.md "Deployment
                           # realism"
                           sup_skipped_fault=float(
                               supervisor.stats.skipped_fault),
                           sup_skipped_quorum=float(
                               supervisor.stats.skipped_quorum))
            # host-plane recovery gauges: retries/recoveries/degraded
            # seams (and injected-fault count when a drill is armed) —
            # host counters, zero extra device syncs
            row.update(recovery.stats())
            if injector is not None:
                row.update(injector.stats())
            tel.round_row(row)
            if sc["quorum_degraded"] > 0:
                # a sub-quorum round that committed its renormalized
                # partial cohort (degrade action) or is about to be
                # escalated (abort retries exhausted into a skip) —
                # the per-round operator signal behind the 'degraded'
                # health intent below
                tel.event("lifecycle.quorum_degraded", round=r,
                          n_online=sc["n_online"],
                          avail_dropped=sc["avail_dropped"],
                          deadline_missed=sc["deadline_missed"])
            if anomaly is not None:
                # observe-only EWMA z-score pass over the finished row
                # (telemetry/anomaly.py): events + report fodder, no
                # control flow
                for a in anomaly.observe(row):
                    tel.event("anomaly.detected", round=r, **a)
            if cfg.telemetry.level == "debug" and (r + 1) % 25 == 0:
                # debug cadence snapshot of the async staleness
                # histogram: a hard-killed run (watchdog os._exit)
                # keeps at most 25 commits of histogram, not all of it
                hist = trainer.staleness_histogram()
                if hist:
                    tel.event("async.staleness_hist", round=r,
                              snapshot="debug",
                              hist={str(k): v
                                    for k, v in sorted(hist.items())})
            # health: r+1 rounds complete — same convention as
            # checkpoint.json's "round", so monitors can compare the
            # live counter against the last durable one. Intent
            # reflects the host-plane recovery state: 'degraded' while
            # any seam runs in degraded mode, 'recovering' on a round
            # that absorbed a host-seam retry, 'running' otherwise —
            # the run IS progressing in all three.
            host_retries_now = recovery.total_retries()
            quorum_streak = quorum_streak + 1 \
                if sc["quorum_degraded"] > 0 else 0
            if recovery.degraded or quorum_streak >= 3 or dp_degraded:
                # host seam running degraded, OR the availability
                # lifecycle committing sub-quorum cohorts for 3+
                # consecutive rounds, OR the privacy budget exhausted
                # into noise-free continuation — progressing, but an
                # operator should look (docs/robustness.md)
                intent = "degraded"
            elif host_retries_now > host_retries_seen:
                intent = "recovering"
            else:
                intent = "running"
            host_retries_seen = host_retries_now
            tel.health_update(intent, round_idx=r + 1,
                              staleness=sc["staleness"])

            if round_callback is not None:
                round_callback(r, trainer, server, clients, metrics)
            if sc.get("stop"):
                # SPMD-agreed stop (every process computed the same
                # cross-host max): drain at the round boundary — write
                # a final checkpoint and leave with the restartable
                # exit code instead of dying mid-state. The watchdog
                # must disarm FIRST: a slow final write would read as
                # a stall and os._exit would lose the drain.
                watchdog.stop()
                logger.log(f"preemption: stop requested "
                           f"({preempt.reason or 'peer host'}); "
                           f"draining after round {r}")
                tel.event("preempt.drain", round=r,
                          reason=preempt.reason or "peer host")
                hist = trainer.staleness_histogram()
                if hist:
                    # drain-path snapshot (async plane): the final
                    # emission reads the histogram after the stream
                    # teardown; snapshotting here makes the preempted
                    # run's histogram durable even if the drain's own
                    # checkpoint write later raises
                    tel.event("async.staleness_hist", round=r,
                              snapshot="drain",
                              hist={str(k): v
                                    for k, v in sorted(hist.items())})
                tel.health_update("drain", round_idx=r + 1)
                # the resume point the restart depends on must be
                # DURABLE before exit 75 — a failure here must RAISE,
                # not be recorded as a lost background write. When
                # this round's eval branch already saved, drain the
                # async queue and only redo the (collective-snapshot)
                # write if that queued write was lost.
                final_ckpt_needed = last_saved_round != r
                if not final_ckpt_needed and async_ckpt is not None:
                    async_ckpt.wait()
                    final_ckpt_needed = \
                        async_ckpt.lost_writes > lost_at_save
                    if final_ckpt_needed:
                        logger.log("preemption: this round's async "
                                   "checkpoint was lost — rewriting "
                                   "synchronously before exit")
                if final_ckpt_needed:
                    timer.start("checkpoint")
                    with tel.span("checkpoint", round=r, drain=True):
                        if async_ckpt is not None:
                            # an older queued write landing AFTER the
                            # final sync write would roll the resume
                            # point backwards — drain the queue first
                            async_ckpt.wait()
                        save_checkpoint(
                            ckpt_dir, server, clients, cfg,
                            best_prec1, False,
                            save_all=cfg.checkpoint.save_all_models,
                            save_some_rounds=save_rounds)
                    timer.stop("checkpoint")
                results["preempted"] = True
                results["preempted_at_round"] = r
                break
    except BaseException:
        loop_raised = True
        raise
    finally:
        # the drain itself must not race the watchdog (a slow final
        # write would read as a stall), and the handlers must never
        # outlive the loop in library callers
        watchdog.stop()
        preempt.restore()
        # read the staleness histogram BEFORE the stream teardown: the
        # async trainer's invalidate_stream drops the event scheduler
        # that owns it, which silently lost the run-end
        # async.staleness_hist event on every CLI run (the trainer
        # also stashes it across invalidation now — belt and braces)
        final_hist = trainer.staleness_histogram()
        # streaming data plane: stop the feed producer and drop any
        # in-flight prefetch — a preemption drain (exit 75) must not
        # leave a worker thread blocked on the feed queue, and a
        # library caller resuming this trainer later re-syncs cleanly
        trainer.invalidate_stream()
        flush_raised = False
        try:
            if async_ckpt is not None:
                # flush pending writes even when the loop raised — the
                # checkpoint the user would resume from must hit disk.
                # A background write that failed past its retries was
                # already recorded (ckpt.degraded event + lost-write
                # counters; the drain path writes its final checkpoint
                # synchronously so ITS failure raises at the save) —
                # close() itself raising is a defensive residue, kept
                # because it must not MASK the loop's own exception
                # while still surfacing when the loop succeeded.
                timer.start("checkpoint")
                try:
                    async_ckpt.close()
                except Exception as e:
                    flush_raised = True
                    if loop_raised:
                        logger.log("WARNING: async checkpoint flush "
                                   "failed while handling another "
                                   f"error: {e}")
                    else:
                        raise
                finally:
                    timer.stop("checkpoint")
        finally:
            # final telemetry: the staleness histogram (async plane),
            # the ledger flush, the run-end event, the exit intent,
            # and the trace export — best-effort bookkeeping that must
            # never mask the loop's outcome (the emitters, the ledger
            # flush and Telemetry.close never raise)
            if final_hist:
                tel.event("async.staleness_hist", snapshot="final",
                          hist={str(k): v
                                for k, v in sorted(final_hist.items())})
            if ledger is not None:
                ledger.flush()
            if accountant is not None and tel.is_writer:
                # final durable spend (save absorbs I/O failure — the
                # bookkeeping never masks the loop's outcome)
                accountant.save(ckpt_dir)
            if anomaly is not None:
                tel.event("anomaly.summary", fields=anomaly.summary())
            tel.event("run.end",
                      preempted=bool(results.get("preempted")),
                      raised=loop_raised or flush_raised)
            if loop_raised or flush_raised:
                tel.health_update("error")
            elif results.get("preempted"):
                tel.health_update("preempted")
            elif quorum_streak >= 3 or dp_degraded:
                # the run finished, but its tail was a persistent
                # sub-quorum streak OR a noise-free privacy 'degrade'
                # continuation — keep the operator signal instead of
                # overwriting it with a clean 'complete'. (A budget
                # 'stop' lands in the else: ending at the last
                # affordable round IS the clean outcome.)
                tel.health_update("degraded")
            else:
                tel.health_update("complete")
            _uninstall_host_plane()
            tel.close()
    results["best_top1"] = best_prec1
    if accountant is not None:
        results["dp"] = {
            "epsilon_spent": accountant.epsilon(),
            "delta": cfg.fault.dp_delta,
            "charged_rounds": accountant.charged_rounds,
            "exhausted": bool(results.get("dp_exhausted")),
            "degraded": dp_degraded,
        }
    if supervisor is not None:
        st = supervisor.stats
        results["supervisor"] = {
            "rounds": st.rounds, "retries": st.retries,
            "rollbacks": st.rollbacks,
            "skipped_rounds": st.skipped_rounds,
            "skipped_fault": st.skipped_fault,
            "skipped_quorum": st.skipped_quorum,
            "disk_restores": st.disk_restores,
            "all_rejected_rounds": st.all_rejected_rounds,
            "last_good_round": st.last_good_round}
        if st.rollbacks:
            logger.log(f"supervisor: {st.rollbacks} rollback(s), "
                       f"{st.retries} retrie(s), {st.skipped_rounds} "
                       "skipped round(s)")
    rec_stats = recovery.stats()
    if injector is not None:
        rec_stats.update(injector.stats())
        rec_stats["host_fault_fires"] = injector.fire_counts()
    if any(bool(v) for v in rec_stats.values()):
        results["host_recovery"] = rec_stats
        logger.log(f"host plane: {rec_stats}")
    results["timer"] = timer.summary()
    logger.log(f"phase timers: {timer.summary()}")
    if results.get("preempted"):
        from fedtorch_tpu.robustness import RESTART_EXIT_CODE
        logger.log("preemption: final checkpoint drained and flushed; "
                   f"restartable exit (code {RESTART_EXIT_CODE}) — "
                   "run_elastic/supervise will relaunch with --resume")
    return results


def main(argv=None):
    if argv is None:
        import sys
        argv = sys.argv[1:]
    if argv and argv[0] == "lint":
        # `fedtorch-tpu lint [...]` — the static tracing-hazard
        # analyzer (docs/static_analysis.md); stdlib-only, never
        # initializes jax
        from fedtorch_tpu.lint.cli import main as lint_main
        return lint_main(argv[1:])
    if argv and argv[0] == "audit":
        # `fedtorch-tpu audit [...]` — the program-level + registry-
        # drift audit (docs/static_analysis.md "The program audit"):
        # abstractly lowers every legal round-program builder cell and
        # checks the HLO/jaxpr (FTP rules), then cross-checks the five
        # hand-maintained registries (FTC rules). Initializes jax
        # (CPU is fine); --registry-only stays stdlib.
        from fedtorch_tpu.lint.cli import main as lint_main
        return lint_main(["--audit"] + argv[1:])
    if argv and argv[0] == "report":
        # `fedtorch-tpu report <run_dir>` — summarize a run dir's
        # telemetry (docs/observability.md); stdlib-only, never
        # initializes jax
        from fedtorch_tpu.tools.report import main as report_main
        return report_main(argv[1:])
    if argv and argv[0] == "watch":
        # `fedtorch-tpu watch <run_dir>` — live console over a
        # running run's health/metrics/events (docs/observability.md
        # "Operating and comparing runs"); stdlib-only, never
        # initializes jax; one-shot snapshot on non-tty
        from fedtorch_tpu.tools.watch import main as watch_main
        return watch_main(argv[1:])
    if argv and argv[0] == "compare":
        # `fedtorch-tpu compare A B [--gate gates.json]` — noise-aware
        # run-dir diff with regression gating (exit 1 on a gated
        # regression); stdlib-only, never initializes jax
        from fedtorch_tpu.tools.compare import main as compare_main
        return compare_main(argv[1:])
    if argv and argv[0] == "runs":
        # `fedtorch-tpu runs <root>` — index run dirs into
        # runs_index.json and list/filter them; stdlib-only, never
        # initializes jax
        from fedtorch_tpu.telemetry.runs import main as runs_main
        return runs_main(argv[1:])
    if argv and argv[0] == "supervise":
        # `fedtorch-tpu supervise [opts] -- <training command>` — the
        # per-host auto-restart harness (robustness/harness.py):
        # relaunches the command with --resume on restartable exits
        from fedtorch_tpu.robustness.harness import main as harness_main
        return harness_main(argv[1:])
    args = build_parser().parse_args(argv)
    cfg = args_to_config(args)
    results = run_experiment(cfg, download=args.download)
    if isinstance(results, dict) and results.get("preempted"):
        # EX_TEMPFAIL: the restart-harness contract — raised (not
        # returned) so `python -m fedtorch_tpu.cli` and the console
        # script both exit 75
        from fedtorch_tpu.robustness import RESTART_EXIT_CODE
        raise SystemExit(RESTART_EXIT_CODE)
    return results


if __name__ == "__main__":
    _result = main()
    if isinstance(_result, int):  # lint / supervise exit codes
        raise SystemExit(_result)
