"""Typed, immutable experiment configuration.

Capability parity with the reference flag system
(``/root/reference/fedtorch/parameters.py:12-260``), redesigned for a
TPU/JAX build:

* Static configuration is a frozen, hashable dataclass tree, so it can be
  passed as a ``static_argnum`` through ``jax.jit`` boundaries. The
  reference instead threads a mutable ``argparse.Namespace`` everywhere and
  writes runtime values back into it (``SURVEY.md`` §5.6); here runtime
  state lives in explicit pytrees (see ``fedtorch_tpu.core.state``).
* Post-parse derivations/validations from ``parameters.py:245-259``
  (federated epoch count, AFL coercion, qsparse->compressed, quantize xor
  compress, personalization->fed_personal) are reproduced in
  :meth:`ExperimentConfig.finalize`.
"""
from __future__ import annotations

import dataclasses
import warnings
from dataclasses import dataclass, field
from typing import Optional

# Algorithms that keep a second, personalized model per client
# (ref: parameters.py:257-259).
PERSONALIZED_ALGORITHMS = ("apfl", "perfedme", "perfedavg")

# Robust aggregation rules at the round/commit aggregation seam
# (robustness/aggregators.py; 'mean' = the pre-robustness weighted sum)
# and the in-jit byzantine adversary models that test them
# (robustness/chaos.py). Declared here so config validation stays
# stdlib-only — the jax implementations import THESE tuples.
ROBUST_AGGREGATORS = ("mean", "median", "trimmed_mean", "krum",
                      "multikrum", "norm_bound")
BYZANTINE_MODES = ("sign_flip", "scale", "zero", "gauss", "collude")
# norm_bound carries a params-shaped server momentum in server.aux;
# algorithms with structured payload trees (SCAFFOLD's control deltas,
# qFFL's fairness scalar, DRFA's nested wrapper) have no single tree
# the momentum can live against, so they raise at construction.
NORM_BOUND_ALGORITHMS = ("fedavg", "fedprox", "fedadam")
# The DP stage (robustness/privacy.py) clips each client's single
# params-shaped update payload radially; the same structured-payload
# algorithms that can't host norm_bound's momentum have no single
# tree a fixed-radius clip is meaningful against, so DP refuses them
# by name at finalize too.
DP_ALGORITHMS = ("fedavg", "fedprox", "fedadam")

# Named host-plane fault seams (robustness/host_chaos.py;
# docs/robustness.md "Host plane"). Each names one host-side I/O or
# thread boundary where the seeded injector can fire — and where the
# matching self-healing policy (robustness/host_recovery.py) must
# absorb the fault. Declared here so config validation stays
# stdlib-only; the injector imports THIS tuple.
HOST_FAULT_SEAMS = (
    "stream.gather",    # producer row gather raises (transient)
    "stream.delay",     # producer gather stalls host_fault_delay_s
    "stream.h2d",       # device_put dispatch of the packed feed raises
    "ckpt.write",       # checkpoint atomic write raises ENOSPC
    "ckpt.torn",        # checkpoint write lands TRUNCATED (torn frame)
    "telemetry.write",  # metrics/events/health file write raises
    "native.load",      # native library load fails -> numpy fallback
)

# Client-availability models (robustness/availability.py;
# docs/robustness.md "Deployment realism"). 'default' reproduces the
# legacy AsyncSchedule draws bitwise (straggler-knob aliasing — the
# tail-delay Bernoulli off the _DELAY_SALT fold chain, no dropouts);
# 'trace' is the in-tree synthetic deployment trace: FedScale-style
# device-class speed multipliers + a diurnal on/off availability curve
# + mid-round dropout, all threefry draws off the experiment key.
# Declared here so config validation stays stdlib-only — the jax
# implementation imports THIS tuple.
AVAILABILITY_MODELS = ("default", "trace")

# Client-store implementations behind the stream plane's feed packer
# (data/streaming.py ClientStore; docs/performance.md "The
# million-client store"): 'ram' keeps the [C, n_max, ...] population
# arrays host-resident (the seed behavior — population capped by host
# RAM); 'mmap' memory-maps a manifest-described sharded file layout
# from data.store_dir, so host residency is O(feed) and population is
# capped by disk. Declared here so config validation stays stdlib-only.
CLIENT_STORES = ("ram", "mmap")

# Participation-sampling modes (parallel/federated.py
# participation_indices): 'perm' is the legacy full-permutation draw
# (bitwise-pinned by every parity test — O(C log C) per round); 'sparse'
# is the O(k)-memory sparse Fisher-Yates draw that never materializes a
# [C] array (million-client populations). Both are replayed bit-exactly
# by the host RoundSchedule and the async scheduler.
PARTICIPATION_MODES = ("perm", "sparse")

FEDERATED_ALGORITHMS = (
    "fedavg", "scaffold", "fedprox", "fedgate", "fedadam", "apfl", "afl",
    "perfedavg", "qsparse", "perfedme", "qffl",
)

DATASETS = (
    "cifar10", "cifar100", "mnist", "fashion_mnist", "emnist", "emnist_full",
    "synthetic", "shakespeare", "adult", "epsilon", "MSD", "higgs", "rcv1",
    "stl10",
)


@dataclass(frozen=True)
class DataConfig:
    """Dataset & partitioning knobs (ref: parameters.py:23-37, 41-66)."""
    dataset: str = "cifar10"
    data_dir: str = "./data/"
    partition_data: bool = True
    # Non-IID partitioning scheme (ref: partition.py:106-220).
    iid: bool = True
    num_class_per_client: int = 1
    unbalanced: bool = False
    dirichlet: bool = False
    dirichlet_alpha: float = 0.1  # hard-coded in the reference partitioner
    # Synthetic dataset heterogeneity (ref: parameters.py:33-36).
    synthetic_alpha: float = 0.0
    synthetic_beta: float = 0.0
    synthetic_dim: int = 60
    # default matches the reference GENERATOR (federated_datasets.py:205
    # num_classes=2). Note the reference's own quirk, reproduced by the
    # model zoo for parity: synthetic model HEADS are sized 10-way
    # (logistic_regression.py:65-67) while labels only span this many.
    synthetic_num_classes: int = 2
    # lower edge of the per-client lognormal size window (upper = 2x);
    # the default reproduces the reference's 500/1000 generator window
    synthetic_samples_per_client: int = 500
    synthetic_regression: bool = False
    # Adult sensitive-feature split (ref: parameters.py:37).
    sensitive_feature: int = 9
    # Federated data plane — the round-program builder's data-source
    # axis (docs/performance.md "The round-program builder"): 'device'
    # shards every client's rows into HBM at trainer construction and
    # hands the full [C, n_max, ...] pytree to each jitted round (the
    # reference-faithful seed behavior — population capped by device
    # memory); 'stream' keeps the client store host-resident and feeds
    # each dispatch the K online clients' packed rows — one feed per
    # round, or an [R, ...] feed window under the scanned dispatch
    # (run_rounds) — built and transferred one dispatch ahead of
    # device compute (population capped by host RAM;
    # bitwise-identical trajectories). Both values compose with every
    # dispatch (per-round | scan | async commit) and execution
    # (vmap | fused) the cell validator allows
    # (parallel/round_program.py).
    data_plane: str = "device"
    # Host client-store implementation behind the stream plane's feed
    # packer (CLIENT_STORES; docs/performance.md "The million-client
    # store"): 'ram' holds the population in host memory, 'mmap' maps
    # the sharded on-disk layout at ``store_dir`` (built by
    # data/streaming.py save_client_store / MmapStoreWriter) so host
    # residency stays O(feed) while the population scales to disk.
    # 'mmap' requires data_plane='stream' — the device plane uploads
    # the whole store to HBM, which is exactly what mmap exists to
    # avoid.
    store: str = "ram"
    store_dir: str = ""
    # Batching (ref: parameters.py:131-141).
    batch_size: int = 50
    growing_batch_size: bool = False
    base_batch_size: Optional[int] = None
    max_batch_size: int = 0
    reshuffle_per_epoch: bool = False
    # Personalization val split sizes mirror dataset.py:168-211.
    val_fraction: float = 0.2
    # train-time flip+crop augmentation (prepare_data.py:29-35 applies it
    # for the cifar family); None = on for cifar/stl10, off otherwise
    augment: Optional[bool] = None
    # EMNIST ships train-only in some mirrors; slicing train rows in as
    # a fake test set silently reports train accuracy as test accuracy,
    # so the fallback is opt-in (data/datasets.py raises without it)
    allow_train_as_test: bool = False


@dataclass(frozen=True)
class FederatedConfig:
    """Federated-mode knobs (ref: parameters.py:40-110)."""
    federated: bool = False
    num_clients: int = 10  # world size in the reference's MPI mode
    num_comms: int = 100
    online_client_rate: float = 0.1
    sync_type: str = "epoch"  # 'epoch' | 'local_step'
    num_epochs_per_comm: int = 1
    algorithm: str = "fedavg"  # --federated_type
    # How the k online clients are drawn each round
    # (PARTICIPATION_MODES): 'perm' = the legacy full-permutation
    # sample (misc.py:10-19 — trajectories bitwise-pinned); 'sparse' =
    # the O(k)-memory draw for million-client populations (same
    # uniform without-replacement law, different stream). Replayed
    # bit-exactly by the host schedule and the async scheduler.
    participation_mode: str = "perm"
    # Server execution plane (docs/robustness.md "Asynchronous
    # federation"): 'sync' (default, the reference-faithful seed
    # behavior) blocks each round on all k online clients; 'async' is
    # the FedBuff-style buffered server (arXiv:2106.06639) — clients
    # train against a possibly-stale snapshot from a commit-versioned
    # ring, the server folds arrivals into a buffer of
    # ``async_buffer_size`` staleness-weighted updates and commits
    # through the guard/renormalization path when it fills. In async
    # mode ``num_comms`` counts COMMITS and ``fault.straggler_rate``
    # draws arrival DELAYS (long-tail wall-clock), not step cuts.
    sync_mode: str = "sync"  # 'sync' | 'async'
    # updates buffered per commit (FedBuff's m). 0 = auto:
    # max(1, k_online // 2) — commits gate on the fastest half of the
    # in-flight cohort, never on the slowest client.
    async_buffer_size: int = 0
    # concurrently-training clients (FedBuff's M_c). 0 = auto: k_online
    # (the sync round's compute budget).
    async_concurrency: int = 0
    # staleness weight s(tau) applied to a buffered update that trained
    # against a snapshot tau commits old: 'poly' = (1+tau)^-exponent
    # (the FedBuff default), 'inv' = 1/(1+tau), 'const' = 1. Weights
    # are normalized to mean 1 per commit, so tau=0 reproduces the sync
    # aggregation weighting exactly (async_plane/staleness.py).
    staleness_weight: str = "poly"
    staleness_exponent: float = 0.5
    # server snapshot ring depth: how many past commit versions stay
    # resident for in-flight clients (memory cost: ring x (params +
    # server aux)). Updates older than the ring are clamped to the
    # oldest retained snapshot (counted in the scheduler stats).
    snapshot_ring: int = 8
    # Personalization.
    personal: bool = False          # --fed_personal
    personal_alpha: float = 0.5     # APFL mixing alpha
    adaptive_alpha: bool = False    # optimize APFL alpha on the fly
    personal_test: bool = False
    # Server adaptivity (FedAdam, arXiv:2003.00295).
    fedadam_beta: float = 0.9
    fedadam_tau: float = 0.1
    # Wire compression (ref: parameters.py:81-89).
    quantized: bool = False
    quantized_bits: int = 8
    compressed: bool = False
    compressed_ratio: float = 1.0
    # DRFA wrapper (ref: parameters.py:90-97).
    drfa: bool = False
    drfa_gamma: float = 0.1
    # paper-faithful lambda-distributed client sampling; the reference's
    # loop samples uniformly (drfa.py:71,216) despite misc.py:30-37
    drfa_lambda_sampling: bool = False
    # Per-algorithm scalars.
    perfedavg_beta: float = 0.001
    fedprox_mu: float = 0.002
    perfedme_lambda: float = 15.0
    qffl_q: float = 0.0


@dataclass(frozen=True)
class ModelConfig:
    """Architecture knobs (ref: parameters.py:113-115, 180-194)."""
    arch: str = "mlp"
    drop_rate: float = 0.0
    # Normalization: 'bn' matches the reference; 'gn' is the TPU-friendly
    # stateless default (no running stats to carry through collectives).
    norm: str = "bn"
    densenet_growth_rate: int = 12
    densenet_bc_mode: bool = False
    densenet_compression: float = 0.5
    wideresnet_widen_factor: int = 4
    mlp_num_layers: int = 2
    mlp_hidden_size: int = 500
    rnn_seq_len: int = 50
    rnn_hidden_size: int = 50
    vocab_size: int = 86
    # transformer arch only: >0 swaps each block's MLP for a Switch-MoE
    # with this many experts (expert-parallel over the mesh when sharded)
    moe_experts: int = 0
    # MoE dispatch: 0 = exact dense one-hot dispatch (no drops, costs E×
    # the dense MLP FLOPs — oracle/testing mode); >0 = sparse Switch
    # dispatch with per-expert capacity ceil(cf·tokens/E) (costs cf× the
    # dense MLP FLOPs; over-capacity tokens drop to the residual)
    moe_capacity_factor: float = 0.0
    # Switch load-balancing auxiliary loss weight (arXiv:2101.03961
    # §2.2; paper default 0.01). 0 disables; without it the top-1 gate
    # can collapse onto one expert.
    moe_aux_weight: float = 0.0
    # resnet-family convolution lowering: 'conv' = XLA's native
    # convolution; 'matmul' = im2col + one batched matmul per layer
    # (identical params/math; fills the MXU differently under the
    # federated engine's per-client weight axis — docs/performance.md
    # "MFU roofline", measured by vmap_penalty_bench's conv_lowering).
    # 'auto' (default) resolves per (arch, dataset) in define_model:
    # matmul for the conv families on small-image datasets, where the
    # round-5 XLA A/B measured 7.0-8.2x (CONV_AB_CPU.json) and the
    # N-lane roofline predicts a larger MXU win; conv elsewhere (the
    # kh*kw x patch-memory trade is prohibitive at 96px+ inputs).
    conv_impl: str = "auto"
    # transformer attention backend: 'dense' (materialized scores),
    # 'flash' (fused online-softmax pallas kernel on TPU, O(block^2)
    # score memory; exact, dense fallback off-TPU), or 'auto'
    # (default): per-sequence-length dispatch that picks flash only
    # where the on-chip training A/B measured it winning outside the
    # noise band (T >= 4096; FLASH_TRAIN.json read 0.68x at T=2048 —
    # ops/attention_dispatch.py:resolve_attention)
    attention: str = "auto"
    pretrained: bool = False
    # 'robust_*' archs learn an adversarial input-noise parameter.
    robust_noise_ascent_lr: float = 0.1


@dataclass(frozen=True)
class OptimConfig:
    """Optimizer & momentum scheme (ref: parameters.py:168-183)."""
    optimizer: str = "sgd"  # 'sgd' | 'adam'
    lr: float = 0.01
    in_momentum: bool = False
    in_momentum_factor: float = 0.9
    out_momentum: bool = False
    # Default derived as 1 - 1/n in the reference (optimizer.py:6-31).
    out_momentum_factor: Optional[float] = None
    use_nesterov: bool = False
    dampening: float = 0.0
    weight_decay: float = 5e-4
    correct_wd: bool = False  # AdamW decoupled weight decay switch
    # True excludes normalization scale/shift and bias parameters from
    # weight decay (the standard deep-learning practice). Default False
    # = the reference's uniform decay over every parameter
    # (sgd.py:96-101 applies wd to the whole param group, BN included)
    # — parity runs against the reference need the biased-but-faithful
    # behavior, so the exclusion is opt-in (core/optim.py).
    wd_skip_norm_bias: bool = False
    lr_scale_at_sync: float = 1.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8


@dataclass(frozen=True)
class LRConfig:
    """LR schedule compiler inputs (ref: parameters.py:144-166)."""
    # strict|custom_one_cycle|custom_multistep|custom_convex_decay
    schedule_scheme: Optional[str] = None
    lr_change_epochs: Optional[str] = None
    lr_fields: Optional[str] = None
    lr_scale_indicators: Optional[str] = None
    scaleup: bool = False
    scaleup_type: str = "linear"
    scaleup_factor: Optional[float] = None
    warmup: bool = False
    warmup_epochs: int = 5
    decay: float = 10.0
    onecycle_low: float = 0.15
    onecycle_high: float = 3.0
    onecycle_extra_low: float = 0.0015
    onecycle_num_epoch: int = 46
    gamma: Optional[float] = None
    mu: Optional[float] = None
    alpha: Optional[float] = None


@dataclass(frozen=True)
class TrainConfig:
    """Stop criteria & local-step schedule (ref: parameters.py:118-130)."""
    stop_criteria: str = "epoch"  # 'epoch' | 'iteration'
    num_epochs: Optional[int] = None
    num_iterations: Optional[int] = None
    local_step: int = 1
    local_step_warmup_per_interval: bool = False
    local_step_warmup_type: Optional[str] = None  # 'exp' | 'linear' | constant
    local_step_warmup_period: Optional[int] = None
    turn_on_local_step_from: Optional[int] = None
    turn_off_local_step_from: Optional[int] = None
    avg_model: bool = True
    manual_seed: int = 6
    evaluate: bool = False
    eval_freq: int = 1
    summary_freq: int = 10
    # report per-class validation accuracy (--per_class_acc,
    # parameters.py:98-99)
    per_class_acc: bool = False


@dataclass(frozen=True)
class CheckpointConfig:
    """Checkpoint/resume (ref: parameters.py:204-222)."""
    checkpoint_dir: str = "./checkpoint/"
    # exact run directory (no hyperparam/timestamp subfolders). A
    # restarted process must FIND the previous attempt's checkpoint, so
    # elastic runs (robustness/harness.py) pin this to a stable path
    # and pass the same path as the harness's --ckpt_dir.
    run_dir: Optional[str] = None
    resume: Optional[str] = None
    checkpoint_index: Optional[str] = None
    save_all_models: bool = False
    save_some_models: str = "1,29,59"
    # bounded retention for the per-round checkpoint_r{N}.ckpt keeps:
    # > 0 garbage-collects all but the newest N after each write; 0
    # (default) keeps everything — save_all_models' historical
    # semantics. model_best.* and checkpoint.ckpt are never collected.
    keep_last_n: int = 0
    # write checkpoints from a background thread (atomic tmp+rename)
    # so training dispatch never blocks on serialization/disk
    async_save: bool = False
    log_dir: str = "./logdir/"
    track_model_aggregation: bool = False
    check_model_at_sync: bool = False
    debug: bool = False


@dataclass(frozen=True)
class FaultConfig:
    """Fault injection, update guards, and round-supervisor knobs.

    The reference has NO fault handling: its MPI mode is fail-stop (one
    dead client kills the ``mpirun`` job) and a NaN client update
    poisons the server silently. These knobs drive the robustness
    subsystem (``fedtorch_tpu.robustness``, docs/robustness.md):

    * Chaos injection runs INSIDE the jitted round program and is
      deterministic under the threaded PRNG — a seeded run replays the
      exact same crash/straggler/poison schedule.
    * Update guards screen client deltas server-side before aggregation.
    * The supervisor wraps the host round loop with rollback + retry.
    """
    # -- chaos injection (parallel/federated.py) -----------------------
    # per-round probability each ONLINE client crashes mid-round: its
    # update is masked out of aggregation, surviving weights are
    # renormalized, and its local state rolls back (fail-stop semantics)
    client_drop_rate: float = 0.0
    # per-round probability an online client is a straggler: it only
    # completes ceil(straggler_step_frac * budget) of its local steps
    # (reuses the epoch-sync freeze mask, so frozen steps cost lockstep
    # FLOPs but change nothing)
    straggler_rate: float = 0.0
    straggler_step_frac: float = 0.5
    # per-round probability an online client uploads a non-finite
    # (NaN-poisoned) delta — exercises the update guards end to end
    nan_inject_rate: float = 0.0
    # fold constant separating the chaos stream from the round's
    # sampling/training streams (fixed; exposed for reproducibility
    # experiments that want distinct chaos schedules on one data seed)
    chaos_salt: int = 0x7FFFFFFD
    # -- byzantine adversary model (robustness/chaos.py) ----------------
    # fraction of the population that is a FIXED adversarial cohort
    # (floor(rate * num_clients) clients, chosen once per run from the
    # run key — persistent adversaries, not per-round coin flips).
    # Whenever a cohort member is online its upload is replaced at the
    # wire by a crafted vector per byzantine_mode. Unlike nan poison,
    # the crafted upload is FINITE and (for sign_flip/collude at scale
    # 1) carries an honest-sized norm — it passes the update guards by
    # design; the defense is the robust aggregation layer (robust_agg).
    byzantine_rate: float = 0.0
    # sign_flip: -scale*delta | scale: scale*delta | zero: free-rider |
    # gauss: scale*N(0,I) noise | collude: all byzantine clients submit
    # the identical -scale*(honest weighted-mean update)
    byzantine_mode: str = "sign_flip"
    # attack magnitude multiplier (see byzantine_mode semantics)
    byzantine_scale: float = 1.0
    # -- robust aggregation (robustness/aggregators.py) -----------------
    # aggregation rule at the round/commit seam: 'mean' (default; the
    # pre-robustness weighted sum, bitwise-identical), coordinate-wise
    # 'median', 'trimmed_mean' (robust_trim_frac off each end),
    # 'krum'/'multikrum' (pairwise-distance selection as a weight
    # mask), 'norm_bound' (centered clipping toward a server momentum
    # carried in server.aux). Composes AFTER the chaos/guard accept
    # mask and the async staleness weights.
    robust_agg: str = "mean"
    # trimmed_mean's per-end trim fraction AND krum's assumed byzantine
    # fraction f/k (the rules tolerate strictly fewer adversaries than
    # this fraction of the accepted updates)
    robust_trim_frac: float = 0.1
    # norm_bound clip radius as a multiple of the round's median
    # distance-to-momentum (scale-free, like guard_norm_multiplier).
    # Default 1.5: honest updates cluster near the momentum so mild
    # clipping is benign, while a permissive radius lets an adversary
    # ride exactly at the boundary — the attack matrix measured tau=3
    # failing against scale-3 sign flips that tau<=2 fully stops.
    robust_norm_tau: float = 1.5
    # -- server-side update guards -------------------------------------
    # screen client deltas before aggregation: non-finite deltas are
    # always rejected; finite deltas whose global l2 norm exceeds
    # guard_norm_multiplier x the median surviving norm are rejected
    # (guard_mode='reject') or scaled down to the threshold
    # (guard_mode='clip'). Rejected weight is renormalized over the
    # accepted clients.
    guard_updates: bool = False
    guard_norm_multiplier: float = 10.0
    guard_mode: str = "reject"  # 'reject' | 'clip'
    # -- host-side round supervisor ------------------------------------
    supervisor: bool = False
    # non-finite server params always trigger rollback; >0 additionally
    # treats mean online loss > factor x the running loss EMA as
    # divergence
    loss_blowup_factor: float = 0.0
    max_retries: int = 2
    backoff_base_s: float = 0.5
    # fold the attempt number into the server PRNG on retry so the
    # retried round draws a fresh participation/chaos schedule (an
    # unchanged deterministic program would reproduce the failure)
    reseed_on_retry: bool = True
    # -- host-plane fault injection (robustness/host_chaos.py) ---------
    # comma-separated seam names from HOST_FAULT_SEAMS arming the
    # deterministic host-fault injector ("" = off). Unlike the in-jit
    # chaos above, these faults fire on HOST threads and I/O paths —
    # the stream-feed producer, checkpoint writes, telemetry files,
    # the native-library loader — and the self-healing layer
    # (robustness/host_recovery.py) must absorb them: a drill proves
    # the run completes with a bitwise-identical trajectory, not that
    # training routes around lost updates.
    host_fault_seams: str = ""
    # per-check fire probability at each armed seam. The draw is a
    # pure hash of (seed, seam, check index), so a drill replays the
    # exact fault schedule on every run.
    host_fault_rate: float = 0.25
    host_fault_seed: int = 0
    # stall injected at the 'stream.delay' seam (seconds per fire)
    host_fault_delay_s: float = 0.02
    # >0 caps the TOTAL fires per seam — e.g. rate=1.0 with a cap of
    # host_retry_max+1 kills the producer exactly once and lets the
    # rebuilt producer succeed (the producer-rebuild drill)
    host_fault_max: int = 0
    # -- host-plane self-healing (robustness/host_recovery.py) ---------
    # bounded retry-with-backoff at every host seam (stream gather/H2D,
    # checkpoint atomic writes) and the producer-rebuild budget: a
    # failed producer is torn down and rebuilt through the existing
    # invalidate_stream() resync at most this many times per pop
    host_retry_max: int = 3
    host_retry_backoff_s: float = 0.05
    # -- process lifecycle (robustness/preemption.py, watchdog.py) -----
    # > 0 arms the stall watchdog: when no round completes within this
    # many seconds (the signature of a dead peer blocking a DCN
    # collective), thread stacks are dumped to the run log and the
    # process hard-exits with the restartable code 75 so the restart
    # harness cycles it. 0 (default) = off: no monitor thread, and the
    # traced round program is byte-identical (host-only feature).
    watchdog_timeout_s: float = 0.0
    # -- deployment realism (robustness/availability.py) ---------------
    # client-availability model behind AsyncSchedule arrivals and the
    # sync round lifecycle. 'default' reproduces the legacy scheduler
    # draws bitwise (straggler-knob aliasing, no dropouts); 'trace'
    # arms the in-tree synthetic deployment trace: FedScale-style
    # device-class speed multipliers + a diurnal on/off curve, all
    # threefry draws off the experiment key so completion order stays a
    # pure function of (seed, round/commit).
    avail_model: str = "default"
    # mid-round dropout probability per dispatched client: a dropped
    # client never reports (async: its arrival is discarded and its
    # slot re-dispatched; sync: it is masked out through the accept
    # seam and surviving weight renormalized)
    avail_dropout_rate: float = 0.0
    # rounds per diurnal cycle for the trace model's on/off availability
    # curve (0 = flat fleet, no diurnal modulation)
    avail_diurnal_period: int = 0
    # sync round lifecycle: dispatch ceil(over_select_frac * k_online)
    # clients and close the round on the first k_online arrivals; the
    # late tail is masked out through the accept-mask ->
    # guards.renormalize_accepted seam (1.0 = no over-selection)
    over_select_frac: float = 1.0
    # round quorum as a fraction of k_online (0 = no quorum). When
    # fewer clients report by the deadline, the round either commits
    # the renormalized partial cohort and is counted+evented as
    # degraded ('degrade', default — the run never wedges) or is
    # treated as unhealthy and aborted into the supervisor's
    # rollback/retry path ('abort'; requires fault.supervisor)
    avail_quorum_frac: float = 0.0
    avail_quorum_action: str = "degrade"  # 'degrade' | 'abort'
    # -- privacy plane (robustness/privacy.py) --------------------------
    # > 0 arms server-side DP-FedAvg aggregation: per-client L2 clip to
    # dp_clip_norm, then Gaussian noise at stddev
    # dp_noise_multiplier * dp_clip_norm / cohort_k on the weighted
    # estimate, drawn from fold_in(rng_round, DP_SALT). 0 (default) =
    # off: zero extra pytree leaves, round program HLO byte-identical.
    dp_noise_multiplier: float = 0.0
    dp_clip_norm: float = 1.0
    # > 0 arms the epsilon-budget lifecycle: the host-side RDP
    # accountant pre-checks affordability every round and, at
    # exhaustion, either ends the run cleanly at the last affordable
    # round ('stop' -> privacy.budget_exhausted event + 'complete'
    # intent) or continues noise-free ('degrade' -> 'degraded' intent,
    # counted + evented, never wedging). 0 = unlimited budget (the
    # accountant still streams epsilon_spent).
    dp_epsilon_budget: float = 0.0
    dp_delta: float = 1e-5
    dp_budget_action: str = "stop"  # 'stop' | 'degrade'

    @property
    def dp_armed(self) -> bool:
        """True when the DP aggregation stage is traced into the round
        program; disarmed programs stay byte-identical."""
        return self.dp_noise_multiplier > 0.0

    @property
    def avail_armed(self) -> bool:
        """True when any deployment-realism knob changes the traced
        round program; disarmed programs stay byte-identical."""
        return (self.avail_model != "default"
                or self.avail_dropout_rate > 0.0
                or self.over_select_frac > 1.0
                or self.avail_quorum_frac > 0.0)

    @property
    def chaos_enabled(self) -> bool:
        return (self.client_drop_rate > 0.0 or self.straggler_rate > 0.0
                or self.nan_inject_rate > 0.0
                or self.byzantine_rate > 0.0)

    @property
    def host_fault_seam_tuple(self) -> tuple:
        """The armed host seams as a tuple (CLI string split/stripped;
        empty when host chaos is off)."""
        return tuple(s.strip() for s in self.host_fault_seams.split(",")
                     if s.strip())

    @property
    def host_chaos_enabled(self) -> bool:
        return bool(self.host_fault_seam_tuple) \
            and self.host_fault_rate > 0.0


@dataclass(frozen=True)
class TelemetryConfig:
    """Run-telemetry knobs (``fedtorch_tpu.telemetry``,
    docs/observability.md). The subsystem is host-only: no level
    touches a traced program (HLO byte-identical on/off, pinned in
    tests/test_telemetry.py) and every level keeps the per-round
    device-sync count at the loop's one batched fetch."""
    # 'off' = no files, every hook a no-op; 'default' = metrics.jsonl
    # + events.jsonl + health.json + host spans (trace.json exported at
    # run end; measured <= 1% round overhead, TELEMETRY_AB.json);
    # 'debug' additionally re-exports trace.json every 25 rounds so a
    # live Perfetto session can follow a long run.
    level: str = "default"
    # span-buffer bound: past this, new spans are counted as dropped
    # instead of growing host memory on month-long runs
    max_span_events: int = 200_000
    # > 0: the one-shot cost capture additionally AOT-lowers the
    # scan-of-R round-program twin for the active data source
    # (rounds_scan[R] on the device plane, rounds_stream_scan[R] — the
    # scanned streamed program — on the stream plane) into
    # program_costs.json, so the composed builder dispatch is
    # cost-attributed alongside the per-round primary
    # (parallel/round_program.py; telemetry/costs.py). 0 = per-round
    # programs only (the default; the scan twin is a second XLA
    # compile at capture time).
    cost_capture_scan_rounds: int = 0
    # Federation-plane cohort statistics (docs/observability.md
    # "Federation plane"). UNLIKE every other telemetry knob this one
    # changes the traced round/commit program: it adds per-client
    # outputs at the _round_core aggregation seam — online ids, accept
    # /selection masks, per-client suspicion from the robust rule,
    # per-job staleness, update-norm quantiles and the cosine-
    # dispersion heterogeneity gauge — all riding the loop's ONE
    # batched fetch and feeding the per-client ledger
    # (telemetry/ledger.py). Off (default) the program is byte-
    # identical to the pre-cohort engine (the new RoundMetrics fields
    # are None — zero extra outputs); on, it traces once and the
    # trajectory stays bitwise-identical (tests/test_cohort_stats.py).
    cohort_stats: bool = False
    # population threshold/budget of the per-client ledger: at
    # num_clients <= budget the ledger keeps dense per-client numpy
    # counters; above it, count-min participation sketches plus a
    # bounded suspicion top-K — memory stays O(min(C, budget)) at
    # C >= 10^6 (measured in TELEMETRY_AB.json's ledger_memory row).
    ledger_sketch_budget: int = 65536
    # EWMA z-score threshold of the host-side anomaly detector
    # (telemetry/anomaly.py) over the metrics rows (loss, cohort
    # dispersion, guard-reject rate, staleness). Observe-only: it
    # emits `anomaly.detected` events and feeds the report's
    # Federation section, never control flow. 0 disables.
    anomaly_zscore: float = 6.0


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout — replaces the reference's process topology
    (``FCGraph``, utils/topology.py:57-114) with a JAX mesh.

    ``num_devices=None`` means "all visible devices". Clients are laid out
    ``[num_devices, clients_per_device]``; the per-device axis is vmapped,
    the device axis is sharded (SURVEY.md §7 phase 1 / hard part "100+
    clients on a fixed mesh").
    """
    backend: Optional[str] = None  # None = default platform
    num_devices: Optional[int] = None
    axis_name: str = "clients"
    # Multi-host (DCN) initialization; mirrors run_mpi.py's hostfile role.
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # Multi-host bring-up resilience (init_multihost retries transient
    # connect failures): total budget for reaching the coordinator, and
    # the first retry delay (doubles per attempt).
    init_timeout_s: float = 300.0
    init_backoff_s: float = 1.0
    compute_dtype: str = "float32"  # 'bfloat16' for MXU-friendly matmuls
    # Unroll factor for the local-step scan: >1 lets XLA software-
    # pipeline consecutive local steps (more instruction-level overlap,
    # bigger program). The data-dependent step order is preserved;
    # results match the rolled scan to float tolerance (re-fusion of the
    # unrolled body may shift last-ulp rounding).
    scan_unroll: int = 1
    # Per-block rematerialization (jax.checkpoint) for resnet/transformer
    # archs: trade ~1.33x FLOPs for activation memory that scales with
    # one block instead of the depth — the standard TPU HBM lever for
    # deep models / long sequences. Same values, same gradients.
    remat: bool = False
    # Client-axis execution strategy for the per-client model compute
    # inside the jitted round program (docs/performance.md
    # "Client-fused MXU execution"):
    #   'vmap'  — vmap model.apply over the k online clients (each
    #             client's 16-64-channel conv tiles the MXU separately;
    #             the certified round-5 program identity);
    #   'fused' — pack the k clients into the channel axis and run ONE
    #             feature_group_count=k grouped conv per layer (k x the
    #             MXU lanes per pass; numerics-equivalent, pinned by
    #             tests/test_client_fusion.py). Supported for the
    #             resnet-cifar family + cnn with norm='bn' on a
    #             single-device mesh and base-local-step algorithms;
    #             requesting it elsewhere raises with the reason;
    #   'auto'  — currently resolves to 'vmap': the fused lowering is
    #             built and CPU-proven but its on-chip win is still
    #             unmeasured (scripts/mfu_sweep.py fused configs are
    #             armed for the next relay window), and this repo does
    #             not flip defaults ahead of chip data — the conv_impl
    #             lesson (docs/performance.md "Conv-lowering decision").
    client_fusion: str = "auto"
    # Pod-scale client-axis sharding (docs/performance.md "Pod-scale
    # round programs"): shard the k online clients of a round over
    # `client_shards` contiguous device groups — per-shard vmap
    # execution, on-chip partial sums, exactly ONE cross-shard
    # all-reduce at the `_round_core` aggregation seam. 0 (default)
    # keeps the legacy single-shard program byte-identical; 1 arms the
    # hierarchical aggregation seam on an unsharded cohort (the
    # bitwise twin every sharded run is pinned against); S > 1 builds
    # an (S x devices/S) mesh and cuts per-host feed bytes/RAM by S.
    # Must be a power of two <= 64 that divides both the device count
    # and the cohort width; illegal compositions (fused execution,
    # robust rules, cohort stats, ...) are refused by name in
    # `round_program.validate_cell`.
    client_shards: int = 0


@dataclass(frozen=True)
class ExperimentConfig:
    data: DataConfig = field(default_factory=DataConfig)
    federated: FederatedConfig = field(default_factory=FederatedConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    lr_schedule: LRConfig = field(default_factory=LRConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    fault: FaultConfig = field(default_factory=FaultConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    experiment: Optional[str] = None

    def finalize(self) -> "ExperimentConfig":
        """Apply the reference's post-parse derivations & validations
        (parameters.py:245-259)."""
        data, fed = self.data, self.federated
        train, optim = self.train, self.optim

        if data.growing_batch_size and data.base_batch_size is None:
            data = dataclasses.replace(data, base_batch_size=1)

        if data.augment is None:
            # reference default: augmentation ONLY for the cifar family
            # (_get_cifar, prepare_data.py:29-35; _get_stl10 passes the
            # transform through untouched)
            data = dataclasses.replace(
                data, augment=data.dataset in ("cifar10", "cifar100"))

        if fed.federated:
            if data.reshuffle_per_epoch:
                raise ValueError(
                    "Federated mode cannot reshuffle data across clients "
                    "mid-training; set reshuffle_per_epoch=False "
                    "(ref: parameters.py:246-247).")
            # num_epochs = epochs/comm * comms * online rate
            # (parameters.py:248)
            train = dataclasses.replace(
                train,
                num_epochs=int(fed.num_epochs_per_comm * fed.num_comms
                               * fed.online_client_rate))
            if fed.algorithm == "afl":
                # AFL runs exactly one local step per round
                # (parameters.py:249-251).
                fed = dataclasses.replace(fed, sync_type="local_step")
                train = dataclasses.replace(train, local_step=1)
            if fed.algorithm == "qsparse" and not fed.compressed:
                # The reference *intends* this coercion (parameters.py:252
                # has a bug: `args.compressed == True` comparison); we apply
                # the intended semantics.
                fed = dataclasses.replace(fed, compressed=True)
            if fed.quantized and fed.compressed:
                raise ValueError(
                    "Quantization is mutually exclusive with compression "
                    "(ref: parameters.py:254-255).")
            if fed.algorithm in PERSONALIZED_ALGORITHMS and not fed.personal:
                fed = dataclasses.replace(fed, personal=True)
        else:
            if train.num_epochs is None and train.num_iterations is None:
                train = dataclasses.replace(train, num_epochs=10)

        if optim.out_momentum and optim.out_momentum_factor is None:
            # Default out-momentum 1 - 1/n
            # (ref: components/optimizer.py:24-26).
            n = max(fed.num_clients, 1)
            optim = dataclasses.replace(
                optim, out_momentum_factor=1.0 - 1.0 / n)

        if data.data_plane not in ("device", "stream"):
            raise ValueError(
                f"data.data_plane must be 'device' or 'stream', got "
                f"{data.data_plane!r}")
        if data.store not in CLIENT_STORES:
            raise ValueError(
                f"data.store must be one of {CLIENT_STORES}, got "
                f"{data.store!r}")
        if data.store == "mmap":
            if data.data_plane != "stream":
                raise ValueError(
                    "data.store='mmap' is a stream-plane client store "
                    "(the device plane would upload the whole mapped "
                    "population to HBM); set data.data_plane='stream'")
            if not data.store_dir:
                raise ValueError(
                    "data.store='mmap' needs data.store_dir — the "
                    "directory holding the manifest-described shard "
                    "layout (data/streaming.py save_client_store)")
        if fed.participation_mode not in PARTICIPATION_MODES:
            raise ValueError(
                f"federated.participation_mode must be one of "
                f"{PARTICIPATION_MODES}, got {fed.participation_mode!r}")
        if fed.sync_mode not in ("sync", "async"):
            raise ValueError(
                f"federated.sync_mode must be 'sync' or 'async', got "
                f"{fed.sync_mode!r}")
        if fed.sync_mode == "async":
            if not fed.federated:
                raise ValueError(
                    "sync_mode='async' is a federated-server execution "
                    "plane; it requires federated=True")
            if fed.staleness_weight not in ("const", "poly", "inv"):
                raise ValueError(
                    "federated.staleness_weight must be 'const', 'poly' "
                    f"or 'inv', got {fed.staleness_weight!r}")
            if fed.staleness_exponent <= 0.0:
                raise ValueError(
                    "federated.staleness_exponent must be > 0, got "
                    f"{fed.staleness_exponent}")
            if fed.async_buffer_size < 0 or fed.async_concurrency < 0:
                raise ValueError(
                    "federated.async_buffer_size/async_concurrency must "
                    "be >= 0 (0 = auto)")
            if fed.snapshot_ring < 2:
                raise ValueError(
                    "federated.snapshot_ring must be >= 2 (the ring "
                    "holds at least the current and previous commit), "
                    f"got {fed.snapshot_ring}")
        if fed.algorithm not in FEDERATED_ALGORITHMS:
            raise ValueError(f"Unknown federated algorithm {fed.algorithm!r}; "
                             f"expected one of {FEDERATED_ALGORITHMS}")
        if data.dataset not in DATASETS:
            raise ValueError(f"Unknown dataset {data.dataset!r}")
        if self.mesh.scan_unroll < 1:
            raise ValueError(
                f"mesh.scan_unroll must be >= 1, got "
                f"{self.mesh.scan_unroll}")
        if self.model.conv_impl not in ("auto", "conv", "matmul"):
            raise ValueError(
                f"model.conv_impl must be 'auto', 'conv' or 'matmul', "
                f"got {self.model.conv_impl!r}")
        if self.model.attention not in ("auto", "dense", "flash"):
            raise ValueError(
                f"model.attention must be 'auto', 'dense' or 'flash', "
                f"got {self.model.attention!r}")
        if self.mesh.client_fusion not in ("auto", "vmap", "fused"):
            raise ValueError(
                f"mesh.client_fusion must be 'auto', 'vmap' or 'fused', "
                f"got {self.mesh.client_fusion!r}")
        cs = self.mesh.client_shards
        if cs < 0 or cs > 64 or (cs > 0 and cs & (cs - 1)):
            raise ValueError(
                "mesh.client_shards must be 0 (off) or a power of two "
                f"<= 64 (the deterministic aggregation group cap), got "
                f"{cs}")
        flt = self.fault
        for name in ("client_drop_rate", "straggler_rate",
                     "nan_inject_rate", "byzantine_rate"):
            v = getattr(flt, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"fault.{name} must be in [0, 1], got {v}")
        if flt.byzantine_mode not in BYZANTINE_MODES:
            raise ValueError(
                f"fault.byzantine_mode must be one of {BYZANTINE_MODES}, "
                f"got {flt.byzantine_mode!r}")
        if flt.byzantine_scale <= 0.0:
            raise ValueError(
                "fault.byzantine_scale must be > 0, got "
                f"{flt.byzantine_scale}")
        if flt.robust_agg not in ROBUST_AGGREGATORS:
            raise ValueError(
                f"fault.robust_agg must be one of {ROBUST_AGGREGATORS}, "
                f"got {flt.robust_agg!r}")
        if not 0.0 <= flt.robust_trim_frac < 0.5:
            raise ValueError(
                "fault.robust_trim_frac must be in [0, 0.5) (trimming "
                "half or more from each end leaves nothing), got "
                f"{flt.robust_trim_frac}")
        if flt.robust_norm_tau <= 0.0:
            raise ValueError(
                "fault.robust_norm_tau must be > 0, got "
                f"{flt.robust_norm_tau}")
        if flt.robust_agg == "norm_bound" and fed.federated \
                and self.effective_algorithm not in NORM_BOUND_ALGORITHMS:
            raise ValueError(
                "fault.robust_agg='norm_bound' carries a params-shaped "
                "server momentum; algorithm "
                f"{self.effective_algorithm!r} uses a structured payload "
                "tree the momentum cannot live against (supported: "
                f"{', '.join(NORM_BOUND_ALGORITHMS)})")
        if not 0.0 < flt.straggler_step_frac <= 1.0:
            raise ValueError(
                "fault.straggler_step_frac must be in (0, 1], got "
                f"{flt.straggler_step_frac}")
        if flt.guard_mode not in ("reject", "clip"):
            raise ValueError(
                f"fault.guard_mode must be 'reject' or 'clip', got "
                f"{flt.guard_mode!r}")
        if flt.guard_norm_multiplier <= 0.0:
            raise ValueError(
                "fault.guard_norm_multiplier must be > 0, got "
                f"{flt.guard_norm_multiplier}")
        if flt.max_retries < 0:
            raise ValueError(
                f"fault.max_retries must be >= 0, got {flt.max_retries}")
        for seam in flt.host_fault_seam_tuple:
            if seam not in HOST_FAULT_SEAMS:
                raise ValueError(
                    f"fault.host_fault_seams names unknown seam "
                    f"{seam!r}; expected a comma-separated subset of "
                    f"{HOST_FAULT_SEAMS}")
        if not 0.0 <= flt.host_fault_rate <= 1.0:
            raise ValueError(
                "fault.host_fault_rate must be in [0, 1], got "
                f"{flt.host_fault_rate}")
        if flt.host_fault_delay_s < 0.0:
            raise ValueError(
                "fault.host_fault_delay_s must be >= 0, got "
                f"{flt.host_fault_delay_s}")
        if flt.host_fault_max < 0:
            raise ValueError(
                "fault.host_fault_max must be >= 0 (0 = uncapped), got "
                f"{flt.host_fault_max}")
        if flt.host_retry_max < 0:
            raise ValueError(
                f"fault.host_retry_max must be >= 0, got "
                f"{flt.host_retry_max}")
        if flt.host_retry_backoff_s < 0.0:
            raise ValueError(
                "fault.host_retry_backoff_s must be >= 0, got "
                f"{flt.host_retry_backoff_s}")
        if flt.watchdog_timeout_s < 0.0:
            raise ValueError(
                "fault.watchdog_timeout_s must be >= 0 (0 = off), got "
                f"{flt.watchdog_timeout_s}")
        if flt.avail_model not in AVAILABILITY_MODELS:
            raise ValueError(
                f"fault.avail_model must be one of {AVAILABILITY_MODELS}, "
                f"got {flt.avail_model!r}")
        if not 0.0 <= flt.avail_dropout_rate <= 1.0:
            raise ValueError(
                "fault.avail_dropout_rate must be in [0, 1], got "
                f"{flt.avail_dropout_rate}")
        if flt.avail_diurnal_period < 0:
            raise ValueError(
                "fault.avail_diurnal_period must be >= 0 (0 = flat "
                f"fleet), got {flt.avail_diurnal_period}")
        if not 1.0 <= flt.over_select_frac <= 4.0:
            raise ValueError(
                "fault.over_select_frac must be in [1, 4] (dispatching "
                "more than 4x the target cohort pays vmap width for "
                f"nothing), got {flt.over_select_frac}")
        if not 0.0 <= flt.avail_quorum_frac <= 1.0:
            raise ValueError(
                "fault.avail_quorum_frac must be in [0, 1], got "
                f"{flt.avail_quorum_frac}")
        if flt.avail_quorum_action not in ("degrade", "abort"):
            raise ValueError(
                "fault.avail_quorum_action must be 'degrade' or "
                f"'abort', got {flt.avail_quorum_action!r}")
        if flt.avail_quorum_action == "abort" \
                and flt.avail_quorum_frac > 0.0 and not flt.supervisor:
            raise ValueError(
                "fault.avail_quorum_action='abort' routes sub-quorum "
                "rounds into the round supervisor's rollback/retry "
                "path — arm fault.supervisor (or use 'degrade', which "
                "commits the renormalized partial cohort)")
        if flt.dp_noise_multiplier < 0.0:
            raise ValueError(
                "fault.dp_noise_multiplier must be >= 0 (0 = DP off), "
                f"got {flt.dp_noise_multiplier}")
        if flt.dp_armed and flt.dp_clip_norm <= 0.0:
            raise ValueError(
                "fault.dp_clip_norm must be > 0 when DP is armed, got "
                f"{flt.dp_clip_norm}")
        if flt.dp_armed and not 0.0 < flt.dp_delta < 1.0:
            raise ValueError(
                "fault.dp_delta must be in (0, 1) when DP is armed, "
                f"got {flt.dp_delta}")
        if flt.dp_budget_action not in ("stop", "degrade"):
            raise ValueError(
                "fault.dp_budget_action must be 'stop' or 'degrade', "
                f"got {flt.dp_budget_action!r}")
        if flt.dp_epsilon_budget < 0.0:
            raise ValueError(
                "fault.dp_epsilon_budget must be >= 0 (0 = unlimited), "
                f"got {flt.dp_epsilon_budget}")
        if flt.dp_epsilon_budget > 0.0 and not flt.dp_armed:
            raise ValueError(
                "fault.dp_epsilon_budget > 0 without "
                "fault.dp_noise_multiplier > 0: there is no DP "
                "mechanism to budget — arm DP or drop the budget")
        if flt.dp_armed and flt.robust_agg == "norm_bound":
            raise ValueError(
                "fault.dp_noise_multiplier with "
                "fault.robust_agg='norm_bound' double-clips: norm_bound "
                "already radially clips every client toward the server "
                "momentum at a data-dependent radius, which breaks the "
                "fixed-sensitivity bound the DP clip certifies — use a "
                "non-clipping robust rule (trimmed_mean, median, krum) "
                "under DP")
        if flt.dp_armed and fed.federated \
                and self.effective_algorithm not in DP_ALGORITHMS:
            raise ValueError(
                "fault.dp_noise_multiplier clips and noises a single "
                "params-shaped payload tree; algorithm "
                f"{self.effective_algorithm!r} ships a structured "
                "payload the fixed-radius clip is not meaningful "
                f"against (supported: {', '.join(DP_ALGORITHMS)})")
        if fed.sync_mode == "async" and flt.straggler_rate > 0.0 \
                and flt.avail_model == "default" and not flt.avail_armed:
            warnings.warn(
                "async arrivals driven by the legacy straggler-knob "
                "aliasing (fault.straggler_rate reinterpreted as an "
                "arrival tail-delay rate). This spelling is deprecated: "
                "set fault.avail_model='trace' for the deployment-trace "
                "arrival model (docs/robustness.md 'Deployment "
                "realism'). The default model reproduces the legacy "
                "draws bitwise, so existing A/Bs and resumes stay "
                "valid.", FutureWarning, stacklevel=2)
        if self.checkpoint.keep_last_n < 0:
            raise ValueError(
                "checkpoint.keep_last_n must be >= 0 (0 = unlimited), "
                f"got {self.checkpoint.keep_last_n}")
        if self.telemetry.level not in ("off", "default", "debug"):
            raise ValueError(
                "telemetry.level must be 'off', 'default' or 'debug', "
                f"got {self.telemetry.level!r}")
        if self.telemetry.max_span_events < 1:
            raise ValueError(
                "telemetry.max_span_events must be >= 1, got "
                f"{self.telemetry.max_span_events}")
        if self.telemetry.cost_capture_scan_rounds < 0:
            raise ValueError(
                "telemetry.cost_capture_scan_rounds must be >= 0 "
                "(0 = per-round programs only), got "
                f"{self.telemetry.cost_capture_scan_rounds}")
        if self.telemetry.ledger_sketch_budget < 64:
            raise ValueError(
                "telemetry.ledger_sketch_budget must be >= 64 (the "
                "sketch needs a few rows of width to say anything), "
                f"got {self.telemetry.ledger_sketch_budget}")
        if self.telemetry.anomaly_zscore < 0.0:
            raise ValueError(
                "telemetry.anomaly_zscore must be >= 0 (0 = detector "
                f"off), got {self.telemetry.anomaly_zscore}")

        return dataclasses.replace(
            self, data=data, federated=fed, train=train, optim=optim)

    # -- Derived quantities -------------------------------------------------
    @property
    def effective_algorithm(self) -> str:
        """DRFA wraps an inner aggregation algorithm (parameters.py:90-93)."""
        return "drfa" if self.federated.drfa else self.federated.algorithm

    def batches_per_epoch(self, samples_per_client: int) -> int:
        return max(samples_per_client // self.data.batch_size, 1)

    def local_steps_per_round(self, samples_per_client: int) -> int:
        """Fixed trace-time local-step count for one communication round.

        The reference's `while not is_sync_fed` (federated/main.py:83-155)
        has data-dependent bounds; on TPU the loop is a `lax.scan` with a
        static length (SURVEY.md §7 'hard parts'). Epoch-sync mode converts
        to steps exactly like the centered code (nodes_centered.py:47-50).
        """
        if self.federated.sync_type == "epoch":
            return self.batches_per_epoch(samples_per_client) * \
                self.federated.num_epochs_per_comm
        return max(self.train.local_step, 1)
