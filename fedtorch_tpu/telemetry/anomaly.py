"""Host-side anomaly detection over the metrics rows
(docs/observability.md "Federation plane").

A stdlib EWMA z-score detector the CLI loop feeds each completed
metrics row: per watched field it tracks an exponentially-weighted mean
and variance and, once past warmup, flags values more than
``zscore`` standard deviations out — a diverging loss, a dispersion
spike (an attack cohort or a partition shift), a guard-rejection burst,
a staleness runaway. Strictly **observe-only**: anomalies become
``anomaly.detected`` events (and the report tool's Federation section)
and drive NO control flow — the supervisor's rollback/retry machinery
(robustness/supervisor.py) stays the only actor, this is the operator's
smoke alarm.

Emission discipline: one event per field per EXCURSION (the detector
re-arms when the field returns inside the band), capped per field so a
permanently-shifted metric cannot flood ``events.jsonl`` on a
month-long run. The EWMA keeps absorbing every value — including
anomalous ones — so a genuine level shift becomes the new normal
instead of alerting forever.

Stdlib-only (not even numpy): O(fields) floats of state, O(1) per row.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

# metrics-row fields watched by default. ``reject_rate``,
# ``dropout_rate`` and ``deadline_miss_rate`` are derived (count /
# max(n_online, 1)) — the raw counts scale with k and would alias
# cohort-size changes into anomalies.
ANOMALY_FIELDS = ("loss", "cohort_dispersion", "reject_rate",
                  "staleness", "dropout_rate", "deadline_miss_rate",
                  "dp_clipped_frac")


class EwmaAnomalyDetector:
    """Per-field EWMA mean/variance + z-score excursion detection."""

    def __init__(self, zscore: float = 6.0, fields=ANOMALY_FIELDS,
                 alpha: float = 0.1, warmup: int = 10,
                 max_events_per_field: int = 20):
        if zscore <= 0.0:
            raise ValueError(f"zscore must be > 0, got {zscore}")
        self.zscore = float(zscore)
        self.fields = tuple(fields)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.max_events_per_field = int(max_events_per_field)
        # field -> (n, mean, var, in_excursion, emitted)
        self._state: Dict[str, Tuple[int, float, float, bool, int]] = {
            f: (0, 0.0, 0.0, False, 0) for f in self.fields}

    @staticmethod
    def derive(row: Dict) -> Dict[str, float]:
        """The derived fields observed alongside the raw row."""
        out = {}
        if "rejected" in row and "n_online" in row:
            out["reject_rate"] = float(row["rejected"]) \
                / max(float(row["n_online"]), 1.0)
        # availability-lifecycle rates (robustness/availability.py):
        # a dropout or deadline-miss burst is a deployment-health
        # signal even before quorum degrades
        if "avail_dropped" in row and "n_online" in row:
            out["dropout_rate"] = float(row["avail_dropped"]) \
                / max(float(row["n_online"]), 1.0)
        if "deadline_missed" in row and "n_online" in row:
            out["deadline_miss_rate"] = float(row["deadline_missed"]) \
                / max(float(row["n_online"]), 1.0)
        # privacy plane: dp_clipped_frac is already a cohort-size-
        # invariant fraction — a clip-saturation excursion means the
        # update distribution shifted against the fixed dp_clip_norm
        if "dp_clipped_frac" in row:
            out["dp_clipped_frac"] = float(row["dp_clipped_frac"])
        return out

    def observe(self, row: Dict) -> List[Dict]:
        """Feed one metrics row; returns the (possibly empty) list of
        anomaly records — ``{"field", "value", "zscore", "ewma_mean",
        "ewma_std"}`` — for the caller to emit as ``anomaly.detected``
        events. Never raises on missing/odd fields: telemetry must not
        outcrash the loop it watches."""
        values = dict(row)
        values.update(self.derive(row))
        out: List[Dict] = []
        for field in self.fields:
            v = values.get(field)
            if v is None or isinstance(v, bool) \
                    or not isinstance(v, (int, float)):
                continue
            x = float(v)
            n, mean, var, in_exc, emitted = self._state[field]
            std = math.sqrt(max(var, 0.0))
            anomalous = False
            z: Optional[float] = None
            if not math.isfinite(x):
                # a NaN/Inf metric is an anomaly by definition (and
                # must not poison the EWMA below)
                anomalous = n >= self.warmup
            elif n >= self.warmup:
                dev = abs(x - mean)
                if std > 0.0:
                    z = dev / std
                    anomalous = z > self.zscore
                else:
                    # a zero-variance history (e.g. a reject rate that
                    # was 0.0 every round) makes ANY departure
                    # infinitely many sigmas out — z stays None
                    anomalous = dev > max(1e-9 * abs(mean), 1e-12)
            if anomalous and not in_exc \
                    and emitted < self.max_events_per_field:
                out.append({
                    "field": field, "value": x if math.isfinite(x)
                    else repr(x),
                    "zscore": round(z, 2) if z is not None else None,
                    "ewma_mean": round(mean, 6),
                    "ewma_std": round(std, 6)})
                emitted += 1
            if math.isfinite(x):
                # standard EW mean/variance update (West 1979 form);
                # anomalous values are absorbed too — a level shift
                # becomes the new normal instead of alerting forever
                diff = x - mean
                incr = self.alpha * diff
                mean += incr
                var = (1.0 - self.alpha) * (var + diff * incr)
                n += 1
            self._state[field] = (n, mean, var, anomalous, emitted)
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-field detector state for end-of-run reporting."""
        return {
            f: {"observations": n, "ewma_mean": round(mean, 6),
                "ewma_std": round(math.sqrt(max(var, 0.0)), 6),
                "events": emitted}
            for f, (n, mean, var, _exc, emitted) in self._state.items()}


def replay_anomalies(run_dir: str, zscore: float = 6.0,
                     **detector_kwargs) -> Dict:
    """Offline anomaly replay: run a FRESH detector over a recorded
    run dir's ``metrics.jsonl`` (e.g. to re-judge a run at a different
    threshold than the live one, or a run that had the detector off).
    Torn-tail tolerant and restart-stitched via the shared
    ``telemetry.schema`` loader — a truncated final line is counted,
    never raises. Returns ``{"anomalies": [per-row records with the
    round attached], "summary": detector state, "rows": n,
    "torn_lines": n}``."""
    import os

    from fedtorch_tpu.telemetry.schema import load_jsonl, stitch_rows

    _header, records, torn = load_jsonl(
        os.path.join(run_dir, "metrics.jsonl"))
    rows = stitch_rows(records)
    det = EwmaAnomalyDetector(zscore=zscore, **detector_kwargs)
    out: List[Dict] = []
    for row in rows:
        for a in det.observe(row):
            out.append({"round": row.get("round"), **a})
    return {"anomalies": out, "summary": det.summary(),
            "rows": len(rows), "torn_lines": torn}
