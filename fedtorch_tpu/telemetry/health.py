"""Machine-readable per-host liveness: ``health.json``.

Before this file existed, "is the run alive?" was answerable only from
inside the process (the watchdog's in-memory heartbeat) or by regex on
``record0`` timestamps. ``health.json`` makes it a contract external
monitors can poll: one small JSON document per host, atomically
replaced at every round boundary, carrying the round/commit, a
monotonic last-progress stamp, the mean staleness (async plane), and
the process's **exit intent** — so a scraper can distinguish "draining
on SIGTERM" from "wedged in a collective" from "done" without reading
logs.

Atomicity: ``os.replace`` of a fully-written temp file, so a reader
polling mid-write always sees a complete, parseable document (pinned
under the SIGTERM drain drill in tests/test_preemption.py). No fsync:
the file is a liveness signal, not durable state — a host that loses
power stops updating it, which IS the signal, and an fsync per round
would put disk latency on the round clock.
"""
from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, Optional

from fedtorch_tpu.telemetry.schema import HEALTH_SCHEMA, validate_health


def health_path(run_dir: str, process_index: int = 0) -> str:
    """Per-host file name: process 0 owns the plain ``health.json``
    (what single-host monitors poll); peers get an indexed sibling."""
    name = "health.json" if process_index == 0 \
        else f"health.p{process_index}.json"
    return os.path.join(run_dir, name)


class HealthFile:
    """Atomic writer for one host's health document.

    ``update`` is cheap (one json.dumps + tmp write + rename of ~300
    bytes) and failure-tolerant: a full disk or read-only run dir must
    degrade telemetry, never kill training — write errors are counted,
    not raised."""

    def __init__(self, path: str, process_index: int = 0,
                 clock=time.monotonic, min_interval_s: float = 1.0,
                 max_consecutive_errors: int = 3, on_degrade=None):
        self.path = path
        self.process_index = process_index
        self.clock = clock
        # disk-write throttle: a run doing 100+ rounds/s must not pay
        # an atomic file replace per round (~1ms on hardened
        # filesystems — the dominant term of the telemetry A/B before
        # throttling). Liveness monitors poll at >= seconds
        # granularity, so the round field lagging up to this interval
        # costs nothing; INTENT changes always write immediately.
        self.min_interval_s = float(min_interval_s)
        self.write_errors = 0
        self.writes = 0
        self.throttled = 0
        # degrade-to-off (docs/robustness.md "Host plane"): after this
        # many CONSECUTIVE replace failures the writer stops touching
        # the sick filesystem — a silent health file IS the liveness
        # signal a dead disk should produce, and per-round write
        # attempts against it would put its timeouts on the round clock
        self.max_consecutive_errors = int(max_consecutive_errors)
        self.degraded = False
        self._on_degrade = on_degrade
        self._consecutive_errors = 0
        self._last: Dict = {}
        self._last_write_t: Optional[float] = None
        self._last_progress = clock()
        self._last_round: Optional[int] = None

    def update(self, intent: str, round_idx: Optional[int] = None,
               staleness: Optional[float] = None, **extra) -> Dict:
        """Write the document. ``round_idx`` advancing (or first
        appearing) refreshes the monotonic last-progress stamp;
        intent-only updates (e.g. ``drain``) keep it, so staleness
        stays measurable through a drain."""
        now = self.clock()
        if round_idx is not None and round_idx != self._last_round:
            self._last_round = round_idx
            self._last_progress = now
        if (self._last_write_t is not None
                and intent == self._last.get("intent")
                and now - self._last_write_t < self.min_interval_s):
            self.throttled += 1
            return self._last
        doc = {
            "schema": HEALTH_SCHEMA,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "process_index": self.process_index,
            "round": self._last_round if self._last_round is not None
            else -1,
            "intent": intent,
            "updated_unix": time.time(),
            # monotonic stamps let a same-host reader compute
            # time-since-progress without wall-clock skew
            "progress_monotonic": self._last_progress,
            "updated_monotonic": now,
            "since_progress_s": now - self._last_progress,
        }
        if staleness is not None:
            doc["staleness"] = float(staleness)
        doc.update(extra)
        self._last = doc
        self._last_write_t = now
        if self.degraded:
            return doc  # document kept current in memory; disk is off
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            from fedtorch_tpu.telemetry import faults
            faults.check("telemetry.write")
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
            self.writes += 1
            self._consecutive_errors = 0
        except OSError:
            self.write_errors += 1
            self._consecutive_errors += 1
            if self._consecutive_errors >= self.max_consecutive_errors:
                self.degraded = True
                from fedtorch_tpu.telemetry import faults
                faults.note_degraded("telemetry.write")
                if self._on_degrade is not None:
                    try:
                        self._on_degrade(self)
                    except Exception:
                        pass
        return doc

    @property
    def last(self) -> Dict:
        return dict(self._last)


def read_health(run_dir_or_path: str,
                process_index: int = 0) -> Optional[Dict]:
    """Parse a health document; None when missing/unreadable (a
    monitor's absence case, never an exception). A parseable document
    with a bad schema DOES raise — that is a version skew the operator
    must see."""
    path = run_dir_or_path
    if os.path.isdir(path):
        path = health_path(path, process_index)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    validate_health(doc)
    return doc
