"""Telemetry record schemas (docs/observability.md "Metric catalog").

Everything the run emits machine-readably is versioned here: the
``metrics.jsonl`` per-round row, the ``events.jsonl`` event record, and
the ``health.json`` liveness document. Consumers (the ``fedtorch-tpu
report`` tool, external monitors, tests) key on ``SCHEMA`` /
``HEALTH_SCHEMA`` strings instead of sniffing shapes, so a future
breaking change bumps the version and old parsers fail loudly.

Stdlib-only on purpose: the report tool and external monitors must be
able to parse a run dir without initializing JAX.
"""
from __future__ import annotations

import json
from typing import Dict, Iterator, List, Optional, Tuple

# bump ONLY on breaking changes (renamed/retyped required fields);
# adding optional fields is backward-compatible and needs no bump
METRICS_SCHEMA = "fedtorch_tpu.metrics/v1"
EVENTS_SCHEMA = "fedtorch_tpu.events/v1"
HEALTH_SCHEMA = "fedtorch_tpu.health/v1"

# -- the per-round metrics row ------------------------------------------
# Required fields every row carries. All values are host-side Python
# scalars: the row is populated exclusively from the round loop's ONE
# batched scalar fetch (FederatedTrainer.round_host_scalars) plus
# host-only counters — emitting a row costs zero device syncs.
METRICS_REQUIRED = {
    "round": int,        # round index (async: commit version)
    "round_s": float,    # wall-clock of the jitted round/commit call
    "loss": float,       # mean online train loss
    "acc": float,        # mean online train top-1
    "lr": float,         # schedule LR at the round's mean epoch
    "n_online": float,   # online clients this round
    "comm_bytes": float,  # uplink payload volume
}

# Optional gauge groups (absent when the subsystem is off). Names are
# the catalog rendered in docs/observability.md.
METRICS_OPTIONAL = {
    # row stamps (telemetry/metrics.py JsonlWriter — every row since
    # the ops plane; optional so pre-ops run dirs stay valid): `seq`
    # restarts at 0 per writer, so a mid-file seq drop marks an
    # elastic-restart boundary and `t` orders rows across it —
    # cross-restart stitching in compare/watch is unambiguous
    "seq": "monotonic per-writer row sequence (resets on restart)",
    "t": "wall-clock emit time (unix seconds)",
    # robustness counters (chaos/guards; 0-valued when enabled but calm)
    "dropped": "chaos-crashed clients masked out of aggregation",
    "stragglers": "step-budget cuts (async: delayed dispatches)",
    "rejected": "guard-rejected updates",
    "clipped": "guard-norm-clipped updates",
    # byzantine adversary + robust aggregation (robustness/chaos.py,
    # robustness/aggregators.py)
    "byzantine": "adversary-crafted uploads injected this round",
    "robust_selected": "updates the robust aggregation rule kept",
    "robust_trimmed": "updates the robust rule excluded/clipped "
                      "beyond the guards",
    "staleness": "mean snapshot staleness this commit (async plane)",
    # deployment-realism availability lifecycle
    # (robustness/availability.py; docs/robustness.md "Deployment
    # realism")
    "avail_dropped": "mid-round client dropouts (sync lifecycle)",
    "deadline_missed": "late survivors masked after the round closed "
                       "on its first k arrivals (over-selection)",
    "quorum_degraded": "1 when the accepted cohort fell below the "
                       "configured quorum this round",
    "mean_epoch": "mean training epoch over real clients",
    # per-round host phase wall-clock (seconds)
    "fetch_s": "batched scalar-fetch wall (blocks on the round)",
    "eval_s": "server eval wall (eval rounds only)",
    "checkpoint_s": "checkpoint snapshot+dispatch wall (eval rounds)",
    # eval results (eval rounds only; host floats from the eval fetch)
    "test_top1": "server-model test top-1 this eval",
    "best_top1": "best test top-1 so far",
    # stream plane (trainer.stream_stats)
    "stream_depth": "prefetched feeds ready at fetch time",
    "stream_wait_s": "consumer wall blocked on the feed queue (total)",
    "stream_gather_s": "producer schedule+pack wall (total)",
    "stream_h2d_s": "producer device_put dispatch wall (total)",
    "stream_produced": "feeds produced since (re)start",
    "stream_store_resident_mb": "client-store bytes held in host RAM "
                                "(mmap store: sizes vector only)",
    "stream_store_mapped_mb": "client-store bytes memory-mapped from "
                              "disk (0 for the RAM store)",
    # pod-scale client-axis sharding (parallel/podscale.py;
    # docs/performance.md "Pod-scale round programs") — present only
    # when mesh.client_shards arms the sharded seam
    "client_shards": "client-axis shard count S of the armed mesh "
                     "(the round's cohort is split S ways)",
    "cohort_allreduce_bytes": "static [G, P] partial-sum bytes the "
                              "seam's ONE cross-shard all-reduce "
                              "moves per round (stashed at trace "
                              "time)",
    "stream_shard_rows": "cohort rows THIS host's producer packed "
                         "(its owned shard slices; k/S per shard)",
    "stream_shard_pack_s": "producer wall spent packing this host's "
                           "shard rows (per-host scaling gauge)",
    # round-wall critical path (telemetry/critical_path.py;
    # docs/observability.md "Operating and comparing runs")
    "overlap_efficiency": "fraction of this round's producer "
                          "gather+H2D wall hidden under device "
                          "compute (stream plane)",
    # async commit plane (trainer.schedule_stats + staleness histogram)
    "async_dispatches": "client dispatches simulated so far",
    "async_stragglers": "tail-delayed dispatches so far",
    "async_ring_clamped": "arrivals older than the snapshot ring",
    "async_buffer": "buffer size m (updates folded per commit)",
    "async_commit_rate": "commits per virtual time unit so far",
    "async_dropouts": "mid-round dropouts discarded at arrival and "
                      "re-dispatched (availability model)",
    # checkpoint IO (AsyncCheckpointer.stats)
    "ckpt_queue_depth": "writes queued behind the worker",
    "ckpt_writes": "checkpoints durably written so far",
    "ckpt_last_write_s": "serialization+disk wall of the last write",
    "ckpt_total_write_s": "cumulative write wall over the run",
    # checkpoint degraded mode (docs/robustness.md "Host plane")
    "ckpt_degraded": "1 once the async writer fell back to sync "
                     "writes after a lost background write",
    "ckpt_lost_writes": "background checkpoint writes durably lost "
                        "(each emitted a ckpt.degraded event)",
    # supervisor (host counters)
    "sup_rollbacks": "supervisor rollbacks so far",
    "sup_retries": "supervisor retries so far",
    "sup_skipped": "supervisor skipped rounds so far",
    "sup_skipped_fault": "skips caused by divergence or a raising "
                         "round program",
    "sup_skipped_quorum": "skips caused by sub-quorum rounds under "
                          "avail_quorum_action='abort'",
    # host-plane chaos + self-healing (robustness/host_chaos.py,
    # robustness/host_recovery.py; docs/robustness.md "Host plane")
    "host_faults": "injected host-seam faults fired so far (armed "
                   "drills only)",
    "host_retries": "host-seam recovery retries so far (all seams)",
    "host_recovered": "host operations that succeeded after >= 1 "
                      "retry",
    "host_degraded": "host seams currently in degraded mode",
    "stream_rebuilds": "stream feed producers rebuilt via the "
                       "invalidate_stream resync after a death",
    # device-side gauges (telemetry.costs.ProgramCostCapture; present
    # once program_costs.json was captured — docs/observability.md
    # "Device-side")
    "model_flops_utilization": "round-program FLOPs / (round wall x "
                               "peak x chips) — measured MFU fraction",
    "hbm_program_peak_bytes": "compiled round program's static device-"
                              "memory watermark (memory_analysis)",
    "hbm_live_bytes": "live jax.Array bytes at row time "
                      "(live_buffer_summary — metadata walk, no sync)",
    "round_device_min_s": "FLOPs-at-peak device-time floor of the "
                          "captured primary program (the analytic "
                          "lower bound on device-busy seconds)",
    "round_host_frac": "1 - round_device_min_s/round_s — the round-"
                       "wall share NOT explained by the device floor "
                       "(host phases, dispatch gap, sub-peak MXU)",
    # federation-plane cohort statistics (telemetry.cohort_stats;
    # robustness/aggregators.py:cohort_statistics — docs/
    # observability.md "Federation plane")
    "cohort_dispersion": "1 - mean cosine of the accepted unit "
                         "updates vs their weighted mean (the "
                         "heterogeneity gauge)",
    "cohort_norm_min": "min accepted unit-update l2 norm",
    "cohort_norm_q25": "25th-percentile accepted unit-update norm",
    "cohort_norm_med": "median accepted unit-update norm",
    "cohort_norm_q75": "75th-percentile accepted unit-update norm",
    "cohort_norm_max": "max accepted unit-update norm",
    # privacy plane (robustness/privacy.py; docs/robustness.md
    # "Privacy plane") — present only when fault.dp_noise_multiplier
    # arms the DP aggregation stage
    "dp_clipped_frac": "fraction of accepted clients the DP L2 clip "
                       "actually shrank this round",
    "dp_noise_sigma": "applied DP noise stddev on the released "
                      "estimate (0 after a budget 'degrade')",
    "dp_epsilon_spent": "cumulative accounted epsilon at dp_delta "
                        "(host-side RDP accountant)",
    # per-client ledger (telemetry/ledger.py)
    "ledger_tracked": "clients with exact per-client ledger records "
                      "(dense: the population; sketch: the "
                      "suspicion top-K)",
    "ledger_bytes": "ledger host-memory footprint — bounded "
                    "O(min(C, ledger_sketch_budget))",
}

def all_metric_fields() -> frozenset:
    """Every cataloged metrics-row field name (required + optional) —
    the single catalog surface consumers key on. The registry-drift
    checker (``fedtorch_tpu.lint.registry_audit``, FTC001) gates this
    set against the actual emit sites and the docs/observability.md
    tables in tier-1, so a field cannot exist in only one of the
    three places."""
    return frozenset(METRICS_REQUIRED) | frozenset(METRICS_OPTIONAL)


HEALTH_INTENTS = (
    "starting",    # process up, loop not yet entered
    "running",     # making round progress
    "recovering",  # progressing, but a host seam retried this round
    "degraded",    # progressing with >= 1 host seam in degraded mode
    "drain",       # stop agreed; writing the final checkpoint
    "preempted",   # drained and exiting restartable (75)
    "stalled",     # watchdog fired; exiting restartable (75)
    "complete",    # ran to num_comms
    "error",       # round loop raised
)


def validate_metrics_row(row: Dict) -> None:
    """Raise ``ValueError`` when ``row`` violates the v1 contract —
    the schema half of the round-trip test."""
    for key, typ in METRICS_REQUIRED.items():
        if key not in row:
            raise ValueError(f"metrics row missing required {key!r}")
        v = row[key]
        if typ is float and isinstance(v, (int, float)) \
                and not isinstance(v, bool):
            continue
        if typ is int and isinstance(v, int) and not isinstance(v, bool):
            continue
        raise ValueError(
            f"metrics row field {key!r} must be {typ.__name__}, got "
            f"{type(v).__name__} ({v!r})")
    unknown = [k for k in row
               if k not in METRICS_REQUIRED and k not in METRICS_OPTIONAL]
    if unknown:
        raise ValueError(
            f"metrics row carries uncataloged fields {unknown!r} — add "
            "them to telemetry.schema.METRICS_OPTIONAL (the catalog is "
            "the contract docs/observability.md renders)")


def validate_health(doc: Dict) -> None:
    if doc.get("schema") != HEALTH_SCHEMA:
        raise ValueError(
            f"health schema {doc.get('schema')!r} != {HEALTH_SCHEMA!r}")
    for key in ("pid", "host", "round", "intent", "updated_unix",
                "progress_monotonic"):
        if key not in doc:
            raise ValueError(f"health.json missing required {key!r}")
    if doc["intent"] not in HEALTH_INTENTS:
        raise ValueError(f"unknown health intent {doc['intent']!r} "
                         f"(expected one of {HEALTH_INTENTS})")


def iter_jsonl(path: str, on_torn=None) -> Iterator[Dict]:
    """Yield one dict per line; the header line (``{"schema": ...}``)
    is included — callers filter on the ``"schema"`` key. A torn
    partial line (crash/preemption mid-append — normally the file's
    last line, but an elastic restart can bury one mid-file) is
    skipped, not fatal: every COMPLETE line was written atomically
    enough (single ``write`` of a line under append mode) to parse.
    ``on_torn(line)``, when given, is called once per skipped line so
    readers surface a COUNTED warning instead of silently dropping."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                if on_torn is not None:
                    on_torn(line)
                continue


def load_jsonl(path: str) -> Tuple[Optional[Dict], List[Dict], int]:
    """``(header, records, torn_lines)`` — the whole-file form every
    offline reader (report / compare / runs registry / anomaly replay)
    shares, so torn-tail tolerance and its counted warning cannot be
    implemented five slightly-different ways. ``header`` is the first
    record carrying a ``schema`` key (None for headerless files);
    later ``schema`` records (an elastic restart appending a fresh
    header) are dropped from ``records`` too."""
    torn = [0]

    def _count(_line: str) -> None:
        torn[0] += 1

    header: Optional[Dict] = None
    records: List[Dict] = []
    for rec in iter_jsonl(path, on_torn=_count):
        if "schema" in rec:
            if header is None:
                header = rec
            continue
        records.append(rec)
    return header, records, torn[0]


def count_restarts(records: List[Dict]) -> int:
    """Elastic-restart boundaries in a stitched row stream: each time
    the per-writer ``seq`` stamp drops, a fresh writer appended to the
    same file. Rows without ``seq`` (pre-ops runs) contribute no
    boundaries."""
    restarts = 0
    prev = None
    for rec in records:
        seq = rec.get("seq")
        if not isinstance(seq, int) or isinstance(seq, bool):
            continue
        # within one writer seq is STRICTLY increasing, so a repeat is
        # a boundary too (a pre-crash writer that flushed exactly one
        # row hands seq 0 to the restart's first seq 0)
        if prev is not None and seq <= prev:
            restarts += 1
        prev = seq
    return restarts


def stitch_rows(records: List[Dict], key: str = "round") -> List[Dict]:
    """Cross-restart stitching: an elastic restart resumes from the
    last durable checkpoint, so the re-run rounds appear twice in the
    appended stream. The LAST occurrence of each ``key`` wins (file
    order — the re-run row supersedes the pre-crash one), and the
    result is sorted by ``key``. Rows missing ``key`` are dropped."""
    by_key: Dict = {}
    for rec in records:
        k = rec.get(key)
        if isinstance(k, (int, float)) and not isinstance(k, bool):
            by_key[k] = rec
    return [by_key[k] for k in sorted(by_key)]


def read_header(path: str) -> Optional[Dict]:
    for rec in iter_jsonl(path):
        return rec if "schema" in rec else None
    return None
