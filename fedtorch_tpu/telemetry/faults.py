"""Seam hooks connecting the telemetry writers to the host-chaos layer.

The telemetry package must stay stdlib-only (the report tool and
external monitors parse run dirs without a backend), so it cannot
import ``fedtorch_tpu.robustness`` — whose package init pulls the
jax-backed chaos/guard modules. This tiny registry inverts the
dependency: the host-fault injector (``robustness/host_chaos.py``)
registers a *check* hook here when it installs, and the recovery
recorder (``robustness/host_recovery.py``) registers a *degrade sink*;
the writers call :func:`check`/:func:`note_degraded` unconditionally,
which compile to a None-test when nothing is armed.

* :func:`check` — called inside each writer's try block, so an
  injected ``OSError`` flows through the SAME error handling a real
  full disk would exercise (the point of the drill).
* :func:`note_degraded` — called once when a writer gives up (too many
  consecutive failures), so the run's degraded-seam set and the
  ``health.json`` ``degraded`` intent see it.
"""
from __future__ import annotations

from typing import Callable, Optional

_check_hook: Optional[Callable[[str], None]] = None
_degrade_sink: Optional[Callable[[str], None]] = None


def set_check_hook(fn: Optional[Callable[[str], None]]) -> None:
    """Install (or clear, with None) the fault-injection check hook."""
    global _check_hook
    _check_hook = fn


def set_degrade_sink(fn: Optional[Callable[[str], None]]) -> None:
    """Install (or clear, with None) the degraded-seam sink."""
    global _degrade_sink
    _degrade_sink = fn


def check(seam: str) -> None:
    """Give an armed injector the chance to raise at ``seam``. Called
    inside the writer's own try block — injected faults exercise the
    real recovery path, not a parallel one."""
    if _check_hook is not None:
        _check_hook(seam)


def note_degraded(seam: str) -> None:
    """Report that the subsystem owning ``seam`` degraded itself."""
    if _degrade_sink is not None:
        _degrade_sink(seam)
