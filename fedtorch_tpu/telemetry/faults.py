"""Seam hooks connecting the telemetry writers to the host-chaos layer.

The telemetry package must stay stdlib-only (the report tool and
external monitors parse run dirs without a backend), so it cannot
import ``fedtorch_tpu.robustness`` — whose package init pulls the
jax-backed chaos/guard modules. This tiny registry inverts the
dependency: the host-fault injector (``robustness/host_chaos.py``)
registers a *check* hook here when it installs, and the recovery
recorder (``robustness/host_recovery.py``) registers a *degrade sink*;
the writers call :func:`check`/:func:`note_degraded` unconditionally,
which compile to a None-test when nothing is armed.

* :func:`check` — called inside each writer's try block, so an
  injected ``OSError`` flows through the SAME error handling a real
  full disk would exercise (the point of the drill).
* :func:`note_degraded` — called once when a writer gives up (too many
  consecutive failures), so the run's degraded-seam set and the
  ``health.json`` ``degraded`` intent see it.
* :func:`new_lock` — the lock factory the host-plane subsystems create
  their mutexes through. Unarmed it returns a plain
  ``threading.Lock`` (zero overhead); the runtime lock-order sentinel
  (``fedtorch_tpu.utils.lock_sentinel`` — which lives on the jax side
  and therefore cannot be imported from here) registers a factory hook
  while armed, so every lock created inside its scope is instrumented
  with a stable name and per-thread acquisition-order recording.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

_check_hook: Optional[Callable[[str], None]] = None
_degrade_sink: Optional[Callable[[str], None]] = None
_lock_hook: Optional[Callable[[str], object]] = None


def set_check_hook(fn: Optional[Callable[[str], None]]) -> None:
    """Install (or clear, with None) the fault-injection check hook."""
    global _check_hook
    _check_hook = fn


def set_degrade_sink(fn: Optional[Callable[[str], None]]) -> None:
    """Install (or clear, with None) the degraded-seam sink."""
    global _degrade_sink
    _degrade_sink = fn


def check(seam: str) -> None:
    """Give an armed injector the chance to raise at ``seam``. Called
    inside the writer's own try block — injected faults exercise the
    real recovery path, not a parallel one."""
    if _check_hook is not None:
        _check_hook(seam)


def note_degraded(seam: str) -> None:
    """Report that the subsystem owning ``seam`` degraded itself."""
    if _degrade_sink is not None:
        _degrade_sink(seam)


def set_lock_hook(fn: Optional[Callable[[str], object]]):
    """Install (or clear, with None) the named-lock factory hook.
    Returns the previously installed hook so a scoped sentinel can
    chain/restore it on exit."""
    global _lock_hook
    prev = _lock_hook
    _lock_hook = fn
    return prev


def new_lock(name: str):
    """A mutex for the host-plane subsystem that names it. Plain
    ``threading.Lock`` unless a lock-order sentinel armed the factory
    hook — then an instrumented wrapper recording acquisition order
    under ``name``."""
    if _lock_hook is not None:
        return _lock_hook(name)
    return threading.Lock()
