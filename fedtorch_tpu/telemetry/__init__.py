"""Unified run telemetry (docs/observability.md).

Three pillars, one subsystem:

* **Structured metrics/events** — schema-versioned ``metrics.jsonl`` /
  ``events.jsonl`` per run dir, populated exclusively from host-side
  values (the round loop's ONE batched scalar fetch plus host
  counters): zero added device syncs, FTL001-clean by construction.
* **Host-span tracing** — ``telemetry.span("h2d", round=r)`` records
  host phases into a Chrome trace-event ``trace.json`` (Perfetto),
  with lanes for the CLI loop, the stream-feed producer, and the
  async checkpoint writer.
* **Machine-readable health** — the atomically-replaced per-host
  ``health.json`` (round, intent, monotonic last-progress) consumed by
  the watchdog, the restart harness, and external monitors.

The package is stdlib-only (no jax import): the ``fedtorch-tpu
report`` tool and external monitors can parse a run dir without
initializing a backend, and importing the hooks into hot modules costs
nothing.

Library-code usage (no Telemetry object in scope)::

    from fedtorch_tpu import telemetry

    with telemetry.span("stream.gather", round=r):
        ...                      # no-op unless a run installed one
    telemetry.event("supervisor.rollback", round=r, attempt=a)
"""
from __future__ import annotations

from fedtorch_tpu.telemetry.anomaly import (  # noqa: F401
    ANOMALY_FIELDS, EwmaAnomalyDetector, replay_anomalies,
)
from fedtorch_tpu.telemetry.costs import (  # noqa: F401
    PROGRAM_COSTS_SCHEMA, ProgramCostCapture, program_costs_path,
    read_program_costs, resolve_peak_tflops, validate_program_costs,
)
from fedtorch_tpu.telemetry.critical_path import (  # noqa: F401
    StreamOverlapTracker, overlap_efficiency, overlap_summary,
    round_wall_decomposition,
)
from fedtorch_tpu.telemetry.ledger import (  # noqa: F401
    LEDGER_SCHEMA, ClientLedger, ledger_path, read_client_ledger,
    suspicion_ranking, validate_client_ledger,
)
from fedtorch_tpu.telemetry.health import (  # noqa: F401
    HealthFile, health_path, read_health,
)
from fedtorch_tpu.telemetry.metrics import JsonlWriter  # noqa: F401
from fedtorch_tpu.telemetry.runtime import (  # noqa: F401
    LEVELS, Telemetry, get_active,
)
from fedtorch_tpu.telemetry.schema import (  # noqa: F401
    EVENTS_SCHEMA, HEALTH_INTENTS, HEALTH_SCHEMA, METRICS_OPTIONAL,
    METRICS_REQUIRED, METRICS_SCHEMA, count_restarts, iter_jsonl,
    load_jsonl, read_header, stitch_rows, validate_health,
    validate_metrics_row,
)
from fedtorch_tpu.telemetry.spans import (  # noqa: F401
    NULL_SPAN, SpanRecorder,
)


def span(name: str, **args):
    """Module-level span hook: records on the active run's recorder,
    or returns the shared no-op context when telemetry is off."""
    t = get_active()
    if t is None:
        return NULL_SPAN
    return t.span(name, **args)


def event(name: str, **fields) -> None:
    t = get_active()
    if t is not None:
        t.event(name, **fields)


def instant(name: str, **args) -> None:
    t = get_active()
    if t is not None:
        t.instant(name, **args)
