"""Cross-run registry: index run dirs into ``runs_index.json``.

A capture season leaves dozens of run dirs (telemetry captures,
A/B legs, chaos drills, elastic-restart trees) that until now were
compared by eyeball over ad-hoc ``ls``+``report`` loops. The registry
makes the population a queryable document: one schema-versioned record
per run dir — config header, final metrics, round rate, event/anomaly
counts, program-cost summary, ledger top-suspicion, health outcome,
torn-line/restart counts — written atomically to ``<root>/
runs_index.json`` and listed/filtered by ``fedtorch-tpu runs``.

Stdlib-only and NEVER imports jax (the ``tools/report.py`` rule,
asserted in tests): a monitor box indexes a mounted artifact tree.
Broken run dirs become records with an ``error`` field, not
exceptions — an index that dies on one torn dir indexes nothing.

Usage::

    fedtorch-tpu runs <root> [--json] [--filter k=v ...] [--no-write]
    python -m fedtorch_tpu.telemetry.runs <root>
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

RUNS_INDEX_SCHEMA = "fedtorch_tpu.runs_index/v1"
RUNS_INDEX_NAME = "runs_index.json"

# any of these makes a directory a run dir (metrics-first; health-only
# covers a run that died before its first flush; record0 covers the
# legacy pre-telemetry trees the report tool still renders)
RUN_DIR_MARKERS = ("metrics.jsonl", "health.json", "record0")


def is_run_dir(path: str) -> bool:
    return os.path.isdir(path) and any(
        os.path.exists(os.path.join(path, m)) for m in RUN_DIR_MARKERS)


def scan_run_dirs(root: str) -> List[str]:
    """Run dirs under ``root``: the root itself when it IS one, else
    its direct children (sorted) — the layout every capture script
    produces (``artifacts/<run>``, ``checkpoint/<run>``)."""
    if is_run_dir(root):
        return [root]
    out = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    for name in entries:
        p = os.path.join(root, name)
        if is_run_dir(p):
            out.append(p)
    return out


def index_run(run_dir: str) -> Dict:
    """One registry record. Absorbs every per-dir failure into an
    ``error`` field: the index must survive any single broken dir."""
    rec: Dict = {"name": os.path.basename(os.path.normpath(run_dir)),
                 "path": run_dir}
    try:
        from fedtorch_tpu.tools.report import summarize
        s = summarize(run_dir)
    except Exception as e:  # noqa: BLE001 — record, don't raise
        rec["error"] = f"{type(e).__name__}: {e}"[:200]
        return rec
    rec["source"] = s.get("source")
    rec["meta"] = s.get("meta") or {}
    rec["rounds"] = s.get("rounds", 0)
    for key in ("first_round", "last_round", "round_s_mean_steady",
                "rounds_per_s_steady", "comm_bytes_total",
                "final_loss", "final_acc", "final_test_top1",
                "best_test_top1", "torn_lines", "restarts"):
        if key in s:
            rec[key] = s[key]
    h = s.get("health")
    if h:
        rec["health"] = {"intent": h.get("intent"),
                         "round": h.get("round"),
                         "updated_unix": h.get("updated_unix")}
    ev = s.get("events") or {}
    if ev:
        rec["events_total"] = int(sum(
            v for k, v in ev.items() if isinstance(v, (int, float))))
        rec["anomalies"] = int(ev.get("anomaly.detected", 0))
    fed = s.get("federation") or {}
    led = fed.get("ledger") or {}
    if led.get("top_suspicion"):
        cid, sus = led["top_suspicion"][0]
        rec["ledger_top_suspicion"] = [cid, sus]
    ov = s.get("overlap")
    if ov:
        rec["overlap_efficiency_mean"] = ov["mean"]
    gauges = s.get("last_gauges") or {}
    cp = s.get("critical_path") or {}
    pc = s.get("program_costs")
    if pc is not None:
        # already read + validated by summarize — no second parse
        rec["program_costs"] = {
            "primary": pc.get("primary"), "backend": pc.get("backend"),
            "flops": pc.get("flops"),
            "peak_hbm_bytes": pc.get("peak_hbm_bytes"),
        }
    for key in ("model_flops_utilization", "hbm_program_peak_bytes"):
        if key in gauges:
            rec[key] = gauges[key]
    if "host_frac" in cp:
        rec["round_host_frac"] = cp["host_frac"]
    return rec


def build_index(root: str, write: bool = True,
                out_path: Optional[str] = None) -> Dict:
    """The whole index document; atomically written to
    ``<root>/runs_index.json`` unless ``write`` is False."""
    doc = {
        "schema": RUNS_INDEX_SCHEMA,
        "created_unix": time.time(),
        "root": root,
        "runs": [index_run(d) for d in scan_run_dirs(root)],
    }
    if write:
        path = out_path or os.path.join(root, RUNS_INDEX_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:
            # a read-only artifact mount still lists; note, don't die
            doc["write_error"] = str(e)
    return doc


def validate_runs_index(doc: Dict) -> None:
    if doc.get("schema") != RUNS_INDEX_SCHEMA:
        raise ValueError(
            f"runs_index schema {doc.get('schema')!r} != "
            f"{RUNS_INDEX_SCHEMA!r}")
    if not isinstance(doc.get("runs"), list):
        raise ValueError("runs_index 'runs' must be a list of records")


def load_index(root_or_path: str) -> Dict:
    path = root_or_path
    if os.path.isdir(path):
        path = os.path.join(path, RUNS_INDEX_NAME)
    with open(path) as f:
        doc = json.load(f)
    validate_runs_index(doc)
    return doc


def _lookup(rec: Dict, dotted: str):
    cur = rec
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def match_filters(rec: Dict, filters: List[str]) -> bool:
    """Each filter is ``dotted.key=value``: numeric values compare
    ==, strings compare case-insensitive substring (so
    ``meta.algorithm=fed`` matches fedavg and fedadam). A record
    missing the key does not match."""
    for f in filters:
        key, _, want = f.partition("=")
        have = _lookup(rec, key.strip())
        if have is None:
            return False
        want = want.strip()
        if isinstance(have, bool):
            if want.lower() not in (str(have).lower(), str(int(have))):
                return False
        elif isinstance(have, (int, float)):
            try:
                if float(want) != float(have):
                    return False
            except ValueError:
                return False
        elif want.lower() not in str(have).lower():
            return False
    return True


def _fmt(v, width: int) -> str:
    if v is None:
        s = "-"
    elif isinstance(v, float):
        s = f"{v:.4g}"
    else:
        s = str(v)
    return s[:width].ljust(width)


def render_index(doc: Dict, runs: Optional[List[Dict]] = None) -> str:
    runs = doc["runs"] if runs is None else runs
    lines = [f"runs index: {doc.get('root')}  ({len(runs)} run(s), "
             f"schema {doc.get('schema')})"]
    header = ("name", "rounds", "intent", "acc", "test_top1",
              "r/s", "mfu", "ovl", "anom", "torn")
    widths = (24, 6, 10, 7, 9, 8, 7, 5, 5, 5)
    lines.append("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in runs:
        if "error" in r:
            lines.append(f"{_fmt(r.get('name'), 24)}  "
                         f"unreadable: {r['error']}")
            continue
        h = r.get("health") or {}
        row = (r.get("name"), r.get("rounds"), h.get("intent"),
               r.get("final_acc"), r.get("final_test_top1"),
               r.get("rounds_per_s_steady"),
               r.get("model_flops_utilization"),
               r.get("overlap_efficiency_mean"),
               r.get("anomalies"), r.get("torn_lines"))
        lines.append("  ".join(_fmt(v, w) for v, w in zip(row, widths)))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fedtorch-tpu runs",
        description="Index run dirs under a root into runs_index.json "
                    "and list/filter them (docs/observability.md "
                    "'Operating and comparing runs')")
    p.add_argument("root", help="directory holding run dirs (or one "
                                "run dir)")
    p.add_argument("--filter", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="dotted-key filter, repeatable (e.g. "
                        "meta.algorithm=fedavg health.intent=complete)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the (filtered) index document as JSON")
    p.add_argument("--no-write", action="store_true",
                   help="list without (re)writing runs_index.json")
    args = p.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"runs: {args.root}: not a directory", file=sys.stderr)
        return 2
    doc = build_index(args.root, write=not args.no_write)
    runs = [r for r in doc["runs"] if match_filters(r, args.filter)]
    if args.as_json:
        out = dict(doc, runs=runs)
        print(json.dumps(out, indent=2, sort_keys=True, default=str))
    else:
        print(render_index(doc, runs))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
