"""Host-span tracing exported as Chrome trace-event JSON.

The profiler (``utils.tracing.capture_round_trace``) attributes time
*inside* one XLA program; what it cannot see is the host side of a
round — schedule replay, feed gather, H2D dispatch, the dispatch gap
between rounds, scalar fetch, eval, checkpoint IO. Those phases are
exactly where ~90% of the north-star round's wall-time hides
(docs/performance.md §headroom), and :class:`SpanRecorder` makes them
visible facts: every instrumented host phase becomes a complete event
(``ph: "X"``) in a ``trace.json`` loadable in Perfetto / chrome://
tracing, with thread lanes for the CLI loop, the stream-feed producer,
and the async checkpoint writer.

Overhead discipline: opening+closing a span is two
``time.perf_counter_ns`` calls and one ``list.append`` (GIL-atomic, so
producer/writer threads record without locks) — sub-microsecond,
measured end-to-end by ``scripts/telemetry_bench.py``. The buffer is
bounded (``max_events``); past the cap new spans are counted as
dropped instead of growing without bound on month-long runs.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class _Span:
    """Reusable context manager for one span (allocation-light: one
    object per ``span()`` call, no closure)."""

    __slots__ = ("_rec", "name", "args", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, args: Optional[Dict]):
        self._rec = rec
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        self._rec._record(self.name, self._t0, time.perf_counter_ns(),
                          self.args)


class _NullSpan:
    """The disabled path: one shared instance, empty enter/exit."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


NULL_SPAN = _NullSpan()


class SpanRecorder:
    """In-memory span buffer with a Chrome trace-event exporter.

    ``ts``/``dur`` are microseconds relative to the recorder's creation
    (the Chrome format treats the origin as arbitrary); the absolute
    wall-clock origin is recorded as trace metadata so spans can be
    correlated with profiler captures and log timestamps.
    """

    def __init__(self, max_events: int = 200_000,
                 pid: Optional[int] = None):
        self.pid = pid if pid is not None else os.getpid()
        self.max_events = int(max_events)
        self.origin_ns = time.perf_counter_ns()
        self.origin_unix = time.time()
        self.dropped = 0
        self._events: List[tuple] = []  # (name, t0, t1, tid, args)
        self._instants: List[tuple] = []  # (name, t, tid, args)
        # tid -> thread name, captured at RECORD time: worker threads
        # (the stream producer, the checkpoint writer) exit before the
        # run-end export, when threading.enumerate() can no longer
        # name them — their lanes must not degrade to "thread-<id>"
        self._names: Dict[int, str] = {}

    # -- recording ------------------------------------------------------
    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def _record(self, name, t0, t1, args) -> None:
        if len(self._events) >= self.max_events:
            self.dropped += 1
            return
        tid = threading.get_ident()
        if tid not in self._names:
            self._names[tid] = threading.current_thread().name
        self._events.append((name, t0, t1, tid, args))

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (``ph: "i"``) — used for correlating
        external windows (profiler captures) and one-shot events."""
        if len(self._instants) >= self.max_events:
            self.dropped += 1
            return
        tid = threading.get_ident()
        if tid not in self._names:
            self._names[tid] = threading.current_thread().name
        self._instants.append((name, time.perf_counter_ns(), tid,
                               args or None))

    def __len__(self) -> int:
        return len(self._events) + len(self._instants)

    # -- export ---------------------------------------------------------
    def _us(self, t_ns: int) -> float:
        return (t_ns - self.origin_ns) / 1e3

    def to_trace_events(self) -> List[Dict]:
        """The Chrome trace-event list (JSON-ready dicts)."""
        # thread-name metadata: Perfetto renders these as lane labels
        # (record-time capture in self._names; live threads refresh it
        # in case one was renamed)
        names = dict(self._names)
        names.update({t.ident: t.name for t in threading.enumerate()})
        tids = {tid for *_, tid, _ in self._events} \
            | {tid for _, _, tid, _ in self._instants}
        out: List[Dict] = [
            {"name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
             "args": {"name": "fedtorch_tpu host"}},
        ]
        for tid in sorted(tids):
            out.append({"name": "thread_name", "ph": "M", "pid": self.pid,
                        "tid": tid,
                        "args": {"name": names.get(tid, f"thread-{tid}")}})
        for name, t0, t1, tid, args in self._events:
            ev = {"name": name, "cat": "host", "ph": "X",
                  "ts": self._us(t0), "dur": (t1 - t0) / 1e3,
                  "pid": self.pid, "tid": tid}
            if args:
                ev["args"] = args
            out.append(ev)
        for name, t, tid, args in self._instants:
            ev = {"name": name, "cat": "host", "ph": "i", "s": "p",
                  "ts": self._us(t), "pid": self.pid, "tid": tid}
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def export(self, path: str) -> int:
        """Write the Perfetto-loadable trace file; returns the event
        count. Atomic (tmp + rename) so a crash mid-export never leaves
        a torn file where a monitor expects JSON."""
        doc = {
            "traceEvents": self.to_trace_events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "origin_unix": self.origin_unix,
                "dropped_spans": self.dropped,
            },
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(self)
