"""Round-wall critical-path attribution (docs/observability.md
"Operating and comparing runs").

Two derivations the telemetry records but never computed, both pure
arithmetic over already-recorded host values — stdlib-only, zero
device syncs, shared by the live round loop (``cli.run_experiment``),
``fedtorch-tpu report`` and ``fedtorch-tpu compare``:

* :func:`overlap_efficiency` — the stream plane's missing number
  (ROADMAP item 1): what fraction of the producer's gather+H2D wall
  actually hid under device compute this round. STREAM_AB still shows
  stream 1.15x slower than device-resident at C=100; this gauge says
  per-round whether the overlap is working or the producer is the
  round clock.
* :func:`round_wall_decomposition` — the host/device split of the
  round wall (ROADMAP item 3): joins the per-round span walls the
  metrics rows carry with the captured program costs'
  FLOPs-at-peak device-time floor, so "certified MFU 3.37%" becomes
  "the wall is X device-floor + Y host phases + Z unattributed".

The producer accounting: ``StreamFeedProducer.stats`` exposes the
cumulative producer gather/H2D-dispatch wall and the cumulative
consumer queue-wait. Producer work that did NOT hide under compute
surfaces as consumer wait (the tf.data input-stall signal, Murray et
al. 2021) — so per round,

    hidden = max(d_gather + d_h2d - d_wait, 0)
    overlap_efficiency = hidden / (d_gather + d_h2d)

clamped to [0, 1]. A round where the producer did no work has no
defined efficiency (``None``, not 1.0 — an idle producer is not a
perfectly-overlapped one).
"""
from __future__ import annotations

from typing import Dict, List, Optional

# metrics-row keys the per-round delta derivation consumes (cumulative
# counters, StreamFeedProducer.stats)
STREAM_CUMULATIVE_KEYS = ("stream_gather_s", "stream_h2d_s",
                          "stream_wait_s")


def overlap_efficiency(gather_s: float, h2d_s: float,
                       wait_s: float) -> Optional[float]:
    """Fraction of one round's producer wall (gather + H2D dispatch)
    hidden under device compute, clamped to [0, 1]; ``None`` when the
    producer did no work this round (no wall to hide). ``wait_s``
    exceeding the producer wall (the consumer also waited on a stall
    that wasn't producer work — a rebuild, a retry backoff) clamps to
    0: nothing provably hid."""
    producer_wall = float(gather_s) + float(h2d_s)
    if producer_wall <= 0.0:
        return None
    hidden = producer_wall - max(float(wait_s), 0.0)
    return min(max(hidden / producer_wall, 0.0), 1.0)


class StreamOverlapTracker:
    """Per-round :func:`overlap_efficiency` from the CUMULATIVE
    producer gauges the metrics row already carries. The CLI loop
    feeds it each round's gauge dict; report/compare replay it over
    recorded rows. A cumulative counter going backwards (producer
    rebuild, elastic restart re-zeroing `.stats`) resets the baseline
    instead of producing a negative delta."""

    def __init__(self):
        self._prev: Optional[Dict[str, float]] = None

    def observe(self, gauges: Dict) -> Optional[float]:
        """One round's gauge dict (any dict containing the cumulative
        ``stream_gather_s``/``stream_h2d_s``/``stream_wait_s`` keys);
        returns this round's overlap efficiency or ``None`` (non-stream
        row, first row, counter reset, idle producer)."""
        try:
            cur = {k: float(gauges[k]) for k in STREAM_CUMULATIVE_KEYS}
        except (KeyError, TypeError, ValueError):
            return None
        prev, self._prev = self._prev, cur
        if prev is None:
            return None
        deltas = {k: cur[k] - prev[k] for k in STREAM_CUMULATIVE_KEYS}
        if any(d < 0.0 for d in deltas.values()):
            # counters re-zeroed under us: new producer / restart —
            # this round's delta is unattributable
            return None
        return overlap_efficiency(deltas["stream_gather_s"],
                                  deltas["stream_h2d_s"],
                                  deltas["stream_wait_s"])


def replay_overlap(rows: List[Dict]) -> List[Optional[float]]:
    """Per-row overlap efficiency over recorded metrics rows: the
    row's own ``overlap_efficiency`` gauge when the run emitted it
    (post-ops-plane runs), else re-derived from the cumulative
    counters (older runs) — one entry per row, ``None`` where
    undefined."""
    tracker = StreamOverlapTracker()
    out: List[Optional[float]] = []
    for row in rows:
        derived = tracker.observe(row)
        emitted = row.get("overlap_efficiency")
        out.append(float(emitted) if isinstance(emitted, (int, float))
                   and not isinstance(emitted, bool) else derived)
    return out


def _counter_total(rows: List[Dict], key: str) -> float:
    """Total accumulated by a CUMULATIVE per-writer counter across the
    whole (possibly restart-stitched) row stream: segment-aware, so a
    counter that re-zeroes mid-run (elastic restart, producer rebuild)
    contributes every segment's growth instead of only the last
    segment's final value."""
    total = 0.0
    prev = None
    for r in rows:
        v = r.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        v = float(v)
        # a drop starts a fresh writer counting from 0
        total += v if (prev is None or v < prev) else v - prev
        prev = v
    return total


def overlap_summary(rows: List[Dict]) -> Optional[Dict]:
    """Run-level overlap statistics for report/compare: mean/min/last
    efficiency over the rounds where it is defined, plus the producer
    wall and the exposed (unhidden) share of it — both reset-aware
    across elastic restarts (``_counter_total``). ``None`` for
    non-stream runs."""
    effs = [e for e in replay_overlap(rows) if e is not None]
    if not effs:
        return None
    producer_wall = _counter_total(rows, "stream_gather_s") \
        + _counter_total(rows, "stream_h2d_s")
    wait = _counter_total(rows, "stream_wait_s")
    return {
        "rounds": len(effs),
        "mean": sum(effs) / len(effs),
        "min": min(effs),
        "last": effs[-1],
        "producer_wall_s": producer_wall,
        "consumer_wait_s": wait,
        "exposed_frac": min(wait / producer_wall, 1.0)
        if producer_wall > 0 else 0.0,
    }


def device_floor_s(costs_doc: Optional[Dict]) -> Optional[float]:
    """The primary program's FLOPs-at-peak device-time floor (seconds)
    from a ``program_costs.json`` document — the analytic lower bound
    on device-busy time per round. ``None`` when the capture has no
    usable primary FLOPs."""
    if not costs_doc:
        return None
    primary = (costs_doc.get("programs") or {}).get(
        costs_doc.get("primary"))
    if not primary:
        return None
    flops = primary.get("flops")
    peak = costs_doc.get("peak_tflops_per_chip")
    chips = costs_doc.get("num_devices") or 1
    if not flops or not peak:
        return None
    return float(flops) / (float(peak) * 1e12 * float(chips))


def round_wall_decomposition(rows: List[Dict],
                             costs_doc: Optional[Dict] = None
                             ) -> Optional[Dict]:
    """Mean per-round wall split into attributed terms:

    * ``device_floor_s`` — the captured primary program's FLOPs at
      peak (what a 100%-MFU chip would need; the MXU share of the
      round is AT LEAST this);
    * ``host_fetch_s`` / ``host_eval_s`` / ``host_checkpoint_s`` —
      the measured host phases around the jitted call;
    * ``stream_exposed_s`` — the producer wall the overlap failed to
      hide (consumer queue-wait; inside ``round_s``'s clock on the
      stream plane, so it is named, not added);
    * ``unattributed_s`` — round wall minus the device floor: dispatch
      gap, sub-peak MXU occupancy, copies/infeed — what the profiler
      trace attribution (``tools/trace_attrib``) decomposes further.

    Per-round means over the steady-state rows (the compile round is
    excluded, like the report's rate). ``None`` without rows."""
    steady = rows[1:] or rows
    if not steady:
        return None
    n = len(steady)
    mean = lambda key: sum(float(r.get(key, 0.0)) for r in steady) / n
    round_s = mean("round_s")
    floor = device_floor_s(costs_doc)
    out: Dict = {
        "rounds": n,
        "round_s_mean": round_s,
        "host_fetch_s": mean("fetch_s"),
        "host_eval_s": mean("eval_s"),
        "host_checkpoint_s": mean("checkpoint_s"),
    }
    # stream_wait_s is cumulative; per-round exposure is the mean
    # GROWTH after the first observation (reset-aware: a restart's
    # re-zeroed counter starts a new segment instead of clamping the
    # whole-run delta to 0)
    waits = [float(r["stream_wait_s"]) for r in rows
             if isinstance(r.get("stream_wait_s"), (int, float))
             and not isinstance(r.get("stream_wait_s"), bool)]
    if waits:
        if len(waits) >= 2:
            grown = sum((v if v < p else v - p)
                        for p, v in zip(waits, waits[1:]))
            out["stream_exposed_s"] = grown / (len(waits) - 1)
        else:
            out["stream_exposed_s"] = waits[0]
    if floor is not None and round_s > 0:
        out["device_floor_s"] = floor
        out["device_floor_frac"] = min(floor / round_s, 1.0)
        out["unattributed_s"] = max(round_s - floor, 0.0)
        out["host_frac"] = min(max(1.0 - floor / round_s, 0.0), 1.0)
    return out
