"""Append-only JSONL writers for ``metrics.jsonl`` / ``events.jsonl``.

One line per record, first line a schema header. Rows are buffered in
memory and flushed on a time budget (``flush_interval_s``, default 1s)
or every ``flush_rows`` records, whichever comes first — a per-row
flush would put an fsync-adjacent syscall on the round clock (measured
~100us/row on hardened filesystems, the second-largest term of the
telemetry A/B), while a 1s budget bounds crash loss to one second of
rows (``iter_jsonl`` skips a torn tail) and keeps ``tail -f`` usable.
Events flush immediately (rare, and each one matters). Values must
already be host-side Python scalars — the writers never touch device
values, which is what keeps the emission path FTL001-clean and the
per-round device-sync count at exactly the one batched fetch the loop
already paid.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional


class JsonlWriter:
    """Buffered line-per-record appender with a schema header.

    Failure-tolerant like the health file: IO errors are counted and
    the writer goes inert instead of killing training."""

    def __init__(self, path: str, schema: str,
                 run_meta: Optional[Dict] = None,
                 flush_interval_s: float = 1.0, flush_rows: int = 200):
        self.path = path
        self.schema = schema
        self.rows = 0
        self.write_errors = 0
        self.flush_interval_s = float(flush_interval_s)
        self.flush_rows = int(flush_rows)
        self._buf: List[str] = []
        self._last_flush = time.monotonic()
        self._f = None
        self._header = {"schema": schema,
                        "created_unix": time.time(),
                        **({"run": run_meta} if run_meta else {})}

    def _ensure_open(self):
        if self._f is not None or self.write_errors:
            return self._f
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "a")
            if self._f.tell() == 0:
                self._f.write(json.dumps(self._header) + "\n")
                self._f.flush()
        except OSError:
            self.write_errors += 1
            self._f = None
        return self._f

    def write(self, row: Dict, flush: bool = False) -> None:
        try:
            self._buf.append(json.dumps(row) + "\n")
        except (TypeError, ValueError):
            self.write_errors += 1
            return
        self.rows += 1
        now = time.monotonic()
        if (flush or len(self._buf) >= self.flush_rows
                or now - self._last_flush >= self.flush_interval_s):
            self.flush()

    def flush(self) -> None:
        self._last_flush = time.monotonic()
        if not self._buf:
            return
        f = self._ensure_open()
        if f is None:
            self._buf.clear()  # inert writer: don't grow forever
            return
        try:
            # one write call for the batch: concurrent readers (and a
            # crash) see whole lines or nothing
            f.write("".join(self._buf))
            f.flush()
            self._buf.clear()
        except OSError:
            self.write_errors += 1
            self._buf.clear()

    def close(self) -> None:
        self.flush()
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
