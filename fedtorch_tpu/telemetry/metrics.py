"""Append-only JSONL writers for ``metrics.jsonl`` / ``events.jsonl``.

One line per record, first line a schema header. Rows are buffered in
memory and flushed on a time budget (``flush_interval_s``, default 1s)
or every ``flush_rows`` records, whichever comes first — a per-row
flush would put an fsync-adjacent syscall on the round clock (measured
~100us/row on hardened filesystems, the second-largest term of the
telemetry A/B), while a 1s budget bounds crash loss to one second of
rows (``iter_jsonl`` skips a torn tail) and keeps ``tail -f`` usable.
Events flush immediately (rare, and each one matters). Values must
already be host-side Python scalars — the writers never touch device
values, which is what keeps the emission path FTL001-clean and the
per-round device-sync count at exactly the one batched fetch the loop
already paid.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from fedtorch_tpu.telemetry import faults as _tel_faults


class JsonlWriter:
    """Buffered line-per-record appender with a schema header.

    Failure-tolerant like the health file, with a bounded-retry
    degrade policy (docs/robustness.md "Host plane"): a failed flush
    KEEPS its buffered rows (bounded) and retries on the next flush —
    a transient full disk loses nothing — and only after
    ``max_consecutive_errors`` consecutive failures does the writer
    degrade to off: buffer dropped, one ``on_degrade`` notification
    (the hub turns it into a ``telemetry.degraded`` event), and every
    later write a cheap no-op. Telemetry must never kill training."""

    # rows kept across failed flushes before the oldest are dropped
    MAX_BUFFER_ROWS = 2000

    def __init__(self, path: str, schema: str,
                 run_meta: Optional[Dict] = None,
                 flush_interval_s: float = 1.0, flush_rows: int = 200,
                 max_consecutive_errors: int = 3, on_degrade=None):
        self.path = path
        self.schema = schema
        self.rows = 0
        self.write_errors = 0
        self.dropped_rows = 0
        self.degraded = False
        self.max_consecutive_errors = int(max_consecutive_errors)
        self.flush_interval_s = float(flush_interval_s)
        self.flush_rows = int(flush_rows)
        self._on_degrade = on_degrade
        self._consecutive_errors = 0
        self._buf: List[str] = []
        # events arrive from worker threads too (the stream producer's
        # chaos.host_fault, the checkpoint worker's ckpt.degraded, the
        # watchdog's firing): buffer append/drain must be mutually
        # exclusive or a row appended mid-flush is cleared unwritten.
        # _mutex guards ONLY the buffer (never held across IO);
        # _open_lock serializes the one-time file open; _io_lock
        # serializes batch writes (TextIOWrapper is not thread-safe —
        # concurrent f.write calls can splice lines). The injection
        # check runs under NONE of them: its first-fire announce
        # re-enters this writer, and any held lock would self-deadlock.
        # Created through the faults lock factory: the lock-order
        # sentinel (utils/lock_sentinel.py) instruments them by name
        # when armed; unarmed these are plain threading.Locks.
        self._mutex = _tel_faults.new_lock("JsonlWriter._mutex")
        self._open_lock = _tel_faults.new_lock("JsonlWriter._open_lock")
        self._io_lock = _tel_faults.new_lock("JsonlWriter._io_lock")
        self._last_flush = time.monotonic()
        self._f = None
        self._header = {"schema": schema,
                        "created_unix": time.time(),
                        **({"run": run_meta} if run_meta else {})}

    def _open(self):
        """Open (once) the output file, writing the schema header on a
        fresh file. Raises ``OSError`` on failure. Guarded by its own
        lock so two racing first-flushes cannot double-write the
        header; held only around the open, never around batch IO.
        NO injection check in here — flush() already checks the seam
        before calling, and a check under ``_open_lock`` could fire
        the first-fire announce, which re-enters this writer and would
        self-deadlock on the held lock."""
        with self._open_lock:
            if self._f is None:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                f = open(self.path, "a")
                if f.tell() == 0:
                    f.write(json.dumps(self._header) + "\n")
                    f.flush()
                else:
                    # appending to an existing file (elastic restart):
                    # a crash may have left a torn final line with NO
                    # newline — writing our first row directly after
                    # it would merge the two into one unparseable line
                    # and lose BOTH (the stale pre-crash row would
                    # then win the restart stitch). A defensive
                    # newline isolates the torn bytes; readers skip
                    # blank lines.
                    f.write("\n")
                    f.flush()
                self._f = f
            return self._f

    def _io_error(self) -> None:
        """Called OUTSIDE ``_mutex``: the degrade notifications can
        re-enter this (or another) writer via ``telemetry.event``."""
        self.write_errors += 1
        self._consecutive_errors += 1
        if self._consecutive_errors >= self.max_consecutive_errors \
                and not self.degraded:
            self.degraded = True
            with self._mutex:
                self.dropped_rows += len(self._buf)
                self._buf.clear()
            from fedtorch_tpu.telemetry import faults
            faults.note_degraded("telemetry.write")
            if self._on_degrade is not None:
                try:
                    self._on_degrade(self)
                except Exception:
                    pass  # the notification must not outcrash the IO

    def write(self, row: Dict, flush: bool = False) -> None:
        if self.degraded:
            self.dropped_rows += 1
            return
        # stamp every row with the per-writer monotonic sequence and a
        # wall-clock time (events already carry their own `t`): an
        # elastic restart appends a fresh writer to the SAME file, so
        # a mid-stream seq drop marks the restart boundary and `t`
        # orders rows across it — compare/watch stitch unambiguously
        # (telemetry.schema count_restarts/stitch_rows). Stamped under
        # the mutex so seq order matches buffer order.
        row = dict(row)
        if "t" not in row:
            row["t"] = time.time()
        with self._mutex:
            row["seq"] = self.rows
            try:
                line = json.dumps(row) + "\n"
            except (TypeError, ValueError):
                self.write_errors += 1
                return
            self._buf.append(line)
            self.rows += 1
            if len(self._buf) > self.MAX_BUFFER_ROWS:
                # a long outage must not grow host memory without bound
                del self._buf[0]
                self.dropped_rows += 1
            # the flush decision reads the buffer length, so it
            # belongs under the same mutex as the appends (FTH003
            # half-discipline: a concurrent drain between the append
            # and an unlocked read could skip the row-count trigger)
            want_flush = flush or len(self._buf) >= self.flush_rows
        now = time.monotonic()
        if want_flush or now - self._last_flush >= self.flush_interval_s:
            self.flush()

    def flush(self) -> None:
        self._last_flush = time.monotonic()
        if self.degraded:
            return
        # swap the batch out under the lock, do ALL IO (and the
        # injection check) outside it: a slow disk must not block
        # every telemetry-emitting thread behind the mutex, and the
        # injector's first-fire announce re-enters this writer via
        # telemetry.event — under a held non-reentrant lock that was
        # a self-deadlock
        with self._mutex:
            if not self._buf:
                return
            batch, self._buf = self._buf, []
        try:
            from fedtorch_tpu.telemetry import faults
            faults.check("telemetry.write")
            with self._io_lock:
                f = self._open()
                # one write call for the batch: concurrent readers
                # (and a crash) see whole lines or nothing
                f.write("".join(batch))
                f.flush()
                self._consecutive_errors = 0
        except OSError:
            # the batch stays buffered for the next attempt (a
            # transient full disk loses nothing); rows appended by
            # other threads meanwhile land AFTER — ordering wobble,
            # never loss
            with self._mutex:
                self._buf[0:0] = batch
            self._io_error()

    def close(self) -> None:
        self.flush()
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
