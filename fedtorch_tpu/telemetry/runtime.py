"""The run-scoped telemetry hub: one object per training run.

:class:`Telemetry` bundles the three pillars (docs/observability.md):

* structured **metrics/events** — ``metrics.jsonl`` (one row per
  round/commit, schema-versioned) and ``events.jsonl`` (irregular
  occurrences: drain requests, supervisor rollbacks, chaos summaries,
  watchdog firings) in the run dir;
* **host-span tracing** — a :class:`~.spans.SpanRecorder` exported to
  ``trace.json`` (Chrome trace-event format, loads in Perfetto);
* machine-readable **health** — the atomically-replaced per-host
  ``health.json``.

Library code that cannot see the run's ``Telemetry`` object (the
stream-feed producer thread, the async checkpoint writer, the
supervisor, ``capture_round_trace``) records through the module-level
:func:`~fedtorch_tpu.telemetry.span` / ``event`` / ``instant``
functions, which dispatch to the ACTIVE instance — installed by the
CLI loop for the run's duration — and compile to a shared no-op when
none is active (or ``level='off'``), so instrumented hot paths cost an
attribute load + truth test when telemetry is disabled.

Multi-host: every process writes its own health file; only process 0
writes metrics/events/trace (after the collective scalar fetch every
process holds the same values — N writers would race on one file for
no information gain).
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

from fedtorch_tpu.telemetry.health import HealthFile, health_path
from fedtorch_tpu.telemetry.metrics import JsonlWriter
from fedtorch_tpu.telemetry.schema import (
    EVENTS_SCHEMA, METRICS_SCHEMA,
)
from fedtorch_tpu.telemetry.spans import NULL_SPAN, SpanRecorder

LEVELS = ("off", "default", "debug")

# the active instance (None = every module-level hook is a no-op)
_active: Optional["Telemetry"] = None


def get_active() -> Optional["Telemetry"]:
    return _active


class Telemetry:
    """Per-run telemetry files + span recorder + health document.

    Use as a context manager (installs/uninstalls the active instance)
    or call :meth:`install`/:meth:`close` explicitly. Safe to construct
    with ``level='off'``: everything becomes inert and no files are
    touched — callers never need an ``if`` around instrumentation.
    """

    def __init__(self, run_dir: Optional[str], level: str = "default",
                 process_index: int = 0,
                 run_meta: Optional[Dict] = None,
                 max_span_events: int = 200_000):
        if level not in LEVELS:
            raise ValueError(
                f"telemetry level must be one of {LEVELS}, got {level!r}")
        self.level = level
        self.run_dir = run_dir
        self.process_index = process_index
        self.enabled = level != "off" and run_dir is not None
        self.is_writer = process_index == 0
        self._installed = False
        self.metrics: Optional[JsonlWriter] = None
        self.events: Optional[JsonlWriter] = None
        self.spans: Optional[SpanRecorder] = None
        self.health: Optional[HealthFile] = None
        self.trace_path: Optional[str] = None
        self._rounds_seen = 0
        if not self.enabled:
            return
        self.health = HealthFile(health_path(run_dir, process_index),
                                 process_index,
                                 on_degrade=lambda _w:
                                 self._writer_degraded("health"))
        if self.is_writer:
            self.metrics = JsonlWriter(
                os.path.join(run_dir, "metrics.jsonl"), METRICS_SCHEMA,
                run_meta,
                on_degrade=lambda _w: self._writer_degraded("metrics"))
            self.events = JsonlWriter(
                os.path.join(run_dir, "events.jsonl"), EVENTS_SCHEMA,
                run_meta,
                on_degrade=lambda _w: self._writer_degraded("events"))
            self.spans = SpanRecorder(max_events=max_span_events)
            self.trace_path = os.path.join(run_dir, "trace.json")

    # -- lifecycle ------------------------------------------------------
    def install(self) -> "Telemetry":
        global _active
        if self.enabled:
            _active = self
            self._installed = True
        return self

    def close(self) -> None:
        """Uninstall, export the trace, close the writers. Idempotent;
        never raises (end-of-run bookkeeping must not mask the loop's
        own outcome)."""
        global _active
        if _active is self:
            _active = None
        self._installed = False
        if self.spans is not None and self.trace_path is not None:
            try:
                self.spans.export(self.trace_path)
            except OSError:
                pass
        for w in (self.metrics, self.events):
            if w is not None:
                w.close()

    def __enter__(self) -> "Telemetry":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.close()

    def _writer_degraded(self, which: str) -> None:
        """One of the pillar writers gave up (too many consecutive IO
        failures): emit ONE ``telemetry.degraded`` event — on the
        events channel if it is still alive (a degraded events writer
        silently drops it, which is the best that can be done with a
        dead disk) — and a stderr line so the operator sees it even
        with every file channel down. The loop keeps running either
        way: telemetry degrades to off, never to a crash."""
        import sys
        try:
            self.event("telemetry.degraded", writer=which)
        except Exception:
            pass
        print(f"telemetry: {which} writer degraded to off after "
              "repeated write failures", file=sys.stderr, flush=True)

    # -- recording ------------------------------------------------------
    def span(self, name: str, **args):
        if self.spans is None:
            return NULL_SPAN
        return self.spans.span(name, **args)

    def instant(self, name: str, **args) -> None:
        if self.spans is not None:
            self.spans.instant(name, **args)

    def event(self, name: str, **fields) -> None:
        """One irregular occurrence: a line in ``events.jsonl`` plus an
        instant marker on the trace timeline (same name — so Perfetto
        shows WHERE in the round the drain/rollback/firing landed)."""
        if self.events is not None:
            self.events.write({"t": time.time(), "event": name,
                               **fields}, flush=True)
        if self.spans is not None:
            self.spans.instant(name, **fields)

    def round_row(self, row: Dict) -> None:
        """Append one per-round metrics row (see telemetry.schema).
        ``level='debug'`` additionally re-exports the trace every 25
        rounds so a live Perfetto session can follow a long run."""
        if self.metrics is not None:
            self.metrics.write(row)
        self._rounds_seen += 1
        if self.level == "debug" and self.spans is not None \
                and self.trace_path is not None \
                and self._rounds_seen % 25 == 0:
            try:
                self.spans.export(self.trace_path)
            except OSError:
                pass

    def health_update(self, intent: str, round_idx: Optional[int] = None,
                      staleness: Optional[float] = None, **extra) -> None:
        if self.health is not None:
            self.health.update(intent, round_idx=round_idx,
                               staleness=staleness, **extra)
