"""Host-side per-client federation ledger (docs/observability.md
"Federation plane").

PR 7 instrumented the run and PR 8 the device; the FEDERATION itself —
which clients participated, which were guard-rejected, what the robust
rule suspected of whom, how stale each committed update was — died in
process memory every round. The ledger accumulates exactly that, fed
solely from the round loop's ONE batched per-round fetch
(``FederatedTrainer.cohort_fetch_dev`` — [k]-sized vectors riding the
same ``device_get`` as the log scalars, so the per-round device-sync
count stays at one), and persists it as a schema-versioned, atomically
replaced ``client_ledger.json`` that elastic restarts adopt like
``program_costs.json``.

Memory contract — **O(min(C, sketch_budget)) at any population**:

* ``C <= sketch_budget`` — **dense** mode: one numpy counter array per
  tracked quantity (8 x 8 bytes/client; ~4 MiB at the default
  65536 budget).
* ``C > sketch_budget`` — **sketch** mode: a count-min sketch (depth
  ``_CM_DEPTH``, width ``budget // depth``) answers per-client
  participation queries within the classic overestimate bound, and a
  space-saving top-K (``budget // 16`` records) keeps EXACT per-client
  records for the highest-cumulative-suspicion clients — the clients an
  operator actually asks about. A C=10^6 population costs the same
  bytes as the budget, measured in TELEMETRY_AB.json's
  ``ledger_memory`` row.

Per-round semantics for an online client (all O(k) numpy updates):
``participation`` += 1 (sampled/dispatched), ``online`` += survived
chaos, ``accepted`` += passed the guards, ``rejected`` += survived but
guard-rejected, ``selected`` += the robust rule aggregated it,
``dropped`` += dispatched but never reported (chaos crash,
availability dropout, or deadline miss),
``suspicion`` += the rule's per-client score
(robustness/aggregators.py:RobustReport), ``staleness`` += commit
staleness (async plane; 0 on sync).

numpy-only, never jax (the telemetry package rule): the report tool and
external monitors read the file through the pure-stdlib
:func:`read_client_ledger` / :func:`suspicion_ranking` half without
initializing a backend. Writes degrade silently (errors counted) —
telemetry must never kill training.
"""
from __future__ import annotations

import heapq
import json
import os
import time
from typing import Dict, List, Optional, Tuple

# numpy is imported LAZILY (first ClientLedger construction): the
# reader half below is pure stdlib, and the telemetry package —
# through which `fedtorch-tpu report` imports — must stay importable
# on a monitor box with neither jax nor numpy installed.
np = None


def _numpy():
    global np
    if np is None:
        import numpy
        np = numpy
    return np

LEDGER_SCHEMA = "fedtorch_tpu.client_ledger/v1"
LEDGER_FILE = "client_ledger.json"

# per-client quantities the ledger accumulates; integer-count semantics
# for the first six, float sums for the last two. ``dropped`` is
# derived per round as participation - online: the client was
# dispatched but never reported (chaos crash, availability dropout, or
# deadline miss — the deployment-realism lifecycle's per-client
# accounting, docs/robustness.md "Deployment realism")
LEDGER_COUNTERS = ("participation", "online", "accepted", "rejected",
                   "selected", "dropped", "suspicion", "staleness")
_INT_COUNTERS = ("participation", "online", "accepted", "rejected",
                 "selected", "dropped")

# count-min geometry (sketch mode): classic (depth, width) trade —
# 4 rows bound the overestimate at ~e^-4 failure odds per query
_CM_DEPTH = 4
# 31-bit Mersenne prime for the universal hash family; a*x+b stays
# under 2^62, so uint64 arithmetic never overflows
_CM_PRIME = 2147483647


def ledger_path(run_dir: str) -> str:
    return os.path.join(run_dir, LEDGER_FILE)


def _hash_params(seed: int) -> List[Tuple[int, int]]:
    """Deterministic (a, b) pairs of the count-min universal hash
    family — a tiny LCG off the seed, so two ledgers with equal seeds
    sketch identically (the determinism-under-seed test)."""
    out = []
    s = (seed * 2654435761 + 0x9E3779B9) & 0x7FFFFFFF
    for _ in range(_CM_DEPTH):
        s = (s * 1103515245 + 12345) & 0x7FFFFFFF
        a = (s % (_CM_PRIME - 1)) + 1
        s = (s * 1103515245 + 12345) & 0x7FFFFFFF
        b = s % _CM_PRIME
        out.append((a, b))
    return out


class ClientLedger:
    """Accumulates the per-client federation record and persists it.

    ``update`` is called once per round with the host copies of the
    cohort vectors; ``flush`` atomically replaces
    ``client_ledger.json`` (every ``flush_every`` rounds and at run
    end); ``load_existing`` adopts a prior attempt's file on elastic
    restart. ``stats`` serves the two metrics-row gauges
    (``ledger_tracked`` / ``ledger_bytes``)."""

    # exact per-client records kept in sketch mode (space-saving by
    # cumulative suspicion); dense mode tracks everyone exactly
    TOP_DIVISOR = 16
    # entries of the persisted top-suspicion preview in dense mode
    PREVIEW = 32

    def __init__(self, run_dir: str, num_clients: int,
                 sketch_budget: int = 65536, seed: int = 0,
                 flush_every: int = 25,
                 run_meta: Optional[Dict] = None, log=None):
        np = _numpy()
        self.path = ledger_path(run_dir)
        self.num_clients = int(num_clients)
        self.sketch_budget = int(sketch_budget)
        self.seed = int(seed)
        self.flush_every = max(int(flush_every), 1)
        self.run_meta = run_meta or {}
        self._log = log if log is not None else (lambda *_: None)
        self.rounds = 0
        self.write_errors = 0
        self._created = time.time()
        self._rounds_since_flush = 0
        self.mode = "dense" if self.num_clients <= self.sketch_budget \
            else "sketch"
        if self.mode == "dense":
            self._dense = {
                name: np.zeros(
                    self.num_clients,
                    np.int64 if name in _INT_COUNTERS else np.float64)
                for name in LEDGER_COUNTERS}
            self._cm = None
            self._top: Dict[int, Dict[str, float]] = {}
            self.top_k = 0
        else:
            self._dense = None
            self._cm_width = max(self.sketch_budget // _CM_DEPTH, 64)
            self._cm_hash = _hash_params(self.seed)
            self._cm = np.zeros((_CM_DEPTH, self._cm_width), np.int64)
            self.top_k = max(self.sketch_budget // self.TOP_DIVISOR, 16)
            self._top = {}
            # lazy-deletion min-heap over (suspicion, cid): eviction
            # pops amortized O(log K) instead of scanning all K
            # records per insert; stale entries (a client updated
            # since its push) are skipped on pop — suspicion only
            # grows, so a stale entry never masks the true minimum
            self._heap: List[Tuple[float, int]] = []

    # -- accumulation ----------------------------------------------------
    def _cm_rows(self, idx):
        """[depth, k] count-min column indices for the client ids."""
        np = _numpy()
        idx = idx.astype(np.uint64)
        cols = np.empty((_CM_DEPTH, idx.shape[0]), np.int64)
        for j, (a, b) in enumerate(self._cm_hash):
            cols[j] = (((a * idx + b) % _CM_PRIME)
                       % self._cm_width).astype(np.int64)
        return cols

    def _evict_min(self) -> float:
        """Evict the minimum-suspicion record (lazy-deletion heap);
        returns the evicted suspicion floor."""
        while self._heap:
            susp, cid = heapq.heappop(self._heap)
            rec = self._top.get(cid)
            if rec is not None and rec["suspicion"] == susp:
                del self._top[cid]
                return susp
        # heap exhausted of valid entries (all stale): rebuild once
        self._rebuild_heap()
        susp, cid = heapq.heappop(self._heap)
        del self._top[cid]
        return susp

    def _rebuild_heap(self) -> None:
        self._heap = [(rec["suspicion"], cid)
                      for cid, rec in self._top.items()]
        heapq.heapify(self._heap)

    def _top_update(self, cid: int, inc: Dict[str, float]) -> None:
        """Space-saving top-K on cumulative suspicion: a tracked client
        updates in place; an untracked one evicts the current minimum,
        inheriting its suspicion floor (the classic overestimate that
        keeps genuine heavy hitters from being churned out)."""
        rec = self._top.get(cid)
        if rec is None:
            rec = {name: 0.0 for name in LEDGER_COUNTERS}
            if len(self._top) >= self.top_k:
                rec["suspicion"] = self._evict_min()
            self._top[cid] = rec
        for name in LEDGER_COUNTERS:
            rec[name] += inc[name]
        heapq.heappush(self._heap, (rec["suspicion"], cid))
        if len(self._heap) > 4 * self.top_k + 1024:
            self._rebuild_heap()

    def update(self, round_idx: int, led: Dict) -> None:
        """Fold one round's cohort vectors (host numpy copies of
        ``FederatedTrainer.cohort_fetch_dev``) into the ledger. O(k)."""
        np = _numpy()
        idx = np.asarray(led["idx"], np.int64).ravel()
        online = np.asarray(led["online"], np.float64).ravel()
        accept = np.asarray(led["accept"], np.float64).ravel()
        selected = np.asarray(led["selected"], np.float64).ravel()
        suspicion = np.asarray(led["suspicion"], np.float64).ravel()
        staleness = np.asarray(led["staleness"], np.float64).ravel()
        rejected = np.maximum(online - accept, 0.0)
        dropped = np.maximum(1.0 - online, 0.0)
        self.rounds += 1
        if self.mode == "dense":
            d = self._dense
            np.add.at(d["participation"], idx, 1)
            np.add.at(d["online"], idx, online.astype(np.int64))
            np.add.at(d["accepted"], idx, accept.astype(np.int64))
            np.add.at(d["rejected"], idx, rejected.astype(np.int64))
            np.add.at(d["selected"], idx, selected.astype(np.int64))
            np.add.at(d["dropped"], idx, dropped.astype(np.int64))
            np.add.at(d["suspicion"], idx, suspicion)
            np.add.at(d["staleness"], idx, staleness)
        else:
            cols = self._cm_rows(idx)
            for j in range(_CM_DEPTH):
                np.add.at(self._cm[j], cols[j], 1)
            for i, cid in enumerate(idx.tolist()):
                self._top_update(cid, {
                    "participation": 1.0, "online": float(online[i]),
                    "accepted": float(accept[i]),
                    "rejected": float(rejected[i]),
                    "selected": float(selected[i]),
                    "dropped": float(dropped[i]),
                    "suspicion": float(suspicion[i]),
                    "staleness": float(staleness[i])})
        self._rounds_since_flush += 1
        if self._rounds_since_flush >= self.flush_every:
            self.flush()

    # -- queries ---------------------------------------------------------
    def participation_estimate(self, cid: int) -> int:
        """Exact in dense mode; the count-min upper bound in sketch
        mode (min over rows — never undercounts)."""
        if self.mode == "dense":
            return int(self._dense["participation"][cid])
        cols = self._cm_rows(_numpy().asarray([cid]))
        return int(min(self._cm[j, cols[j, 0]]
                       for j in range(_CM_DEPTH)))

    def tracked(self) -> int:
        """Clients with exact per-client records."""
        if self.mode == "dense":
            return self.num_clients
        return len(self._top)

    def memory_bytes(self) -> int:
        """Host bytes the ledger holds — the O(min(C, budget)) bound
        TELEMETRY_AB.json measures at C=10^6."""
        if self.mode == "dense":
            return int(sum(a.nbytes for a in self._dense.values()))
        # dict-of-dict records: ~7 floats + key + dict overhead; the
        # lazy heap is bounded at 4*top_k + 1024 tuples
        per_rec = 8 * len(LEDGER_COUNTERS) + 120
        return int(self._cm.nbytes + len(self._top) * per_rec
                   + len(self._heap) * 72)

    def stats(self) -> Dict[str, float]:
        """The metrics-row gauges (cataloged in telemetry.schema)."""
        return {"ledger_tracked": float(self.tracked()),
                "ledger_bytes": float(self.memory_bytes())}

    # -- persistence -----------------------------------------------------
    def _doc(self) -> Dict:
        doc = {
            "schema": LEDGER_SCHEMA,
            "created_unix": self._created,
            "updated_unix": time.time(),
            "num_clients": self.num_clients,
            "sketch_budget": self.sketch_budget,
            "seed": self.seed,
            "mode": self.mode,
            "rounds": self.rounds,
            "run": self.run_meta,
        }
        np = _numpy()
        if self.mode == "dense":
            counters = {}
            for name, arr in self._dense.items():
                if name in _INT_COUNTERS:
                    counters[name] = arr.tolist()
                else:
                    # vectorized: a per-element Python round() over a
                    # budget-sized array would put tens of ms on the
                    # round the 25-round flush cadence lands on
                    counters[name] = np.round(arr, 6).tolist()
            doc["counters"] = counters
            order = np.argsort(-self._dense["suspicion"],
                               kind="stable")[:self.PREVIEW]
            doc["top_suspicion"] = [
                [int(c), round(float(self._dense["suspicion"][c]), 6)]
                for c in order if self._dense["participation"][c] > 0]
        else:
            doc["sketch"] = {
                "depth": _CM_DEPTH, "width": self._cm_width,
                "participation": self._cm.tolist(),
            }
            doc["top"] = {
                str(cid): {name: (int(rec[name])
                                  if name in _INT_COUNTERS
                                  else round(rec[name], 6))
                           for name in LEDGER_COUNTERS}
                for cid, rec in sorted(self._top.items())}
        return doc

    def flush(self) -> None:
        """Atomic replace (tmp + ``os.replace``): a reader at any
        moment sees a complete document. Never raises — a full disk
        counts an error and training continues."""
        self._rounds_since_flush = 0
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self._doc(), f)
            os.replace(tmp, self.path)
        except OSError as e:
            self.write_errors += 1
            self._log(f"client ledger: write failed ({e}); "
                      "will retry at the next flush")
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def load_existing(self) -> bool:
        """Adopt a prior attempt's ledger (elastic restart — the
        ``program_costs.json`` convention): counters resume instead of
        restarting from zero and double-writing a half-empty file over
        the history. Returns True when adopted; a missing file, a
        different schema/population/geometry, or a corrupt document
        adopts nothing — the WHOLE parse runs inside the guard and
        state commits only at the end, so a content-corrupt file (a
        record missing a key, a string in a counter list) can neither
        crash an elastic restart nor leave a half-adopted ledger."""
        np = _numpy()
        try:
            with open(self.path) as f:
                doc = json.load(f)
            validate_client_ledger(doc)
            if doc["num_clients"] != self.num_clients \
                    or doc["mode"] != self.mode \
                    or doc.get("seed", 0) != self.seed \
                    or doc.get("sketch_budget") != self.sketch_budget:
                self._log("client ledger: existing file has a "
                          "different population/geometry; starting "
                          "fresh")
                return False
            rounds = int(doc["rounds"])
            if self.mode == "dense":
                dense = {
                    name: np.asarray(
                        doc["counters"][name],
                        np.int64 if name in _INT_COUNTERS
                        else np.float64)
                    for name in LEDGER_COUNTERS}
                if any(a.shape != (self.num_clients,)
                       for a in dense.values()):
                    raise ValueError("counter shape mismatch")
            else:
                sk = doc["sketch"]
                if sk["depth"] != _CM_DEPTH \
                        or sk["width"] != self._cm_width:
                    self._log("client ledger: existing sketch "
                              "geometry differs; starting fresh")
                    return False
                cm = np.asarray(sk["participation"], np.int64)
                if cm.shape != (_CM_DEPTH, self._cm_width):
                    raise ValueError("sketch table shape mismatch")
                top = {
                    int(cid): {name: float(rec[name])
                               for name in LEDGER_COUNTERS}
                    for cid, rec in doc["top"].items()}
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError):
            return False
        # parsed clean: commit
        self.rounds = rounds
        if self.mode == "dense":
            self._dense = dense
        else:
            self._cm = cm
            self._top = top
            self._rebuild_heap()
        return True


# -- stdlib reader half (report tool, monitors) --------------------------

def validate_client_ledger(doc: Dict) -> None:
    """Raise ``ValueError`` when ``doc`` violates the v1 contract."""
    if doc.get("schema") != LEDGER_SCHEMA:
        raise ValueError(
            f"client ledger schema {doc.get('schema')!r} != "
            f"{LEDGER_SCHEMA!r}")
    for key in ("num_clients", "mode", "rounds", "sketch_budget"):
        if key not in doc:
            raise ValueError(f"client_ledger.json missing {key!r}")
    if doc["mode"] == "dense":
        counters = doc.get("counters")
        if not isinstance(counters, dict):
            raise ValueError("dense ledger missing 'counters'")
        for name in LEDGER_COUNTERS:
            if name == "dropped" and name not in counters:
                # added after v1 shipped; absent in older run dirs —
                # readers backfill zeros (read_client_ledger)
                continue
            vals = counters.get(name)
            if not isinstance(vals, list) \
                    or len(vals) != doc["num_clients"]:
                raise ValueError(
                    f"dense ledger counter {name!r} missing or not "
                    f"[num_clients] long")
    elif doc["mode"] == "sketch":
        if not isinstance(doc.get("sketch"), dict) \
                or not isinstance(doc.get("top"), dict):
            raise ValueError("sketch ledger missing 'sketch'/'top'")
    else:
        raise ValueError(f"unknown ledger mode {doc['mode']!r}")


def read_client_ledger(path: str) -> Dict:
    """Load + validate a ``client_ledger.json`` (``path`` may be the
    file or its run dir). Pure stdlib — no numpy, no jax."""
    if os.path.isdir(path):
        path = ledger_path(path)
    with open(path) as f:
        doc = json.load(f)
    validate_client_ledger(doc)
    # backfill the post-v1 'dropped' counter for older run dirs so
    # every consumer sees the full LEDGER_COUNTERS surface
    if doc["mode"] == "dense" and "dropped" not in doc["counters"]:
        doc["counters"]["dropped"] = [0] * doc["num_clients"]
    elif doc["mode"] == "sketch":
        for rec in doc["top"].values():
            rec.setdefault("dropped", 0)
    return doc


def suspicion_ranking(doc: Dict, top: int = 0) -> List[Tuple[int, float]]:
    """[(client, cumulative suspicion)] sorted most-suspect first,
    from either mode's document — the query the Byzantine-separation
    drill (``chaos_suite.py --ledger-attack``) and the report's
    Federation section ask. ``top`` truncates (0 = all tracked)."""
    if doc["mode"] == "dense":
        pairs = [(cid, float(s)) for cid, s in
                 enumerate(doc["counters"]["suspicion"])
                 if doc["counters"]["participation"][cid] > 0]
    else:
        pairs = [(int(cid), float(rec["suspicion"]))
                 for cid, rec in doc["top"].items()]
    pairs.sort(key=lambda p: (-p[1], p[0]))
    return pairs[:top] if top else pairs
