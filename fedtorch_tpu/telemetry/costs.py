"""Compiled-program cost capture (device-side observability, pillar 1
of docs/observability.md "Device-side").

The host spans answer "where did host wall-clock go"; this module
answers the device half's first question — "what does the compiled
round actually cost" — straight from XLA's own accounting:
``Compiled.cost_analysis()`` (FLOPs, transcendentals, bytes accessed)
and ``Compiled.memory_analysis()`` (argument/output/temp buffer sizes,
whose sum is the program's peak device-memory watermark). One shared
helper replaces the three ad-hoc copies that grew in
``scripts/mfu_sweep.py``, ``scripts/moe_ab_bench.py`` and ``bench.py``,
so every bench reports the same ``flops_source`` vocabulary.

Contract (pinned in tests/test_device_observability.py):

* **Zero effect on the traced program.** Cost capture AOT-lowers
  UNINSTRUMENTED twins of the run's jitted programs (the trainers'
  ``lowered_cost_programs``) — the live jit caches are untouched, the
  recompilation sentinel sees zero extra trace events, and the twin's
  HLO is byte-identical to the live program's. With the persistent
  compilation cache on (the CLI default) the twin compile is a cache
  hit, not a second real XLA compile.
* **Graceful None.** A backend that doesn't report a statistic yields
  ``None`` for that field, never an exception: a lost FLOPs count must
  not lose the run (same rule the bench scripts always had).
* **Emitted once.** ``ProgramCostCapture`` writes a schema-versioned
  ``program_costs.json`` into the run dir at the first round and then
  only serves host-side gauges (``model_flops_utilization``, the HBM
  watermark pair) to the metrics row — zero added device syncs.

Import cost: stdlib-only at module level (the telemetry package's
no-jax rule); every jax touch is inside a function, so the report tool
and external monitors can import the schema half backend-free.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

PROGRAM_COSTS_SCHEMA = "fedtorch_tpu.program_costs/v1"

# the flops_source vocabulary every consumer shares (MFU_SWEEP.json,
# MOE_AB.json, bench.py records, program_costs.json)
FLOPS_XLA = "xla_cost_analysis"
FLOPS_ANALYTIC = "analytic_resnet20"

# bench.py's analytic accounting: resnet20-cifar forward = 40.8e6
# MACs/image (stem 0.44M + 3 stages x ~13-14M + fc; the 41M figure in
# the ResNet paper), training step ~= 3x forward, 2 FLOPs/MAC
ANALYTIC_MACS_PER_IMAGE = {"resnet20": 40.8e6}
_TRAIN_STEP_OVER_FWD = 3 * 2  # bwd ~= 2x fwd, 2 FLOPs per MAC

# TPU v5e per-chip peak (the chip behind every relay capture);
# BENCH_PEAK_TFLOPS overrides for other parts
_DEFAULT_PEAK_TFLOPS = {"bfloat16": 197.0, "float32": 98.0}


def analytic_train_flops_per_image(arch: str) -> Optional[float]:
    """Hand-derived training FLOPs per image for the archs we carry a
    constant for (currently the north-star resnet20); None elsewhere —
    callers must report timing without an MFU rather than invent one."""
    macs = ANALYTIC_MACS_PER_IMAGE.get(arch)
    return _TRAIN_STEP_OVER_FWD * macs if macs is not None else None


def resolve_peak_tflops(dtype: str = "float32") -> Tuple[float, str]:
    """(peak TFLOPs/chip, source string): the ``BENCH_PEAK_TFLOPS``
    env override when set (the zoo-check/bench convention), else the
    TPU v5e per-chip constant for the compute dtype. The source string
    is recorded next to every number derived from the peak, so a
    record is auditable without re-deriving the env state."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env), "env:BENCH_PEAK_TFLOPS"
    peak = _DEFAULT_PEAK_TFLOPS.get(dtype, _DEFAULT_PEAK_TFLOPS["float32"])
    return peak, f"default:tpu_v5e:{dtype}"


# -- XLA cost extraction ------------------------------------------------


def cost_summary(compiled) -> Dict[str, Optional[float]]:
    """Extract the catalogued statistics from a ``jax.stages.Compiled``
    — ``cost_analysis()`` FLOPs/transcendentals/bytes-accessed and
    ``memory_analysis()`` buffer sizes. Every field is ``None`` when
    the backend doesn't expose it (graceful-None contract)."""
    out: Dict[str, Optional[float]] = {
        "flops": None, "transcendentals": None, "bytes_accessed": None,
        "argument_bytes": None, "output_bytes": None, "temp_bytes": None,
        "generated_code_bytes": None, "alias_bytes": None,
        "peak_hbm_bytes": None,
    }
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            fl = float(ca.get("flops", 0.0))
            out["flops"] = fl if fl > 0 else None
            tr = float(ca.get("transcendentals", 0.0))
            out["transcendentals"] = tr if tr > 0 else None
            ba = float(ca.get("bytes accessed", 0.0))
            out["bytes_accessed"] = ba if ba > 0 else None
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = float(ma.argument_size_in_bytes)
            outb = float(ma.output_size_in_bytes)
            tmp = float(ma.temp_size_in_bytes)
            gen = float(ma.generated_code_size_in_bytes)
            ali = float(ma.alias_size_in_bytes)
            out.update(argument_bytes=arg, output_bytes=outb,
                       temp_bytes=tmp, generated_code_bytes=gen,
                       alias_bytes=ali)
            # the watermark: everything resident while the program runs
            # (donated/aliased output pages reuse argument pages, so
            # they are not double-counted)
            out["peak_hbm_bytes"] = arg + outb + tmp + gen - ali
    except Exception:
        pass
    return out


def lowered_cost(lowered) -> Dict[str, Optional[float]]:
    """Compile a ``jax.stages.Lowered`` and summarize it; any failure
    collapses to the all-None summary plus an ``error`` note (a cost
    capture must never take down its caller)."""
    try:
        summary = cost_summary(lowered.compile())
    except Exception as e:
        summary = cost_summary(None)
        summary["error"] = f"{type(e).__name__}: {e}"[:200]
    summary["flops_source"] = FLOPS_XLA if summary.get("flops") else None
    return summary


def program_flops(fn, *args, static_argnums=()) -> Optional[float]:
    """FLOPs of ``jit(fn)(*args)`` from XLA cost analysis — the shared
    probe behind every bench's ``flops_source='xla_cost_analysis'``
    row. None when the backend doesn't report (or anything raises):
    a lost FLOPs count must never lose the caller's timing."""
    try:
        import jax
        lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args)
        return lowered_cost(lowered).get("flops")
    except Exception:
        return None


def train_step_flops(model, batch: int) -> Optional[float]:
    """Per-local-step training FLOPs of ``model``'s compiled fwd+bwd
    (softmax cross-entropy on the model's own sample input) — the
    probe ``scripts/mfu_sweep.py`` and ``bench.py`` share so their MFU
    numerators cannot drift. None on backends without cost analysis."""
    try:
        import jax
        import jax.numpy as jnp

        from fedtorch_tpu.core.losses import softmax_cross_entropy

        x = model.sample_input
        y = jnp.zeros((batch,), jnp.int32)
        params = model.init(jax.random.key(0))

        def loss(p):
            return softmax_cross_entropy(model.apply(p, x), y)

        return program_flops(jax.grad(loss), params)
    except Exception:
        return None


# -- the program_costs.json document ------------------------------------

# field catalogs, mirroring telemetry.schema's metrics-row contract:
# validate_program_costs rejects uncataloged fields so the document
# cannot silently drift from what docs/observability.md describes
PROGRAM_FIELDS = {
    "flops": "executed FLOPs (XLA cost analysis)",
    "transcendentals": "transcendental op count",
    "bytes_accessed": "bytes read+written by the program",
    "argument_bytes": "input buffer bytes",
    "output_bytes": "output buffer bytes",
    "temp_bytes": "intermediate buffer bytes",
    "generated_code_bytes": "executable code bytes",
    "alias_bytes": "donated input bytes reused as outputs",
    "peak_hbm_bytes": "arg+out+temp+code-alias device watermark",
    "flops_source": "xla_cost_analysis or None",
    "error": "capture failure note (program still listed)",
}

_TOP_REQUIRED = ("schema", "created_unix", "backend", "num_devices",
                 "compute_dtype", "peak_tflops_per_chip", "peak_source",
                 "programs")
_TOP_OPTIONAL = ("run", "analytic", "primary")


def validate_program_costs(doc: Dict) -> None:
    """Raise ``ValueError`` when ``doc`` violates the v1 contract —
    the program_costs twin of ``validate_metrics_row``."""
    if doc.get("schema") != PROGRAM_COSTS_SCHEMA:
        raise ValueError(
            f"program_costs schema {doc.get('schema')!r} != "
            f"{PROGRAM_COSTS_SCHEMA!r}")
    for key in _TOP_REQUIRED:
        if key not in doc:
            raise ValueError(f"program_costs missing required {key!r}")
    unknown = [k for k in doc
               if k not in _TOP_REQUIRED and k not in _TOP_OPTIONAL]
    if unknown:
        raise ValueError(
            f"program_costs carries uncataloged top-level fields "
            f"{unknown!r}")
    programs = doc["programs"]
    if not isinstance(programs, dict) or not programs:
        raise ValueError("program_costs 'programs' must be a non-empty "
                         "dict of program-name -> cost summary")
    for name, rec in programs.items():
        if not isinstance(rec, dict):
            raise ValueError(f"program {name!r} record must be a dict")
        bad = [k for k in rec if k not in PROGRAM_FIELDS]
        if bad:
            raise ValueError(
                f"program {name!r} carries uncataloged fields {bad!r} "
                "— add them to telemetry.costs.PROGRAM_FIELDS (the "
                "catalog docs/observability.md renders)")
        for k, v in rec.items():
            if k in ("flops_source", "error"):
                if v is not None and not isinstance(v, str):
                    raise ValueError(
                        f"program {name!r} field {k!r} must be str or "
                        f"None, got {type(v).__name__}")
            elif v is not None and (isinstance(v, bool)
                                    or not isinstance(v, (int, float))):
                raise ValueError(
                    f"program {name!r} field {k!r} must be numeric or "
                    f"None, got {type(v).__name__} ({v!r})")


def program_costs_path(run_dir: str) -> str:
    return os.path.join(run_dir, "program_costs.json")


def read_program_costs(run_dir: str) -> Optional[Dict]:
    """The validated document, or None when the run never captured."""
    path = program_costs_path(run_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    validate_program_costs(doc)
    return doc


class ProgramCostCapture:
    """Once-per-run cost capture + the per-round device gauges.

    Built by the CLI loop (process 0, telemetry on); :meth:`capture`
    runs once right after the first round — the live program is
    compiled and the persistent cache warm, so the uninstrumented-twin
    compiles it triggers are cache hits — and writes
    ``program_costs.json`` atomically. :meth:`round_gauges` then turns
    each round's wall-clock into the measured-MFU and HBM-watermark
    row fields from host state alone. Attempt-once semantics: a failed
    capture is logged and never retried (and never raises — cost
    accounting must not take down training)."""

    def __init__(self, run_dir: str, *, compute_dtype: str = "float32",
                 arch: Optional[str] = None,
                 batch_size: Optional[int] = None,
                 local_steps: Optional[int] = None,
                 k_online: Optional[int] = None,
                 num_devices: int = 1, backend: Optional[str] = None,
                 run_meta: Optional[Dict] = None, log=None):
        self.run_dir = run_dir
        self.compute_dtype = compute_dtype
        self.arch = arch
        self.batch_size = batch_size
        self.local_steps = local_steps
        self.k_online = k_online
        self.num_devices = max(int(num_devices), 1)
        self.backend = backend
        self.run_meta = run_meta
        self.log = log or (lambda *_: None)
        self.peak_tflops, self.peak_source = resolve_peak_tflops(
            compute_dtype)
        self.captured = False
        self.doc: Optional[Dict] = None
        self._primary: Optional[Dict] = None
        self._live_cache: Optional[float] = None
        self._live_cost_s = 0.0
        self._rows_since_live = 0

    # -- the one-shot capture ------------------------------------------
    def load_existing(self) -> bool:
        """Adopt a previous attempt's ``program_costs.json`` instead
        of re-capturing. Elastic restarts reuse the run dir, and
        resumed runs bypass the persistent compile cache (cli.py's
        donation-corruption note) — so re-lowering the twins there
        would be a REAL second XLA compile; the gauges resume from the
        recorded primary without touching the backend."""
        try:
            doc = read_program_costs(self.run_dir)
        except (ValueError, OSError, json.JSONDecodeError):
            return False
        if doc is None:
            return False
        # any schema-valid document is adopted, even one without a
        # usable primary (gauges stay off then): half-adopting and
        # re-capturing would pay exactly the recompile this path exists
        # to avoid
        self.captured = True
        self.doc = doc
        self._primary = doc["programs"].get(doc.get("primary"))
        self.log("cost capture: adopted existing program_costs.json "
                 f"(primary {doc.get('primary')!r}"
                 + ("" if self._primary is not None
                    else " — not found, device gauges off") + ")")
        return True

    def _analytic_block(self) -> Optional[Dict]:
        """The analytic roofline for the active config: hand-derived
        per-image training FLOPs scaled to one round (k clients x K
        local steps x batch B) — the yardstick the XLA number is read
        against (docs/performance.md 'Where the remaining headroom
        is')."""
        if self.arch is None:
            return None
        per_image = analytic_train_flops_per_image(self.arch)
        block: Dict = {"arch": self.arch,
                       "train_flops_per_image": per_image}
        if per_image is not None and self.batch_size \
                and self.local_steps and self.k_online:
            block["round_flops"] = (per_image * self.batch_size
                                    * self.local_steps * self.k_online)
        return block

    def capture(self, programs: Dict, primary: Optional[str] = None
                ) -> Optional[Dict]:
        """Compile + summarize each ``{name: jax.stages.Lowered}`` and
        write ``program_costs.json``. ``primary`` names the program
        whose FLOPs/watermark feed the per-round gauges (default: the
        first entry). Absorbs every failure."""
        self.captured = True  # attempt-once, success or not
        try:
            costs = {name: lowered_cost(lowered)
                     for name, lowered in programs.items()}
            if not costs:
                self.log("cost capture: no programs offered; skipped")
                return None
            if primary is None:
                primary = next(iter(costs))
            doc = {
                "schema": PROGRAM_COSTS_SCHEMA,
                "created_unix": time.time(),
                "backend": self.backend,
                "num_devices": self.num_devices,
                "compute_dtype": self.compute_dtype,
                "peak_tflops_per_chip": self.peak_tflops,
                "peak_source": self.peak_source,
                "primary": primary,
                "programs": costs,
            }
            analytic = self._analytic_block()
            if analytic is not None:
                doc["analytic"] = analytic
            if self.run_meta:
                doc["run"] = self.run_meta
            validate_program_costs(doc)
            self._write(doc)
            self.doc = doc
            self._primary = costs.get(primary)
            fl = (self._primary or {}).get("flops")
            self.log(f"cost capture: {len(costs)} program(s) -> "
                     f"{program_costs_path(self.run_dir)} "
                     f"(primary {primary!r}, flops="
                     f"{fl if fl is not None else 'unreported'})")
            return doc
        except Exception as e:
            self.log(f"cost capture failed ({type(e).__name__}: "
                     f"{str(e)[:160]}); training continues without "
                     "device gauges")
            return None

    def _write(self, doc: Dict) -> None:
        """Atomic replace, like health.json: a reader never sees a
        torn document."""
        path = program_costs_path(self.run_dir)
        tmp = path + ".tmp"
        os.makedirs(self.run_dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        os.replace(tmp, path)

    # -- per-round gauges ----------------------------------------------
    def round_gauges(self, round_s: float) -> Dict[str, float]:
        """The metrics-row fields this pillar adds, all host-side:

        * ``model_flops_utilization`` — primary-program FLOPs /
          (round wall x peak x chips), the measured-MFU gauge;
        * ``hbm_program_peak_bytes`` — the compiled program's static
          device-memory watermark (memory_analysis);
        * ``hbm_live_bytes`` — live ``jax.Array`` bytes
          (``utils.tracing.live_buffer_summary`` — metadata walk, no
          transfer), the dynamic half of the watermark pair.

        Empty until :meth:`capture` succeeded, so rows stay stable."""
        if self._primary is None:
            return {}
        out: Dict[str, float] = {}
        flops = self._primary.get("flops")
        if flops and round_s > 0:
            out["model_flops_utilization"] = flops / (
                round_s * self.peak_tflops * 1e12 * self.num_devices)
            # the round-wall critical path's device side
            # (telemetry/critical_path.py): the FLOPs-at-peak floor of
            # device-busy time, and the wall share it does NOT explain
            # — host phases + dispatch gap + sub-peak MXU occupancy
            floor = flops / (self.peak_tflops * 1e12 * self.num_devices)
            out["round_device_min_s"] = floor
            out["round_host_frac"] = min(
                max(1.0 - floor / round_s, 0.0), 1.0)
        peak = self._primary.get("peak_hbm_bytes")
        if peak is not None:
            out["hbm_program_peak_bytes"] = float(peak)
        live = self._live_bytes(round_s)
        if live is not None:
            out["hbm_live_bytes"] = live
        return out

    _LIVE_REFRESH_ROWS = 25
    _LIVE_BUDGET_FRAC = 0.002

    def _live_bytes(self, round_s: float) -> Optional[float]:
        """The live-array watermark, adaptively sampled: the walk is
        O(live arrays) host work (~3 ms at ~90 arrays), which would
        dominate millisecond rounds and break the <=1% telemetry bar —
        so it refreshes when its own measured cost fits inside 0.2% of
        the round wall (multi-second rounds sample fresh every row),
        and at least every 25 rows regardless (the gauge is a
        watermark, not a per-round delta; the amortized worst case is
        ~0.1 ms/row). Measured by the ``costs`` arm of
        scripts/telemetry_bench.py."""
        due = (self._live_cache is None
               or self._rows_since_live >= self._LIVE_REFRESH_ROWS
               or (round_s > 0
                   and self._live_cost_s
                   <= self._LIVE_BUDGET_FRAC * round_s))
        self._rows_since_live += 1
        if not due:
            return self._live_cache
        try:
            from fedtorch_tpu.utils.tracing import live_buffer_summary
            t0 = time.perf_counter()
            total = live_buffer_summary()["total_bytes"]
            self._live_cost_s = time.perf_counter() - t0
            self._live_cache = float(total)
            self._rows_since_live = 0
        except Exception:
            pass
        return self._live_cache
