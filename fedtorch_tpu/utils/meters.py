"""Meters & phase timers.

Parity with ``logs/meter.py``: :class:`AverageMeter` (:30-48) and the
tracker dicts for training (computing_time / sync_time / load_time /
global_time, :5-8) and validation (:11-12). The reference hand-times every
phase around its MPI calls (SURVEY.md §5.1); here whole-round wall-clock is
measured around the jitted round call (phases inside one XLA program are
fused — per-phase attribution comes from the profiler, utils/tracing.py),
and communication *volume* is accounted exactly via the payload bytes the
engine reports.
"""
from __future__ import annotations

import time
from typing import Dict


class AverageMeter:
    """Computes and stores the average and current value
    (meter.py:30-48)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0.0
        self.max = -float("inf")
        self.min = float("inf")

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count
        self.max = max(self.max, val)
        self.min = min(self.min, val)


TRAIN_TRACKER_KEYS = ("computing_time", "global_time", "load_time",
                      "sync_time", "losses", "top1", "top5")
VAL_TRACKER_KEYS = ("losses", "top1", "top5")


def define_local_training_tracker() -> Dict[str, AverageMeter]:
    """meter.py:5-8."""
    return {k: AverageMeter() for k in TRAIN_TRACKER_KEYS}


def define_val_tracker() -> Dict[str, AverageMeter]:
    """meter.py:11-12."""
    return {k: AverageMeter() for k in VAL_TRACKER_KEYS}


class PhaseTimer:
    """Wall-clock phase accounting: round compute, eval, checkpoint IO,
    and the per-round comm-time/volume ledger (the reference accumulates
    args.comm_time per round, init_config.py:20, printed at
    federated/main.py:208)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.comm_time = [0.0]
        self.comm_bytes = [0.0]
        self._start = {}

    def start(self, phase: str):
        self._start[phase] = time.time()

    def stop(self, phase: str) -> float:
        dt = time.time() - self._start.pop(phase)
        self.totals[phase] = self.totals.get(phase, 0.0) + dt
        return dt

    def new_round(self):
        self.comm_time.append(0.0)
        self.comm_bytes.append(0.0)

    def add_comm(self, seconds: float = 0.0, num_bytes: float = 0.0):
        self.comm_time[-1] += seconds
        self.comm_bytes[-1] += num_bytes

    def summary(self) -> Dict[str, float]:
        out = dict(self.totals)
        out["comm_time_total"] = sum(self.comm_time)
        out["comm_bytes_total"] = sum(self.comm_bytes)
        return out
