"""Profiling / tracing — and the recompilation sentinel.

The reference only hand-times phases (SURVEY.md §5.1); the TPU build adds
real profiler traces: ``jax.profiler`` emits a TensorBoard-compatible
trace of the XLA execution (HLO ops, fusion, collective time on ICI),
which is the per-phase attribution the hand timers cannot see inside one
compiled round.

The **recompilation sentinel** is the runtime half of the tracing-hazard
gate (static half: ``fedtorch_tpu.lint``, docs/static_analysis.md).
Hot callables are registered with :func:`instrument_trace` before they
are handed to ``jax.jit``; tracing executes the wrapped Python body, so
each body execution == one trace event, while steady-state compiled
calls never re-enter Python.  :class:`RecompilationSentinel` scopes the
counting: the tier-1 test asserts the FedAvg/SCAFFOLD round programs
trace exactly once across many rounds and fault schedules — the
"static config => unchanged traced program" contract PR 1's chaos
machinery depends on.
"""
from __future__ import annotations

import contextlib
import functools
from collections import Counter
from typing import Callable, Dict, List, Optional

import jax

# process-lifetime trace counts per instrumented callable name; the
# sentinel snapshots deltas of this via its own scoped counter
_TRACE_COUNTS: Counter = Counter()
_ACTIVE_SENTINELS: List["RecompilationSentinel"] = []


def instrument_trace(name: str, fn: Optional[Callable] = None):
    """Wrap ``fn`` so each execution of its PYTHON body is counted as a
    trace event under ``name``.  Apply to the function handed to
    ``jax.jit`` (inside the jit boundary the body only runs while
    tracing); also usable as ``@instrument_trace("name")``.

    Counts are trace events, not compiles: with the persistent
    compilation cache warm, a retrace still re-executes the body (and
    still costs trace+lowering time) even though XLA compilation is
    skipped — which is exactly what the sentinel must see.
    """
    def deco(f: Callable) -> Callable:
        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            record_trace_event(name)
            return f(*args, **kwargs)
        wrapped.__fedtorch_trace_name__ = name
        return wrapped
    return deco if fn is None else deco(fn)


def record_trace_event(name: str) -> None:
    _TRACE_COUNTS[name] += 1
    for s in _ACTIVE_SENTINELS:
        s.counts[name] += 1


def trace_counts() -> Dict[str, int]:
    """Process-lifetime trace counts (name -> events)."""
    return dict(_TRACE_COUNTS)


class RecompilationSentinel:
    """Scoped trace-event counter.

    ::

        with RecompilationSentinel() as s:
            for _ in range(rounds):
                server, clients, m = trainer.run_round(server, clients)
        s.assert_traces("federated.round[fedavg]", expected=1)

    Any count above ``expected`` means something retraced the round
    program mid-run — a shape/dtype/static-arg change the static
    analyzer (fedtorch_tpu.lint) exists to catch before it ships.
    """

    def __init__(self):
        self.counts: Counter = Counter()

    def __enter__(self) -> "RecompilationSentinel":
        self.counts = Counter()
        _ACTIVE_SENTINELS.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _ACTIVE_SENTINELS.remove(self)

    def count(self, name: str) -> int:
        return self.counts[name]

    def assert_traces(self, name: str, expected: int = 1) -> None:
        got = self.counts[name]
        if got != expected:
            raise AssertionError(
                f"'{name}' traced {got}x, expected {expected}x — "
                f"a retrace crept into the hot path. All counts: "
                f"{dict(self.counts) or '{}'}")


@contextlib.contextmanager
def trace(log_dir: str):
    """Context manager: profile everything inside to ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def fetch_sync(out):
    """Force real completion of ``out`` (any pytree) via a 1-element
    device->host fetch of its first leaf; returns the fetched value.

    THE canonical drain for timing/tracing boundaries:
    ``jax.block_until_ready`` can no-op on the relay backend
    (round-5 timing-methodology finding, BASELINE_REPRO.md), while
    materializing result bytes on the host provably waits for the
    in-order device stream. ``scripts/bench_timing.py`` re-exports
    this for the measurement scripts — one implementation, so the
    rule cannot drift between the bench timers and the trace hook."""
    import numpy as np

    leaf = jax.tree_util.tree_leaves(out)[0]
    # lint: disable=FTL001 — this 1-element fetch IS the sync
    return np.asarray(leaf[(0,) * getattr(leaf, "ndim", 0)])


def capture_round_trace(log_dir: str, fn: Callable, *args):
    """Run ``fn(*args)`` under a ``jax.profiler`` trace written to
    ``log_dir`` and return its result — the canonical on-chip capture
    hook for the round program (scripts/mfu_sweep.py, the MFU_PROFILE
    arm of scripts/tpu_capture.sh).

    The result is drained INSIDE the trace window by :func:`fetch_sync`
    (block_until_ready can no-op on the relay backend): a trace
    stopped before the device stream finishes records dispatch, not
    execution — the exact failure mode that left round 5 with zero
    on-chip traces.

    The written ``log_dir`` is a capture dir in the sense of
    ``fedtorch_tpu.tools.trace_attrib`` / ``fedtorch-tpu report
    --device``: the device-time category attribution runs directly on
    it (docs/observability.md "Device-side")."""
    import os

    from fedtorch_tpu import telemetry

    os.makedirs(log_dir, exist_ok=True)
    # correlated host-span marker: the profiler window shows up on the
    # telemetry timeline (trace.json) with the capture dir in its args,
    # so an operator can line the XLA trace up against the host spans
    with telemetry.span("profiler.capture", log_dir=log_dir):
        jax.profiler.start_trace(log_dir)
        try:
            out = fn(*args)
            fetch_sync(out)
        finally:
            jax.profiler.stop_trace()
    return out


def annotate(name: str):
    """Named sub-span inside a trace (shows up on the TB timeline)."""
    return jax.profiler.TraceAnnotation(name)


def device_memory_stats() -> dict:
    """Per-device live-memory summary (HBM pressure check)."""
    stats = {}
    for d in jax.devices():
        try:
            s = d.memory_stats()
            if s:
                stats[str(d)] = {
                    "bytes_in_use": s.get("bytes_in_use"),
                    "peak_bytes_in_use": s.get("peak_bytes_in_use"),
                    "bytes_limit": s.get("bytes_limit"),
                }
        except Exception:
            pass
    return stats


def live_buffer_summary() -> dict:
    """Live ``jax.Array`` accounting: total ADDRESSABLE bytes (each
    replicated copy counted — the buffers a device actually holds) and
    a per-(shape, dtype) breakdown.

    ``device_memory_stats`` is allocator-dependent and returns nothing
    on the CPU backend, so the streaming-residency contract ("the
    device holds the double-buffered feed, not the client store" —
    tests/test_streaming.py, scripts/stream_bench.py) is asserted
    against THIS view, which works on every platform: what the program
    still holds references to, shape by shape."""
    by_shape: Dict[str, int] = {}
    total = 0
    for a in jax.live_arrays():
        try:
            n = sum(int(s.data.nbytes) for s in a.addressable_shards)
        except Exception:
            try:
                n = int(a.size) * a.dtype.itemsize
            except Exception:
                continue
        key = f"{tuple(a.shape)}:{a.dtype}"
        by_shape[key] = by_shape.get(key, 0) + n
        total += n
    return {"total_bytes": total, "by_shape": by_shape}
