"""Profiling / tracing.

The reference only hand-times phases (SURVEY.md §5.1); the TPU build adds
real profiler traces: ``jax.profiler`` emits a TensorBoard-compatible
trace of the XLA execution (HLO ops, fusion, collective time on ICI),
which is the per-phase attribution the hand timers cannot see inside one
compiled round.
"""
from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Context manager: profile everything inside to ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named sub-span inside a trace (shows up on the TB timeline)."""
    return jax.profiler.TraceAnnotation(name)


def device_memory_stats() -> dict:
    """Per-device live-memory summary (HBM pressure check)."""
    stats = {}
    for d in jax.devices():
        try:
            s = d.memory_stats()
            if s:
                stats[str(d)] = {
                    "bytes_in_use": s.get("bytes_in_use"),
                    "peak_bytes_in_use": s.get("peak_bytes_in_use"),
                    "bytes_limit": s.get("bytes_limit"),
                }
        except Exception:
            pass
    return stats
