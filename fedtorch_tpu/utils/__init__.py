from fedtorch_tpu.utils.checkpoint import (  # noqa: F401
    AsyncCheckpointer, get_checkpoint_folder_name, init_checkpoint_dir,
    maybe_resume, save_checkpoint,
)
from fedtorch_tpu.utils.diagnostics import (  # noqa: F401
    aggregation_tracking, check_finite, model_norms,
)
from fedtorch_tpu.utils.logging import RunLogger  # noqa: F401
from fedtorch_tpu.utils.meters import (  # noqa: F401
    AverageMeter, PhaseTimer, define_local_training_tracker,
    define_val_tracker,
)
from fedtorch_tpu.utils.compile_cache import (  # noqa: F401
    enable_compile_cache, jit_cache_size,
)
from fedtorch_tpu.utils.lock_sentinel import (  # noqa: F401
    LockOrderSentinel, active_sentinel,
)
from fedtorch_tpu.utils.platform import honor_platform_env  # noqa: F401
from fedtorch_tpu.utils.tracing import (  # noqa: F401
    RecompilationSentinel, capture_round_trace, instrument_trace,
    trace_counts,
)
