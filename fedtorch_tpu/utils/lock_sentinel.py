"""Runtime lock-order sentinel — the dynamic half of the FTH audit.

The static concurrency pass (``fedtorch_tpu/lint/concurrency_audit.py``)
proves properties about lock-acquisition *syntax*; this module checks
the orders a live run actually takes. Modeled on the
``RecompilationSentinel`` from PR 2: a scoped context manager that is
inert in production and armed in tests and the host-chaos drill.

While armed, the sentinel installs the ``telemetry.faults.new_lock``
factory hook, so every host-plane mutex created inside its scope
(``JsonlWriter._mutex``/``_open_lock``/``_io_lock``, the fault
injector's and recovery recorder's ``_lock``) comes back wrapped in an
:class:`_InstrumentedLock` that records, per thread, the stack of locks
held at each acquisition:

* **Re-entrant acquire** of a non-reentrant lock by the thread already
  holding it raises ``AssertionError`` *immediately* — turning the
  PR 10 class of self-deadlock (injector first-fire announce re-entering
  the events writer from inside its own flush) into a test failure
  instead of a hang.
* **Order inversion** — acquiring ``B`` while holding ``A`` after some
  thread acquired ``A`` while holding ``B`` — is recorded as a
  violation and raised at scope exit (``strict=True``, the default) or
  via :meth:`assert_clean`. Recording rather than raising keeps the
  first offending thread alive long enough to capture both sites.

Locks created *before* the sentinel armed can be adopted with
:meth:`watch`, which swaps the attribute for a wrapper and restores the
original on exit. Wrappers that outlive their sentinel degrade to plain
pass-through delegation.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from fedtorch_tpu.telemetry import faults as _tel_faults

__all__ = ["LockOrderSentinel", "active_sentinel"]

_RLOCK_TYPE = type(threading.RLock())

# Sentinels currently armed, newest last (mirrors tracing._ACTIVE_SENTINELS).
_ACTIVE_SENTINELS: List["LockOrderSentinel"] = []


def active_sentinel() -> Optional["LockOrderSentinel"]:
    """The innermost armed sentinel, or None."""
    return _ACTIVE_SENTINELS[-1] if _ACTIVE_SENTINELS else None


class _InstrumentedLock:
    """Duck-typed ``threading.Lock`` that reports acquisitions to its
    sentinel. Once the sentinel disarms, every method is a plain
    delegation to the wrapped lock."""

    def __init__(self, inner, name: str, sentinel: "LockOrderSentinel",
                 reentrant: bool = False) -> None:
        self._inner = inner
        self.name = name
        self._sentinel = sentinel
        self._reentrant = reentrant

    def _armed(self) -> bool:
        return self._sentinel is not None and self._sentinel.armed

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._armed():
            self._sentinel._before_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got and self._armed():
            self._sentinel._after_acquire(self)
        return got

    def release(self) -> None:
        if self._armed():
            self._sentinel._on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<_InstrumentedLock {self.name!r} inner={self._inner!r}>"


class LockOrderSentinel:
    """Scoped recorder of per-thread lock acquisition order.

    Usage (tests / host-chaos drill)::

        with LockOrderSentinel() as locks:
            run_experiment(cfg)          # locks created inside are wrapped
        # strict=True: __exit__ raised if any inversion was observed
        locks.assert_clean()             # idempotent, explicit form

    ``watch(obj, "attr", ...)`` adopts pre-existing lock attributes for
    the duration of the scope.
    """

    def __init__(self, strict: bool = True) -> None:
        self.strict = strict
        self.armed = False
        self.violations: List[str] = []
        # Directed acquired-after graph on lock *names*:
        # _edges[a][b] = description of the first site acquiring b while
        # holding a.
        self._edges: Dict[str, Dict[str, str]] = {}
        self._tls = threading.local()
        self._graph_mu = threading.Lock()
        self._watched: List[Tuple[object, str, object]] = []
        self._prev_hook = None

    # -- arming ---------------------------------------------------------

    def __enter__(self) -> "LockOrderSentinel":
        self.armed = True
        _ACTIVE_SENTINELS.append(self)
        self._prev_hook = _tel_faults.set_lock_hook(self._make_lock)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tel_faults.set_lock_hook(self._prev_hook)
        for obj, attr, original in reversed(self._watched):
            setattr(obj, attr, original)
        self._watched.clear()
        if self in _ACTIVE_SENTINELS:
            _ACTIVE_SENTINELS.remove(self)
        self.armed = False
        if exc_type is None and self.strict:
            self.assert_clean()
        return False

    def _make_lock(self, name: str):
        return _InstrumentedLock(threading.Lock(), name, self)

    def watch(self, obj, *attrs: str, name: Optional[str] = None
              ) -> "LockOrderSentinel":
        """Wrap existing lock attributes of ``obj`` (restored on exit)."""
        base = name or type(obj).__name__
        for attr in attrs:
            original = getattr(obj, attr)
            if isinstance(original, _InstrumentedLock):
                continue
            wrapper = _InstrumentedLock(
                original, f"{base}.{attr}", self,
                reentrant=isinstance(original, _RLOCK_TYPE))
            self._watched.append((obj, attr, original))
            setattr(obj, attr, wrapper)
        return self

    # -- recording ------------------------------------------------------

    def _held(self) -> List[_InstrumentedLock]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def _before_acquire(self, lock: _InstrumentedLock) -> None:
        if lock._reentrant:
            return
        for h in self._held():
            if h is lock:
                msg = (f"re-entrant acquire of {lock.name!r} on thread "
                       f"{threading.current_thread().name!r} while already "
                       f"holding it (held: {[x.name for x in self._held()]})"
                       " — this is the PR 10 self-deadlock shape")
                self.violations.append(msg)
                # Raise NOW: letting the acquire proceed would hang the
                # process, which is exactly what this sentinel exists to
                # turn into a test failure.
                raise AssertionError("LockOrderSentinel: " + msg)

    def _after_acquire(self, lock: _InstrumentedLock) -> None:
        held = self._held()
        tname = threading.current_thread().name
        with self._graph_mu:
            for h in held:
                if h.name == lock.name:
                    continue
                site = f"thread {tname!r}: {h.name} -> {lock.name}"
                self._edges.setdefault(h.name, {}).setdefault(lock.name, site)
                if self._reaches(lock.name, h.name):
                    back = self._edges.get(lock.name, {}).get(h.name)
                    self.violations.append(
                        f"lock-order inversion: {site} but earlier "
                        f"{back or f'{lock.name} ..-> {h.name}'}")
        held.append(lock)

    def _on_release(self, lock: _InstrumentedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def _reaches(self, a: str, b: str) -> bool:
        """Path a ..-> b in the acquired-after graph (caller holds _graph_mu)."""
        seen = set()
        stack = [a]
        while stack:
            node = stack.pop()
            if node == b:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self._edges.get(node, ()))
        return False

    # -- reporting ------------------------------------------------------

    def order_edges(self) -> Dict[str, List[str]]:
        """Observed acquired-after pairs: {held: [acquired, ...]}."""
        with self._graph_mu:
            return {a: sorted(bs) for a, bs in sorted(self._edges.items())}

    def assert_clean(self) -> None:
        """Raise if any inversion or re-entrant acquire was observed."""
        if self.violations:
            raise AssertionError(
                "LockOrderSentinel observed %d violation(s):\n  %s"
                % (len(self.violations), "\n  ".join(self.violations)))
