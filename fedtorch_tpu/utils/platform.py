"""Backend-selection helper shared by entry points, scripts, examples.

Some environments install a site hook that pins ``jax_platforms`` to a
TPU proxy at interpreter start, which silently overrides the standard
``JAX_PLATFORMS=cpu`` escape hatch — a CPU-only run then blocks on TPU
backend bring-up. ``honor_platform_env`` re-asserts the user's explicit
environment choice through ``jax.config`` (a no-op everywhere else).
"""
from __future__ import annotations

import os


def honor_platform_env() -> None:
    """If JAX_PLATFORMS is explicitly set, make jax.config agree with it
    even when a site hook pre-set a different platform. Call before the
    first backend touch (``jax.devices``/first dispatch)."""
    want = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backend already initialized or option unknown: keep going
