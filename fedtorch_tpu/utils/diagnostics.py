"""Training sanity checks.

Covers the role of ``logs/check_training.py`` (the reference's substitute
for tests, SURVEY.md §4.2) with deliberately adjusted quantities for the
one-program-per-round design:

* ``model_norms`` — weight-norm reporting at sync (the reference's
  check_model_at_sync, check_training.py:22-37, also prints per-batch
  gradient norms; per-batch gradients live inside the jitted scan here,
  so the norm check applies to the aggregated model).
* ``aggregation_tracking`` — cosine/distance between the PRE- and
  POST-aggregation server models. The reference's
  track_model_aggregation (check_training.py:43-76) instead tracks
  gradient-direction cosine and distance from the *initial* model; the
  pre/post form answers the same "is aggregation doing something sane"
  question per round without holding the initial model forever.
"""
from __future__ import annotations

import os
from typing import Dict

import jax
import jax.numpy as jnp


# lint: disable=FTL004 — callers keep using the params they pass in
@jax.jit
def model_norms(params) -> Dict[str, jnp.ndarray]:
    """Global l2 norm + per-leaf max abs (check_training.py:22-37) +
    a jit-safe ``all_finite`` flag (the divergence signal the round
    supervisor polls — one fused device program, no per-leaf host
    round-trips). An empty pytree is trivially finite with zero norm
    (a structural no-params edge case, not an error)."""
    leaves = jax.tree.leaves(params)
    # lint: disable=FTL005 — leaves is a Python list; emptiness is static
    if not leaves:
        return {"l2": jnp.zeros(()), "max_abs": jnp.zeros(()),
                "all_finite": jnp.asarray(True)}
    sq = sum(jnp.sum(jnp.square(x)) for x in leaves)
    mx = jnp.max(jnp.stack([jnp.max(jnp.abs(x)) for x in leaves]))
    finite = jnp.stack(
        [jnp.all(jnp.isfinite(x)) for x in leaves]).all()
    return {"l2": jnp.sqrt(sq), "max_abs": mx, "all_finite": finite}


# lint: disable=FTL004 — callers keep using both param trees
@jax.jit
def aggregation_tracking(old_params, new_params) -> Dict[str, jnp.ndarray]:
    """Cosine similarity and l2 distance between the model before and
    after aggregation (check_training.py:43-76)."""
    flat_old = jnp.concatenate(
        [x.ravel() for x in jax.tree.leaves(old_params)])
    flat_new = jnp.concatenate(
        [x.ravel() for x in jax.tree.leaves(new_params)])
    denom = jnp.maximum(
        jnp.linalg.norm(flat_old) * jnp.linalg.norm(flat_new), 1e-12)
    return {
        "cosine": jnp.vdot(flat_old, flat_new) / denom,
        "distance": jnp.linalg.norm(flat_new - flat_old),
        "rel_change": jnp.linalg.norm(flat_new - flat_old)
        / jnp.maximum(jnp.linalg.norm(flat_old), 1e-12),
    }


def check_finite(params) -> bool:
    """Divergence guard: all leaves finite (the implicit check the
    reference's norm prints served). Host-side convenience wrapper over
    :func:`model_norms`' fused device check."""
    return bool(model_norms(params)["all_finite"])


def runtime_snapshot() -> Dict[str, object]:
    """Host-side process state for stall post-mortems — what the
    watchdog dumps when no round completes (robustness/watchdog.py).

    Deliberately touches NO device state: on a wedged pod any device
    interaction (even a norm check) would block behind the stuck
    collective, so this reads only interpreter/OS facts. Every probe
    is individually guarded — a half-dead runtime must still produce
    a partial report."""
    import threading

    snap: Dict[str, object] = {"pid": os.getpid()}
    try:
        snap["threads"] = sorted(t.name for t in threading.enumerate())
    except Exception:
        pass
    try:
        import resource
        snap["max_rss_kb"] = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss
    except Exception:
        pass
    try:
        # already-initialized backend facts only: jax.devices() is
        # cached after bring-up and process_index is a local field —
        # neither dispatches device work
        snap["process"] = f"{jax.process_index()}/{jax.process_count()}"
        snap["local_devices"] = jax.local_device_count()
    except Exception:
        pass
    return snap
